"""The single operator registry.

The reference has *two* op worlds — legacy ``OperatorProperty`` layer ops and
NNVM ``FCompute`` tensor ops (reference: include/mxnet/operator.h:34-546,
include/mxnet/op_attr_types.h:33-63) — dual-compiled for cpu/gpu against
mshadow templates. Here there is exactly ONE registry: every op is a pure JAX
function plus declarative metadata. XLA replaces mshadow (kernel codegen,
fusion, memory planning) and the same definition serves:

  * imperative NDArray calls (``mx.nd.Convolution(...)``) — the JAX fn runs
    eagerly (async dispatch gives the engine-like pipelining for free);
  * symbolic Symbol nodes (``mx.sym.Convolution(...)``) — the executor traces
    the same fn under ``jax.jit`` so the whole graph compiles to one XLA
    program (the analog of the reference's bulk-exec segments,
    graph_executor.cc:678-756);
  * gradient construction — ``jax.vjp`` of the composed graph replaces the
    NNVM ``Gradient`` pass + per-op ``FGradient`` registrations.

Op forward signature (the "FCompute" of this framework):

    forward(attrs, inputs, aux, is_train, rng) -> (outputs, new_aux)

where ``attrs`` is the typed param dict, ``inputs``/``aux`` are lists of
jax.Arrays, and outputs/new_aux are lists of jax.Arrays. Most ops register a
*simple* forward ``fn(attrs, *inputs) -> array|tuple`` and are wrapped.

Like the reference's ``_init_ndarray_module``/``_init_symbol_module``
(python/mxnet/ndarray.py:875, symbol.py:1585), the user-facing ``mx.nd.*`` and
``mx.sym.*`` functions are auto-generated from this registry at import time.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["OpDef", "register", "get_op", "list_ops", "OP_REGISTRY"]

OP_REGISTRY = {}


class OpDef:
    """Metadata + kernel for one operator.

    Parameters
    ----------
    name : canonical op name (the public API surface name).
    forward : full-signature forward (attrs, inputs, aux, is_train, rng).
    inputs : list of input names, or callable(attrs)->list for variadic ops
        (e.g. Concat's num_args; reference: ListArguments()).
    aux : auxiliary-state names (BatchNorm moving stats; reference:
        ListAuxiliaryStates()).
    num_outputs : int or callable(attrs)->int.
    output_names : list or callable(attrs)->list (reference: FListOutputNames).
    attr_spec : dict name -> (parser, default). Unknown kwargs are kept
        verbatim (MXNet tolerates extra attrs in JSON round-trips).
    infer_shape : optional fn(attrs, in_shapes)->(in_shapes, out_shapes,
        aux_shapes) for bidirectional inference (weight shapes deduced from
        data, reference: per-op InferShape); a third ``out_known`` parameter
        is detected at registration. When absent, shapes are derived
        by abstract evaluation (jax.eval_shape) which requires complete
        input shapes. Signatures are validated at registration time
        (malformed arity fails fast with the op name, instead of lazily
        at the first symbol.infer_shape walk).
    infer_type : optional fn(attrs, in_types)->(in_types, out_types,
        aux_types).
    shape_passthrough : declares the op shape-identity on its first input
        (all outputs take input 0's shape) without a dedicated infer fn —
        the explicit opt-out the graph verifier (analysis rule GV107)
        accepts in place of ``infer_shape``, so an op can never *silently*
        fall back to abstract evaluation that stalls on partial shapes.
    variants : alternative kernel implementations keyed by tier name
        (today: ``"pallas"``). ``forward`` is always the XLA composition
        and the fallback of last resort; the kernel-tier selection layer
        (kernel_tier.py) picks per (backend, shape, dtype) under
        ``MXNET_KERNEL_TIER``. Values are full-signature forwards or
        ``(forward, eligible)`` pairs where ``eligible(attrs, in_shapes,
        in_dtypes) -> bool`` gates shapes/attrs the kernel supports.
    flops / bytes_moved : optional cost metadata,
        ``fn(attrs, in_shapes) -> float`` — forward-pass floating-point
        ops and HBM bytes touched for one execution at those input
        shapes. Powers the MFU/roofline telemetry (telemetry/mfu.py);
        ops without it are invisible to MFU accounting (analysis rule
        MF601 lists them).
    need_rng : forward consumes the rng key (Dropout, samplers).
    is_loss : op is a loss head (SoftmaxOutput family) — executor seeds its
        cotangent with ones for backward() with no out_grads.
    mutate_inputs : names of inputs the op writes (optimizer update ops;
        reference: FMutateInputs). Imperative invoke swaps the new buffer
        into the corresponding NDArray handle.
    stateful_infer : the op's aux states are read-AND-written during
        inference forwards too (the KV-cache decode contract) — the
        executor writes ``new_aux`` back even when ``is_train=False``.
        Training aux (BatchNorm moving stats) keeps the train-only rule.
    aux_dtypes : dict aux name -> dtype for aux states that must NOT
        bind as the default float32 cell (a KV cache's int32 position
        cursor). ``symbol._create`` stamps the declaration onto the
        auto-created aux variable (``__dtype__``), and the executor
        binds a cell of that dtype — which also exempts integer aux
        from the mixed-precision entry cast.
    """

    def __init__(self, name, forward, inputs=("data",), aux=(),
                 num_outputs=1, output_names=None, attr_spec=None,
                 infer_shape=None, infer_type=None, need_rng=False,
                 is_loss=False, mutate_inputs=(), num_visible=None,
                 shape_passthrough=False, variants=None, flops=None,
                 bytes_moved=None, stateful_infer=False, aux_dtypes=None,
                 doc=""):
        self.name = name
        self.forward = forward
        self.variants = {}
        for vname, vfn in (variants or {}).items():
            if isinstance(vfn, tuple):
                self.add_variant(vname, vfn[0], eligible=vfn[1],
                                 kernel_spec=vfn[2]
                                 if len(vfn) > 2 else None)
            else:
                self.add_variant(vname, vfn)
        self.flops = flops
        self.bytes_moved = bytes_moved
        self._inputs = inputs
        self._aux = aux
        self._num_outputs = num_outputs
        self._num_visible = num_visible
        self._output_names = output_names
        self.attr_spec = attr_spec or {}
        self.infer_shape = infer_shape
        self.infer_type = infer_type
        self.need_rng = need_rng
        self.is_loss = is_loss
        self.mutate_inputs = tuple(mutate_inputs)
        self.stateful_infer = bool(stateful_infer)
        self.aux_dtypes = dict(aux_dtypes or {})
        self.shape_passthrough = bool(shape_passthrough)
        self.doc = doc
        # arity check up front (it used to happen lazily at the first
        # symbol shape walk): a malformed infer fn names its op here
        # instead of failing as a confusing TypeError mid-inference
        self._infer_accepts_out = _validate_infer_signature(
            name, "infer_shape", infer_shape)
        _validate_infer_signature(name, "infer_type", infer_type)

    # --- variadic-aware accessors ---------------------------------------
    def input_names(self, attrs=None):
        if callable(self._inputs):
            return list(self._inputs(attrs or {}))
        return list(self._inputs)

    def aux_names(self, attrs=None):
        if callable(self._aux):
            return list(self._aux(attrs or {}))
        return list(self._aux)

    def num_outputs(self, attrs=None):
        if callable(self._num_outputs):
            return self._num_outputs(attrs or {})
        return self._num_outputs

    def num_visible_outputs(self, attrs=None):
        """Outputs exposed to composition (reference: NNVM
        num_visible_outputs — BatchNorm hides mean/var, Dropout its mask)."""
        if self._num_visible is None:
            return self.num_outputs(attrs)
        if callable(self._num_visible):
            return self._num_visible(attrs or {})
        return self._num_visible

    def output_names(self, attrs=None):
        if self._output_names is None:
            n = self.num_outputs(attrs)
            return ["output"] if n == 1 else [f"output{i}" for i in range(n)]
        if callable(self._output_names):
            return list(self._output_names(attrs or {}))
        return list(self._output_names)

    # --- kernel-tier variants + cost metadata ---------------------------
    def add_variant(self, name, forward, eligible=None,
                    kernel_spec=None):
        """Attach an alternative kernel implementation.

        ``forward`` has the full op signature (attrs, inputs, aux,
        is_train, rng) -> (outputs, new_aux); ``eligible(attrs,
        in_shapes, in_dtypes)`` optionally restricts the shapes/attrs
        the kernel handles. ``name="xla"`` is reserved for the stock
        ``self.forward`` composition and cannot be overridden.

        ``kernel_spec`` declares a Pallas kernel's worst-case VMEM
        tiles and numerics-gate dtype coverage
        (``{"tiles": [((rows, cols), dtype), ...], "dtypes": (...)}``);
        it is validated HERE, at registration — an infeasible kernel
        (VMEM-overflowing tile, lane/sublane misalignment, uncoverable
        dtypes) raises MXNetError with its PK9xx rule id at import
        instead of being silently never-selected by the autotuner
        (analysis/kernelcheck.py).
        """
        if name == "xla":
            raise MXNetError(
                f"op {self.name!r}: 'xla' names the stock forward; "
                "register a differently-named variant")
        if kernel_spec is not None:
            from ..analysis.kernelcheck import validate_kernel_spec
            validate_kernel_spec(self.name, name, kernel_spec)
        self.variants[name] = {"fn": forward, "eligible": eligible,
                               "kernel_spec": kernel_spec}
        return self

    def variant_fn(self, name):
        """Forward callable for one tier: 'xla' -> the stock forward."""
        if name == "xla":
            return self.forward
        return self.variants[name]["fn"]

    def variant_eligible(self, name, attrs, in_shapes, in_dtypes):
        if name == "xla":
            return True
        rec = self.variants.get(name)
        if rec is None:
            return False
        if rec["eligible"] is None:
            return True
        try:
            return bool(rec["eligible"](attrs, in_shapes, in_dtypes))
        except Exception:
            return False

    def set_cost(self, flops=None, bytes_moved=None):
        """Attach/replace cost metadata (fn(attrs, in_shapes)->float)."""
        if flops is not None:
            self.flops = flops
        if bytes_moved is not None:
            self.bytes_moved = bytes_moved
        return self

    def has_cost(self):
        return self.flops is not None and self.bytes_moved is not None

    def cost(self, attrs, in_shapes):
        """(flops, bytes) for one forward execution, or None when the op
        has no metadata or the estimate fails (partial shapes)."""
        if not self.has_cost():
            return None
        try:
            return (float(self.flops(attrs, in_shapes)),
                    float(self.bytes_moved(attrs, in_shapes)))
        except Exception:
            return None

    def normalize_attrs(self, kwargs):
        """Parse raw kwargs/JSON strings into the typed attr dict."""
        attrs = {}
        for key, val in kwargs.items():
            if val is None:
                continue
            spec = self.attr_spec.get(key)
            if spec is not None:
                parser = spec[0]
                attrs[key] = parser(val) if parser else val
            else:
                attrs[key] = val
        for key, spec in self.attr_spec.items():
            if key not in attrs and len(spec) > 1 and spec[1] is not None:
                attrs[key] = spec[1]
        return attrs

    def __repr__(self):
        return f"OpDef({self.name})"


def _validate_infer_signature(op_name, what, fn):
    """Registration-time arity check for infer_shape/infer_type.

    Returns whether the fn accepts the optional third ``out_known``
    argument (bidirectional inference), the property symbol.py used to
    probe lazily per call. Raises MXNetError naming the op when the fn
    cannot even accept the mandatory ``(attrs, in_shapes)`` pair.
    """
    if fn is None:
        return False
    if not callable(fn):
        raise MXNetError(
            f"op {op_name!r}: {what} must be callable, got "
            f"{type(fn).__name__}")
    import inspect
    try:
        sig = inspect.signature(fn)
    except (ValueError, TypeError):
        return False          # builtins/partials: cannot introspect
    required = 0
    max_positional = 0
    has_varargs = False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            max_positional += 1
            if p.default is p.empty:
                required += 1
        elif p.kind == p.VAR_POSITIONAL:
            has_varargs = True
        elif p.kind == p.KEYWORD_ONLY and p.default is p.empty:
            raise MXNetError(
                f"op {op_name!r}: {what} has a required keyword-only "
                f"parameter {p.name!r}; inference calls it positionally "
                "as (attrs, in_shapes[, out_known])")
    if not has_varargs and (max_positional < 2 or required > 3):
        raise MXNetError(
            f"op {op_name!r}: {what} must accept (attrs, in_shapes"
            f"[, out_known]), got signature {sig}")
    return has_varargs or max_positional >= 3


def _wrap_simple(fn):
    """Lift fn(attrs, *inputs) -> array|tuple into the full signature."""
    def forward(attrs, inputs, aux, is_train, rng):
        out = fn(attrs, *inputs)
        if isinstance(out, (tuple, list)):
            return list(out), []
        return [out], []
    return forward


def register(name, inputs=("data",), simple=None, full=None, **kw):
    """Register an op. Use as a decorator or direct call.

    ``simple=fn`` registers fn(attrs, *inputs); ``full=fn`` registers the
    5-arg signature. As a decorator, wraps a simple fn unless
    ``full_signature=True`` is passed.
    """
    full_signature = kw.pop("full_signature", False)

    def do_register(fn, is_full):
        forward = fn if is_full else _wrap_simple(fn)
        opdef = OpDef(name, forward, inputs=inputs, **kw)
        if name in OP_REGISTRY:
            raise MXNetError(f"op {name!r} registered twice")
        OP_REGISTRY[name] = opdef
        return fn

    if simple is not None:
        do_register(simple, False)
        return OP_REGISTRY[name]
    if full is not None:
        do_register(full, True)
        return OP_REGISTRY[name]

    def decorator(fn):
        do_register(fn, full_signature)
        return fn

    return decorator


def alias(new_name, existing):
    """Register an alternative public name for an existing op."""
    opdef = get_op(existing)
    if new_name not in OP_REGISTRY:
        OP_REGISTRY[new_name] = opdef
    return opdef


def get_op(name):
    try:
        return OP_REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    return sorted(OP_REGISTRY)
