"""Fused optimizer update ops.

The reference registers weight updates as graph ops (reference:
src/operator/optimizer_op.cc:17-60, optimizer_op-inl.h) so a whole update is
one fused kernel; python Optimizer classes call them as ``mx.nd.sgd_update``
etc. Here each update is a single jitted JAX function (XLA fuses the whole
elementwise chain into one HBM pass — the same reason the reference fused
them) marked ``mutate_inputs`` so imperative invoke swaps the new buffers
into the weight/state NDArray handles in place.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import parse_float, parse_bool
from .registry import register

_COMMON = {
    "lr": (parse_float, None), "wd": (parse_float, 0.0),
    "rescale_grad": (parse_float, 1.0), "clip_gradient": (parse_float, -1.0),
}


def _prep_grad(grad, weight, attrs):
    grad = grad * attrs.get("rescale_grad", 1.0)
    clip = attrs.get("clip_gradient", -1.0)
    if clip is not None and clip > 0:
        grad = jnp.clip(grad, -clip, clip)
    return grad + attrs.get("wd", 0.0) * weight


@register("sgd_update", inputs=("weight", "grad"), attr_spec=dict(_COMMON),
          mutate_inputs=("weight",))
def _sgd_update(attrs, weight, grad):
    g = _prep_grad(grad, weight, attrs)
    return weight - attrs["lr"] * g


@register("sgd_mom_update", inputs=("weight", "grad", "mom"),
          attr_spec={**_COMMON, "momentum": (parse_float, 0.0)},
          mutate_inputs=("weight", "mom"), num_outputs=2,
          output_names=["weight", "mom"])
def _sgd_mom_update(attrs, weight, grad, mom):
    g = _prep_grad(grad, weight, attrs)
    new_mom = attrs.get("momentum", 0.0) * mom - attrs["lr"] * g
    return weight + new_mom, new_mom


@register("adam_update", inputs=("weight", "grad", "mean", "var"),
          attr_spec={**_COMMON, "beta1": (parse_float, 0.9),
                     "beta2": (parse_float, 0.999),
                     "epsilon": (parse_float, 1e-8)},
          mutate_inputs=("weight", "mean", "var"), num_outputs=3,
          output_names=["weight", "mean", "var"])
def _adam_update(attrs, weight, grad, mean, var):
    g = _prep_grad(grad, weight, attrs)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    new_mean = b1 * mean + (1 - b1) * g
    new_var = b2 * var + (1 - b2) * jnp.square(g)
    new_w = weight - attrs["lr"] * new_mean / \
        (jnp.sqrt(new_var) + attrs.get("epsilon", 1e-8))
    return new_w, new_mean, new_var


@register("rmsprop_update", inputs=("weight", "grad", "n"),
          attr_spec={**_COMMON, "gamma1": (parse_float, 0.95),
                     "epsilon": (parse_float, 1e-8),
                     "clip_weights": (parse_float, -1.0)},
          mutate_inputs=("weight", "n"), num_outputs=2,
          output_names=["weight", "n"])
def _rmsprop_update(attrs, weight, grad, n):
    g = _prep_grad(grad, weight, attrs)
    g1 = attrs.get("gamma1", 0.95)
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_w = weight - attrs["lr"] * g / \
        jnp.sqrt(new_n + attrs.get("epsilon", 1e-8))
    cw = attrs.get("clip_weights", -1.0)
    if cw is not None and cw > 0:
        new_w = jnp.clip(new_w, -cw, cw)
    return new_w, new_n


@register("rmspropalex_update", inputs=("weight", "grad", "n", "g", "delta"),
          attr_spec={**_COMMON, "gamma1": (parse_float, 0.95),
                     "gamma2": (parse_float, 0.9),
                     "epsilon": (parse_float, 1e-8),
                     "clip_weights": (parse_float, -1.0)},
          mutate_inputs=("weight", "n", "g", "delta"), num_outputs=4,
          output_names=["weight", "n", "g", "delta"])
def _rmspropalex_update(attrs, weight, grad, n, g_state, delta):
    g = _prep_grad(grad, weight, attrs)
    g1, g2 = attrs.get("gamma1", 0.95), attrs.get("gamma2", 0.9)
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_g = (1 - g1) * g + g1 * g_state
    new_delta = g2 * delta - attrs["lr"] * g / \
        jnp.sqrt(new_n - jnp.square(new_g) + attrs.get("epsilon", 1e-8))
    new_w = weight + new_delta
    cw = attrs.get("clip_weights", -1.0)
    if cw is not None and cw > 0:
        new_w = jnp.clip(new_w, -cw, cw)
    return new_w, new_n, new_g, new_delta
