"""Operator library: importing this package populates the registry."""
from .registry import OP_REGISTRY, get_op, list_ops, register, alias
from . import tensor  # noqa: F401 — registers tensor ops
from . import nn  # noqa: F401 — registers layer ops
from . import loss  # noqa: F401 — registers loss heads
from . import optimizer_op  # noqa: F401 — registers fused updates
from . import rnn_op  # noqa: F401 — registers the fused RNN
from .. import operator as _custom_op  # noqa: F401 — registers Custom
from . import pallas_kernels  # noqa: F401 — Pallas kernel-tier variants
from . import quant  # noqa: F401 — int8 PTQ ops + graph rewrite
from . import cost  # noqa: F401 — seeds flops/bytes metadata (MFU)
