"""Post-training quantization tiers (weight-only, per-channel):
int8 and fp8 (``float8_e4m3fn`` storage + f32 scales).

Serving is memory-bound: the bucket-ladder programs stream every weight
matrix out of HBM per dispatch, so halving/quartering weight bytes
multiplies serving capacity without new hardware (ROADMAP 4's
"low-precision inference tier"). This module implements the
post-training-quantized (PTQ) path:

* **per-channel scale capture** — ``quantize_per_channel`` maps a float
  weight to ``int8`` values plus one f32 scale per output channel
  (symmetric, amax/127); ``export_model(quantize="int8")`` captures
  scales at export time and bakes int8 weights + in-program dequant
  into the ``.mxp`` artifact;
* **quantized ops** — ``QuantizedFullyConnected`` / ``Quantized
  Convolution``: forward is the exact XLA composition (dequantize in
  f32, then the stock matmul/conv), and each carries a ``pallas``
  variant in the kernel tier — dense fuses the dequant into the matmul
  tile pass (int8 weight tiles decoded in VMEM, never materialized in
  HBM at f32 width), conv fuses the dequant into one tiled VMEM pass
  ahead of the MXU conv. Both ride the SAME numerics gate as every
  tier kernel: a failing kernel can never be selected;
* **graph rewrite** — ``quantize_symbol`` rewrites a trained symbol's
  FullyConnected/Convolution nodes onto the quantized ops and splits
  each weight param into ``<w>_q`` (int8, declared via the var's
  ``__dtype__`` so the executor binds an int8 cell) + ``<w>_scale``
  (f32). ``serve.BucketEngine(compute_dtype="int8")`` runs this
  rewrite at registration, so the bucket ladder pins quantized rungs
  and warm restarts rebuild from the already-quantized payload.

Accuracy contract: int8 outputs sit within ``INT8_TOL`` of the float
composition (per-channel symmetric weight-only PTQ; activations stay in
the incoming float dtype). The serve gate (tests/test_quant.py) pins
``compile_count()`` delta == 0 after warmup plus the tolerance class
against the float ladder.

Quantized graphs are an **inference tier**: binding is
``for_training=False`` everywhere they are produced (export, serving).
The int8 weights carry no gradient path.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError, parse_bool, parse_int
from .registry import OP_REGISTRY, get_op, register

__all__ = ["INT8_TOL", "FP8_TOL", "FP8_MAX", "quantize_per_channel",
           "dequantize", "quantize_symbol", "quantizable_weights"]

#: tolerance class for int8-vs-float OUTPUT comparison (per-channel
#: symmetric weight-only PTQ introduces <= 1/254 relative weight error;
#: tests and the serve gate compare against the float ladder with this)
INT8_TOL = {"atol": 0.05, "rtol": 0.05}

#: tolerance class for fp8-vs-float OUTPUT comparison: e4m3's 3-bit
#: mantissa bounds per-weight relative error at 2^-4 (6.25%) after the
#: per-channel amax/448 scaling, so outputs sit a bit wider than int8's
FP8_TOL = {"atol": 0.15, "rtol": 0.15}

#: max finite magnitude of float8_e4m3fn (the fp8 serving storage type)
FP8_MAX = 448.0

#: dtype aliases quantize surfaces accept -> canonical storage dtype
_QUANT_DTYPES = {"int8": "int8",
                 "fp8": "float8_e4m3fn",
                 "float8_e4m3fn": "float8_e4m3fn"}

#: ops the rewrite lowers, old op name -> quantized op name
_QUANT_OPS = {"FullyConnected": "QuantizedFullyConnected",
              "Convolution": "QuantizedConvolution"}


# ----------------------------------------------------------- numerics
def quantize_per_channel(arr, axis=0, dtype="int8"):
    """Symmetric per-channel narrow-dtype quantization.

    Returns ``(q, scale)``: ``q`` shaped like ``arr`` in the storage
    dtype (``int8`` or ``fp8``/``float8_e4m3fn``), ``scale`` f32 shaped
    ``(arr.shape[axis],)`` with ``arr ≈ q * scale`` along ``axis``.
    int8 maps amax to 127 with round-to-nearest; fp8 maps amax to the
    e4m3 max finite (448) and lets the cast's mantissa rounding land
    the rest. All-zero channels get scale 1.0 (q is zero anyway).
    """
    storage = _QUANT_DTYPES.get(str(dtype))
    if storage is None:
        raise MXNetError(f"quantize: unsupported dtype {dtype!r} "
                         "(int8 or fp8)")
    a = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr,
                   dtype=np.float32)
    red = tuple(i for i in range(a.ndim) if i != axis)
    amax = np.max(np.abs(a), axis=red) if red else np.abs(a)
    bshape = [1] * a.ndim
    bshape[axis] = -1
    if storage == "int8":
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.round(a / scale.reshape(bshape)), -127, 127)
        return q.astype(np.int8), scale
    scale = np.where(amax > 0, amax / FP8_MAX, 1.0).astype(np.float32)
    q = np.clip(a / scale.reshape(bshape), -FP8_MAX, FP8_MAX)
    return q.astype(np.dtype("float8_e4m3fn")), scale


def dequantize(q, scale, axis=0):
    """f32 reconstruction of a per-channel quantized array."""
    bshape = [1] * q.ndim
    bshape[axis] = -1
    return q.astype(jnp.float32) * scale.reshape(bshape)


# ------------------------------------------------- quantized dense op
def _qfc_inputs(attrs):
    if parse_bool(attrs.get("no_bias", False)):
        return ["data", "weight", "scale"]
    return ["data", "weight", "scale", "bias"]


def _qfc_infer(attrs, in_shapes, out_known=None):
    num_hidden = parse_int(attrs["num_hidden"])
    no_bias = parse_bool(attrs.get("no_bias", False))
    data_s = in_shapes[0]
    w_s, out_s = None, (0, num_hidden)
    if data_s is not None:
        if all(d > 0 for d in data_s[1:]):
            w_s = (num_hidden, int(np.prod(data_s[1:], dtype=np.int64)))
        out_s = (data_s[0], num_hidden)
    new_in = [data_s, w_s, (num_hidden,)] + \
        ([] if no_bias else [(num_hidden,)])
    return new_in, [out_s], []


def _qfc_flatten(attrs, data):
    if data.ndim > 2 and parse_bool(attrs.get("flatten", True)):
        data = data.reshape((data.shape[0], -1))
    return data


def _qfc_xla(attrs, data, weight, scale, bias=None):
    """The exact composition: f32 dequant, f32 matmul, cast back —
    the reference both tiers are gated against."""
    data = _qfc_flatten(attrs, data)
    wf = dequantize(weight, scale, axis=0)
    out = jnp.dot(data.astype(jnp.float32), wf.T)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(data.dtype)


def _qfc_kernel(x_ref, w_ref, s_ref, o_ref):
    # x (bm, K) — w (bn, K) int8 decoded in VMEM: the f32-width weight
    # never exists in HBM, which is the whole win on a memory-bound rung
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32) * s_ref[...].reshape(-1, 1)
    o_ref[...] = jnp.dot(x, w.T,
                         precision=jax.lax.Precision.HIGHEST)


def _pl_qfc_matmul(x2, wq, scale):
    from .pallas_kernels import pallas_call, _divisor_block
    import jax.experimental.pallas as pl
    m, k = x2.shape
    n = wq.shape[0]
    bm = _divisor_block(m, 256)
    bn = _divisor_block(n, 256)
    return pallas_call(
        _qfc_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=(m // bm, n // bn),
        in_specs=[pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                  pl.BlockSpec((bn, k), lambda i, j: (j, 0)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)))(
            x2, wq, scale.reshape(1, n))


def _qfc_pallas_variant(attrs, inputs, aux, is_train, rng):
    data, weight, scale = inputs[:3]
    bias = inputs[3] if len(inputs) > 3 else None
    data = _qfc_flatten(attrs, data)
    out = _pl_qfc_matmul(data, weight, scale)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return [out.astype(data.dtype)], []


def _qfc_eligible(attrs, in_shapes, in_dtypes):
    data_s, w_s = in_shapes[0], in_shapes[1]
    if len(data_s) != 2 or len(w_s) != 2:
        return False
    if str(in_dtypes[1]) not in ("int8", "float8_e4m3fn"):
        return False
    if w_s[1] > 16384 or str(in_dtypes[0]) not in (
            "float32", "bfloat16", "float16"):
        return False
    # whole-K tiles must fit VMEM alongside the (bm, bn) accumulator:
    # bound the ACTUAL block working set (x f32 + w int8 + out f32),
    # mirroring _pl_qfc_matmul's block choice — the declared
    # _QFC_KSPEC is validated against the same ceiling at registration
    from .pallas_kernels import _divisor_block
    k = w_s[1]
    bm = _divisor_block(data_s[0], 256)
    bn = _divisor_block(w_s[0], 256)
    return bm * k * 4 + bn * k * 1 + bm * bn * 4 <= 12 << 20


# -------------------------------------------------- quantized conv op
def _qconv_inputs(attrs):
    if parse_bool(attrs.get("no_bias", False)):
        return ["data", "weight", "scale"]
    return ["data", "weight", "scale", "bias"]


def _qconv_infer(attrs, in_shapes):
    from .nn import _conv_infer
    nf = parse_int(attrs["num_filter"])
    no_bias = parse_bool(attrs.get("no_bias", False))
    new_in, out_s, _ = _conv_infer(dict(attrs, no_bias=True),
                                   in_shapes[:2])
    new_in = [new_in[0], new_in[1], (nf,)] + \
        ([] if no_bias else [(nf,)])
    return new_in, out_s, []


def _qconv_xla(attrs, data, weight, scale, bias=None):
    from .nn import _convolution
    bshape = (-1,) + (1,) * (weight.ndim - 1)
    wf = weight.astype(jnp.float32) * scale.reshape(bshape)
    return _convolution(dict(attrs, no_bias=bias is None), data, wf,
                        bias)


def _dequant_rows_kernel(w_ref, s_ref, o_ref):
    o_ref[...] = w_ref[...].astype(jnp.float32) * \
        s_ref[...].reshape(-1, 1)


def _qconv_pallas_variant(attrs, inputs, aux, is_train, rng):
    # the conv itself stays on the MXU (XLA is already optimal there,
    # same split as FusedConvBNReLU); the Pallas half is the dequant —
    # ONE tiled VMEM pass over the int8 rows
    from .pallas_kernels import pallas_call, _divisor_block
    import jax.experimental.pallas as pl
    from .nn import _convolution
    data, weight, scale = inputs[:3]
    bias = inputs[3] if len(inputs) > 3 else None
    o = weight.shape[0]
    cols = int(np.prod(weight.shape[1:]))
    bo = _divisor_block(o, 256)
    wf = pallas_call(
        _dequant_rows_kernel,
        out_shape=jax.ShapeDtypeStruct((o, cols), jnp.float32),
        grid=(o // bo,),
        in_specs=[pl.BlockSpec((bo, cols), lambda i: (i, 0)),
                  pl.BlockSpec((1, bo), lambda i: (0, i))],
        out_specs=pl.BlockSpec((bo, cols), lambda i: (i, 0)))(
            weight.reshape(o, cols), scale.reshape(1, o))
    out = _convolution(dict(attrs, no_bias=bias is None), data,
                       wf.reshape(weight.shape), bias)
    return [out], []


def _qconv_eligible(attrs, in_shapes, in_dtypes):
    w_s = in_shapes[1]
    if len(in_shapes[0]) != 4 or len(w_s) != 4:
        return False
    if str(in_dtypes[1]) not in ("int8", "float8_e4m3fn"):
        return False
    if int(np.prod(w_s[1:])) > 65536 or str(in_dtypes[0]) not in (
            "float32", "bfloat16", "float16"):
        return False
    # the dequant pass keeps (bo, cols) int8-in + f32-out resident:
    # bound the block working set like _qconv_pallas_variant builds it
    from .pallas_kernels import _divisor_block
    cols = int(np.prod(w_s[1:]))
    bo = _divisor_block(w_s[0], 256)
    return bo * cols * 5 <= 8 << 20


#: worst-case VMEM residency at the _qfc_eligible bound (<= 12 MiB):
#: x rows f32, int8 weight tile decoded in VMEM, f32 accumulator
_QFC_KSPEC = {
    "tiles": [((256, 8192), "float32"), ((256, 16384), "int8"),
              ((256, 256), "float32")],
    "dtypes": ("float32", "bfloat16", "float16", "int8",
               "float8_e4m3fn"),
}

#: dequant rows pass at the _qconv_eligible bound: 1-B weights in
#: (int8 or fp8 — same residency) + f32 out
_QCONV_KSPEC = {
    "tiles": [((256, 6144), "int8"), ((256, 6144), "float32")],
    "dtypes": ("float32", "bfloat16", "float16", "int8",
               "float8_e4m3fn"),
}


def _register_quant_ops():
    if "QuantizedFullyConnected" in OP_REGISTRY:
        return
    from .nn import _CONV_ATTRS
    register("QuantizedFullyConnected", inputs=_qfc_inputs,
             simple=_qfc_xla, infer_shape=_qfc_infer,
             attr_spec={"num_hidden": (parse_int, None),
                        "no_bias": (parse_bool, False),
                        "flatten": (parse_bool, True)},
             variants={"pallas": (_qfc_pallas_variant, _qfc_eligible,
                                  _QFC_KSPEC)})
    register("QuantizedConvolution", inputs=_qconv_inputs,
             simple=_qconv_xla, infer_shape=_qconv_infer,
             attr_spec=dict(_CONV_ATTRS),
             variants={"pallas": (_qconv_pallas_variant,
                                  _qconv_eligible, _QCONV_KSPEC)})


_register_quant_ops()


# ----------------------------------------------------- graph rewrite
def quantizable_weights(symbol, arg_params):
    """Weight params eligible for the int8 rewrite: variables that feed
    ONLY FullyConnected/Convolution nodes at the weight slot (a weight
    shared with any other consumer stays float), are present in
    ``arg_params``, and have >= 2 dims."""
    ok, bad = set(), set()
    for node in symbol._topo_nodes():
        if node.is_variable:
            continue
        for i, (inp, _idx) in enumerate(node.inputs):
            if not inp.is_variable:
                continue
            if node.op in _QUANT_OPS and i == 1:
                ok.add(inp.name)
            else:
                bad.add(inp.name)
    out = []
    for name in sorted(ok - bad):
        p = arg_params.get(name)
        if p is not None and len(p.shape) >= 2:
            out.append(name)
    return out


def quantize_symbol(symbol, arg_params, dtype="int8"):
    """Rewrite a trained graph onto the quantized ops.

    Returns ``(qsymbol, qarg_params)``: every quantizable weight ``w``
    is replaced in the params by ``w_q`` (the storage dtype — int8 or
    fp8/float8_e4m3fn) + ``w_scale`` (f32) and its consumer nodes
    become Quantized* nodes (same node names, so output names and
    downstream wiring are unchanged). Aux params are untouched — pass
    the originals alongside.
    """
    from ..ndarray import NDArray
    from ..symbol import Node, Symbol
    storage = _QUANT_DTYPES.get(str(dtype))
    if storage is None:
        raise MXNetError(f"quantize: unsupported dtype {dtype!r} "
                         "(int8 or fp8)")
    targets = set(quantizable_weights(symbol, arg_params))
    if not targets:
        raise MXNetError(
            "quantize: no quantizable weights (needs FullyConnected/"
            "Convolution nodes with their weight in arg_params)")

    qvars = {}          # weight name -> (q_node, scale_node)

    def qvar(name):
        if name not in qvars:
            qvars[name] = (
                Node(None, f"{name}_q", extra={"__dtype__": storage}),
                Node(None, f"{name}_scale",
                     extra={"__dtype__": "float32"}))
        return qvars[name]

    rebuilt = {}

    def rebuild(node):
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        if node.is_variable:
            rebuilt[id(node)] = node        # var nodes are shared as-is
            return node
        new_inputs = [(rebuild(inp), idx) for inp, idx in node.inputs]
        wnode = node.inputs[1][0] if len(node.inputs) > 1 else None
        if (node.op in _QUANT_OPS and wnode is not None
                and wnode.is_variable and wnode.name in targets):
            q_node, s_node = qvar(wnode.name)
            new_inputs = ([new_inputs[0], (q_node, 0), (s_node, 0)]
                          + new_inputs[2:])
            new = Node(_QUANT_OPS[node.op], node.name,
                       dict(node.attrs), new_inputs, dict(node._extra))
        else:
            new = Node(node.op, node.name, dict(node.attrs),
                       new_inputs, dict(node._extra))
        rebuilt[id(node)] = new
        return new

    qsym = Symbol([(rebuild(n), i) for n, i in symbol._outputs])

    qargs = {}
    for name, val in arg_params.items():
        if name in qvars:
            q, s = quantize_per_channel(val, axis=0, dtype=storage)
            qargs[f"{name}_q"] = NDArray(jnp.asarray(q))
            qargs[f"{name}_scale"] = NDArray(jnp.asarray(s))
        else:
            qargs[name] = val
    return qsym, qargs
