"""Output/loss-head ops.

The reference's output ops (SoftmaxOutput, the regression outputs, MakeLoss,
SVMOutput — reference: src/operator/softmax_output-inl.h:1-381,
regression_output-inl.h, make_loss-inl.h, svm_output-inl.h) have a special
contract: their *backward ignores the incoming head gradient* and emits the
loss gradient directly ((p - onehot(label)) * grad_scale for softmax). They
are simultaneously "predict heads" (forward output = prediction) and "loss
heads" (backward = loss grad).

TPU-native realization: ``jax.custom_vjp`` (attrs as static nondiff args)
pins the exact same gradient, so ``jax.vjp`` over the composed graph — the
replacement for the NNVM Gradient pass — produces identical cotangents to
the reference's hand-written backward kernels. The executor seeds ones as
head cotangents for ops marked ``is_loss`` (matching GraphExecutor's
head-grad entries, graph_executor.cc:178-230).
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..base import parse_bool, parse_float, parse_int
from .registry import register, alias


# --------------------------------------------------------------------------
# SoftmaxOutput
# --------------------------------------------------------------------------
def _softmax_out_fwd_impl(data, label, attrs):
    multi = parse_bool(attrs.get("multi_output", False))
    if multi:
        prob = jax.nn.softmax(data, axis=1)
    elif data.ndim == 1:
        prob = jax.nn.softmax(data, axis=-1)
    else:
        prob = jax.nn.softmax(data.reshape(data.shape[0], -1),
                              axis=-1).reshape(data.shape)
    return prob


def _softmax_out_grad(prob, label, attrs):
    multi = parse_bool(attrs.get("multi_output", False))
    grad_scale = parse_float(attrs.get("grad_scale", 1.0))
    use_ignore = parse_bool(attrs.get("use_ignore", False))
    ignore_label = parse_float(attrs.get("ignore_label", -1.0))
    normalization = attrs.get("normalization", "null")
    if multi:
        # data (n, c, d1...) label (n, d1...)
        onehot = jax.nn.one_hot(label.astype(jnp.int32), prob.shape[1],
                                axis=1, dtype=prob.dtype)
        grad = prob - onehot
        mask = jnp.ones_like(label, dtype=prob.dtype)
        if use_ignore:
            mask = (label != ignore_label).astype(prob.dtype)
        grad = grad * jnp.expand_dims(mask, 1)
        valid = jnp.sum(mask)
    else:
        flat = prob.reshape(prob.shape[0], -1)
        onehot = jax.nn.one_hot(label.astype(jnp.int32).reshape(-1),
                                flat.shape[-1], dtype=prob.dtype)
        grad = (flat - onehot).reshape(prob.shape)
        mask = jnp.ones((prob.shape[0],), dtype=prob.dtype)
        if use_ignore:
            mask = (label.reshape(-1) != ignore_label).astype(prob.dtype)
        grad = grad * mask.reshape((-1,) + (1,) * (prob.ndim - 1))
        valid = jnp.sum(mask)
    if normalization == "batch":
        grad = grad / prob.shape[0]
    elif normalization == "valid":
        grad = grad / jnp.maximum(valid, 1.0)
    return grad * grad_scale


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_output_fn(data, label, attrs_tuple):
    return _softmax_out_fwd_impl(data, label, dict(attrs_tuple))


def _softmax_output_fwd(data, label, attrs_tuple):
    prob = _softmax_out_fwd_impl(data, label, dict(attrs_tuple))
    return prob, (prob, label)


def _softmax_output_bwd(attrs_tuple, res, g):
    prob, label = res
    # reference semantics: head grad ignored, loss grad emitted directly
    grad = _softmax_out_grad(prob, label, dict(attrs_tuple))
    return grad.astype(prob.dtype), jnp.zeros_like(label)


_softmax_output_fn.defvjp(_softmax_output_fwd, _softmax_output_bwd)

_SOFTMAX_ATTRS = {
    "grad_scale": (parse_float, 1.0), "ignore_label": (parse_float, -1.0),
    "multi_output": (parse_bool, False), "use_ignore": (parse_bool, False),
    "preserve_shape": (parse_bool, False), "normalization": (None, "null"),
    "out_grad": (parse_bool, False),
}


def _softmax_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    label_s = in_shapes[1] if len(in_shapes) > 1 else None
    if data_s is not None:
        if parse_bool(attrs.get("multi_output", False)):
            label_s = (data_s[0],) + tuple(data_s[2:])
        else:
            label_s = (data_s[0],)
    return [data_s, label_s], [data_s], []


@register("SoftmaxOutput", inputs=("data", "label"), is_loss=True,
          attr_spec=dict(_SOFTMAX_ATTRS), infer_shape=_softmax_infer)
def _softmax_output_op(attrs, data, label):
    return _softmax_output_fn(data, label, tuple(sorted(attrs.items())))

alias("Softmax", "SoftmaxOutput")


# --------------------------------------------------------------------------
# Regression outputs (reference: regression_output-inl.h) — forward is
# identity/sigmoid; backward = (pred - label) * grad_scale / num_output
# --------------------------------------------------------------------------
def _make_regression(transform, grad_fn):
    @partial(jax.custom_vjp, nondiff_argnums=(2,))
    def reg(data, label, grad_scale):
        return transform(data)

    def fwd(data, label, grad_scale):
        out = transform(data)
        return out, (out, label)

    def bwd(grad_scale, res, g):
        out, label = res
        denom = out.size // out.shape[0] if out.ndim > 1 else 1
        grad = grad_fn(out, label.reshape(out.shape)) * grad_scale / denom
        return grad.astype(out.dtype), jnp.zeros_like(label)

    reg.defvjp(fwd, bwd)
    return reg


_LINREG = _make_regression(lambda x: x, lambda o, l: o - l)
_LOGREG = _make_regression(jax.nn.sigmoid, lambda o, l: o - l)
_MAEREG = _make_regression(lambda x: x, lambda o, l: jnp.sign(o - l))

_REG_ATTRS = {"grad_scale": (parse_float, 1.0)}


def _reg_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    return [data_s, data_s], [data_s], []


for _name, _fn in (("LinearRegressionOutput", _LINREG),
                   ("LogisticRegressionOutput", _LOGREG),
                   ("MAERegressionOutput", _MAEREG)):
    register(_name, inputs=("data", "label"), is_loss=True,
             attr_spec=dict(_REG_ATTRS), infer_shape=_reg_infer,
             simple=(lambda attrs, data, label, _f=_fn:
                     _f(data, label,
                        parse_float(attrs.get("grad_scale", 1.0)))))


# --------------------------------------------------------------------------
# SVMOutput (reference: svm_output-inl.h)
# --------------------------------------------------------------------------
@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_fn(data, label, margin, reg_coef, use_linear):
    return data


def _svm_fwd(data, label, margin, reg_coef, use_linear):
    return data, (data, label)


def _svm_bwd(margin, reg_coef, use_linear, res, g):
    data, label = res
    n, c = data.shape
    onehot = jax.nn.one_hot(label.astype(jnp.int32), c, dtype=data.dtype)
    score_correct = jnp.sum(data * onehot, axis=1, keepdims=True)
    viol = data - score_correct + margin
    if use_linear:
        mask = ((viol > 0).astype(data.dtype)) * (1 - onehot)
        grad = mask - onehot * jnp.sum(mask, axis=1, keepdims=True)
    else:
        maskv = jnp.maximum(viol, 0) * (1 - onehot)
        grad = 2 * maskv - 2 * onehot * jnp.sum(maskv, axis=1, keepdims=True)
    return grad * reg_coef, jnp.zeros_like(label)


_svm_fn.defvjp(_svm_fwd, _svm_bwd)


@register("SVMOutput", inputs=("data", "label"), is_loss=True,
          attr_spec={"margin": (parse_float, 1.0),
                     "regularization_coefficient": (parse_float, 1.0),
                     "use_linear": (parse_bool, False)},
          infer_shape=lambda attrs, s: ([s[0], (s[0][0],) if s[0] else None],
                                        [s[0]], []))
def _svm_output(attrs, data, label):
    return _svm_fn(data, label, parse_float(attrs.get("margin", 1.0)),
                   parse_float(attrs.get("regularization_coefficient", 1.0)),
                   parse_bool(attrs.get("use_linear", False)))


# --------------------------------------------------------------------------
# MakeLoss (reference: make_loss-inl.h) — forward identity, backward = ones *
# grad_scale (turns any symbol into a loss)
# --------------------------------------------------------------------------
@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _makeloss_fn(data, grad_scale, norm):
    return data


def _makeloss_fwd(data, grad_scale, norm):
    return data, data


def _makeloss_bwd(grad_scale, norm, res, g):
    shape, dtype = res.shape, res.dtype
    grad = jnp.full(shape, grad_scale, dtype=dtype)
    if norm == "batch":
        grad = grad / shape[0]
    elif norm == "valid":
        grad = grad / float(np.prod(shape))
    return (grad,)


_makeloss_fn.defvjp(_makeloss_fwd, _makeloss_bwd)


@register("MakeLoss", inputs=("data",), is_loss=True,
          attr_spec={"grad_scale": (parse_float, 1.0),
                     "valid_thresh": (parse_float, 0.0),
                     "normalization": (None, "null")},
          infer_shape=lambda attrs, s: (s, [s[0]], []))
def _make_loss_op(attrs, data):
    return _makeloss_fn(data, parse_float(attrs.get("grad_scale", 1.0)),
                        attrs.get("normalization", "null"))


@register("IdentityAttachKLSparseReg", inputs=("data",),
          attr_spec={"sparseness_target": (parse_float, 0.1),
                     "penalty": (parse_float, 0.001),
                     "momentum": (parse_float, 0.9)},
          infer_shape=lambda attrs, s: (s, [s[0]], []))
def _identity_kl(attrs, data):
    # identity forward; the KL-sparsity penalty enters only through the
    # gradient (value-zero term kl - stop_gradient(kl))
    target = parse_float(attrs.get("sparseness_target", 0.1))
    penalty = parse_float(attrs.get("penalty", 0.001))
    rho_hat = jnp.mean(data, axis=0, keepdims=True)
    kl = penalty * jnp.sum(
        target * jnp.log(target / (rho_hat + 1e-12)) +
        (1 - target) * jnp.log((1 - target) / (1 - rho_hat + 1e-12)))
    return data + (kl - jax.lax.stop_gradient(kl))
