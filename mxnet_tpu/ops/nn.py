"""Neural-network layer ops.

The reference implements these as stateful ``OperatorProperty`` classes over
mshadow/cuDNN (reference: src/operator/*-inl.h, e.g. convolution-inl.h:1-570,
batch_norm-inl.h:1-358). Here each layer is a pure JAX function registered in
the unified registry; XLA lowers convs/matmuls onto the MXU and fuses the
elementwise epilogues, which is what cuDNN kernel selection + mshadow fusion
did for GPUs.

API conventions preserved from the reference: NCHW data layout, the same
parameter names (kernel/stride/pad/num_filter/num_hidden/...), auto-created
weight/bias inputs with bidirectional shape inference (weight shapes deduced
from data shapes at bind time), aux states for BatchNorm moving stats.

dtype note: inputs compute in their incoming dtype — bfloat16 flows through
every layer untouched (TPU-native mixed precision); BatchNorm statistics are
accumulated in float32 regardless of input dtype for stability.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import (parse_tuple, parse_bool, parse_int, parse_float,
                    merge_shape, shape_is_known)
from .registry import register, alias


def _pair(v, default):
    t = parse_tuple(v, None) if v is not None else None
    if t is None:
        return default
    if len(t) == 1:
        return (t[0], t[0])
    return t


# --------------------------------------------------------------------------
# FullyConnected (reference: fully_connected-inl.h)
# --------------------------------------------------------------------------
def _fc_inputs(attrs):
    if parse_bool(attrs.get("no_bias", False)):
        return ["data", "weight"]
    return ["data", "weight", "bias"]


def _fc_infer(attrs, in_shapes, out_known=None):
    num_hidden = parse_int(attrs["num_hidden"])
    no_bias = parse_bool(attrs.get("no_bias", False))
    data_s = in_shapes[0]
    out_s = (0, num_hidden)
    w_s = in_shapes[1] if len(in_shapes) > 1 else None
    if out_known and out_known[0] is not None:
        out_s = merge_shape(out_s, out_known[0])
    if data_s is not None:
        if all(d > 0 for d in data_s[1:]):
            in_dim = int(np.prod(data_s[1:], dtype=np.int64))
            w_s = merge_shape(w_s, (num_hidden, in_dim))
        out_s = merge_shape(out_s, (data_s[0], num_hidden))
        # back-fill batch dim from a known output (bidirectional pass)
        data_s = merge_shape(data_s, (out_s[0],) + tuple(data_s[1:]))
    elif out_s is not None and w_s is not None and shape_is_known(w_s):
        # fully-unknown data: batch from output, feature dim from weight
        # (valid when data is 2-d, the dominant case for h2h matmuls)
        data_s = (out_s[0], w_s[1])
    new_in = [data_s, w_s] + ([] if no_bias else [(num_hidden,)])
    return new_in, [out_s], []


@register("FullyConnected", inputs=_fc_inputs,
          attr_spec={"num_hidden": (parse_int, None),
                     "no_bias": (parse_bool, False),
                     "flatten": (parse_bool, True)},
          infer_shape=_fc_infer)
def _fully_connected(attrs, data, weight, bias=None):
    if data.ndim > 2 and attrs.get("flatten", True):
        data = data.reshape((data.shape[0], -1))
    # weight stored (num_hidden, in_dim) per reference layout -> x @ W^T on
    # MXU; bf16 operands accumulate in f32 natively on the MXU, so no
    # explicit preferred_element_type (whose downcast breaks the conv/dot
    # transpose rules under mixed dtypes)
    out = jnp.dot(data, weight.T.astype(data.dtype))
    if bias is not None:
        out = out + bias.astype(data.dtype)
    return out


# --------------------------------------------------------------------------
# Convolution / Deconvolution (reference: convolution-inl.h,
# deconvolution-inl.h; cudnn_convolution.h autotune -> XLA picks algorithms)
# --------------------------------------------------------------------------
_CONV_ATTRS = {
    "kernel": (parse_tuple, None), "stride": (parse_tuple, None),
    "dilate": (parse_tuple, None), "pad": (parse_tuple, None),
    "num_filter": (parse_int, None), "num_group": (parse_int, 1),
    "no_bias": (parse_bool, False), "workspace": (parse_int, 1024),
    "cudnn_tune": (None, None), "cudnn_off": (parse_bool, False),
    "layout": (None, None),
}


def _conv_inputs(attrs):
    if parse_bool(attrs.get("no_bias", False)):
        return ["data", "weight"]
    return ["data", "weight", "bias"]


def _conv_out_dim(in_dim, k, s, p, d):
    return (in_dim + 2 * p - (d * (k - 1) + 1)) // s + 1


def _conv_infer(attrs, in_shapes):
    kernel = parse_tuple(attrs["kernel"])
    nf = parse_int(attrs["num_filter"])
    ng = parse_int(attrs.get("num_group", 1))
    no_bias = parse_bool(attrs.get("no_bias", False))
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    dilate = _ntuple(attrs.get("dilate"), nd, 1)
    data_s = in_shapes[0]
    w_s, out_s = None, None
    if data_s is not None:
        cin = data_s[1]
        w_s = (nf, cin // ng) + kernel
        spatial = tuple(_conv_out_dim(data_s[2 + i], kernel[i], stride[i],
                                      pad[i], dilate[i]) for i in range(nd))
        out_s = (data_s[0], nf) + spatial
    new_in = [data_s, w_s] + ([] if no_bias else [(nf,)])
    return new_in, [out_s], []


def _ntuple(v, n, default):
    t = parse_tuple(v) if v is not None else None
    if t is None:
        return (default,) * n
    if len(t) != n:
        t = tuple(t) + (default,) * (n - len(t))
    return t


@register("Convolution", inputs=_conv_inputs, attr_spec=dict(_CONV_ATTRS),
          infer_shape=_conv_infer)
def _convolution(attrs, data, weight, bias=None):
    kernel = parse_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    dilate = _ntuple(attrs.get("dilate"), nd, 1)
    ng = parse_int(attrs.get("num_group", 1))
    if nd == 1:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                        ("NCH", "OIH", "NCH"))
    elif nd == 2:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    else:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                        ("NCDHW", "OIDHW", "NCDHW"))
    # bf16 operands accumulate in f32 on the MXU natively; an explicit
    # preferred_element_type=f32 + downcast breaks conv's VJP transpose
    # (f32 cotangent vs bf16 operand), so operand dtypes drive the output
    out = lax.conv_general_dilated(
        data, weight.astype(data.dtype), stride,
        [(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=ng)
    if bias is not None:
        out = out + bias.astype(data.dtype).reshape((1, -1) + (1,) * nd)
    return out

alias("Convolution_v1", "Convolution")


def _deconv_infer(attrs, in_shapes):
    kernel = parse_tuple(attrs["kernel"])
    nf = parse_int(attrs["num_filter"])
    ng = parse_int(attrs.get("num_group", 1))
    no_bias = parse_bool(attrs.get("no_bias", True))
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    adj = _ntuple(attrs.get("adj"), nd, 0)
    data_s = in_shapes[0]
    w_s, out_s = None, None
    if data_s is not None:
        cin = data_s[1]
        w_s = (cin, nf // ng) + kernel
        spatial = tuple(stride[i] * (data_s[2 + i] - 1) + kernel[i]
                        - 2 * pad[i] + adj[i] for i in range(nd))
        out_s = (data_s[0], nf) + spatial
    new_in = [data_s, w_s] + ([] if no_bias else [(nf,)])
    return new_in, [out_s], []


@register("Deconvolution", inputs=_conv_inputs,
          attr_spec={**_CONV_ATTRS, "adj": (parse_tuple, None),
                     "target_shape": (parse_tuple, None),
                     "no_bias": (parse_bool, True)},
          infer_shape=_deconv_infer)
def _deconvolution(attrs, data, weight, bias=None):
    kernel = parse_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    ng = parse_int(attrs.get("num_group", 1))
    # MXNet deconv weight is (cin, nf, k...) — the weight of the *forward*
    # conv nf->cin, i.e. OIHW with O=cin; transpose_kernel runs its
    # transpose, mapping cin -> nf
    spec = ("NCHW", "OIHW", "NCHW") if nd == 2 else ("NCH", "OIH", "NCH")
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, spec)
    adj = _ntuple(attrs.get("adj"), nd, 0)
    # conv_transpose's padding is the raw lhs-dilated conv padding:
    # out = (in-1)*s - k + 2 + lo + hi. MXNet wants (in-1)*s + k - 2p + adj
    # => lo = k-1-p, hi = k-1-p+adj (adj = output_padding, high side)
    pads = [(k - 1 - p, k - 1 - p + a)
            for k, p, a in zip(kernel, pad, adj)]
    out = lax.conv_transpose(
        data, weight.astype(data.dtype), stride,
        pads, dimension_numbers=dn,
        transpose_kernel=True) if ng == 1 else _grouped_deconv(
            data, weight, stride, pads, dn, ng)
    if bias is not None:
        out = out + bias.astype(data.dtype).reshape((1, -1) + (1,) * nd)
    return out


def _grouped_deconv(data, weight, stride, pads, dn, ng):
    xs = jnp.split(data, ng, axis=1)
    ws = jnp.split(weight, ng, axis=0)
    outs = [lax.conv_transpose(x, w.astype(x.dtype), stride,
                               pads, dimension_numbers=dn,
                               transpose_kernel=True)
            for x, w in zip(xs, ws)]
    return jnp.concatenate(outs, axis=1)


# --------------------------------------------------------------------------
# Pooling (reference: pooling-inl.h + nn/pool.h kernels)
# --------------------------------------------------------------------------
def _pool_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None], []
    kernel = parse_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    if parse_bool(attrs.get("global_pool", False)):
        out_s = data_s[:2] + (1,) * nd
    else:
        conv = parse_bool(attrs.get("pooling_convention", "valid") == "full")
        dims = []
        for i in range(nd):
            x = data_s[2 + i] + 2 * pad[i] - kernel[i]
            if conv:
                dims.append(int(np.ceil(x / stride[i])) + 1)
            else:
                dims.append(x // stride[i] + 1)
        out_s = data_s[:2] + tuple(dims)
    return in_shapes, [out_s], []


@register("Pooling", inputs=("data",),
          attr_spec={"kernel": (parse_tuple, None), "pool_type": (None, "max"),
                     "global_pool": (parse_bool, False),
                     "pooling_convention": (None, "valid"),
                     "stride": (parse_tuple, None), "pad": (parse_tuple, None)},
          infer_shape=_pool_infer)
def _pooling(attrs, data, channel_axis=1):
    """channel_axis=1 is the reference NCHW layout; the NHWC layout pass
    (ops/layout.py) calls with channel_axis=-1, putting the window over
    the middle axes and the channel in lanes."""
    nd = data.ndim - 2
    nhwc = channel_axis in (-1, data.ndim - 1)
    sp0 = 1 if nhwc else 2              # first spatial axis
    if parse_bool(attrs.get("global_pool", False)):
        kernel = data.shape[sp0:sp0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = parse_tuple(attrs["kernel"])
        stride = _ntuple(attrs.get("stride"), nd, 1)
        pad = _ntuple(attrs.get("pad"), nd, 0)
    ptype = attrs.get("pool_type", "max")
    if nhwc:
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
    else:
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
    # pooling_convention='full' (ceil output shape): pad extra on the high
    # side so reduce_window's floor semantics yield the ceil-based shape
    # that _pool_infer reports
    extra = [0] * nd
    if attrs.get("pooling_convention", "valid") == "full" and \
            not parse_bool(attrs.get("global_pool", False)):
        for i in range(nd):
            x = data.shape[sp0 + i] + 2 * pad[i] - kernel[i]
            want = int(np.ceil(x / stride[i])) + 1
            extra[i] = max(0, (want - 1) * stride[i] + kernel[i]
                           - (data.shape[sp0 + i] + 2 * pad[i]))
    spatial = tuple((p, p + e) for p, e in zip(pad, extra))
    pads = ((0, 0),) + spatial + ((0, 0),) if nhwc else \
        ((0, 0), (0, 0)) + spatial
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if ptype in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if ptype == "sum":
            return summed
        # count_include_pad=True semantics (reference default)
        return summed / np.prod(kernel)
    raise ValueError(f"pool_type {ptype}")

alias("Pooling_v1", "Pooling")


# --------------------------------------------------------------------------
# Activation family (reference: activation-inl.h, leaky_relu-inl.h)
# --------------------------------------------------------------------------
def _ID_INFER(attrs, in_shapes, out_known=None):
    merged = merge_shape(in_shapes[0],
                         out_known[0] if out_known else None)
    return [merged] + list(in_shapes[1:]), [merged], []


def gelu_exact(x):
    """Exact (erf-based) GeLU in f32, cast back to the input dtype —
    the shared definition the Activation op, the LeakyReLU gelu mode,
    and the FusedBiasGeLU epilogue (ops/pallas_kernels.py) all lower
    through, so the kernel tier's numerics gate compares one function."""
    x32 = x.astype(jnp.float32)
    y = 0.5 * x32 * (1.0 + lax.erf(x32 * np.float32(0.7071067811865476)))
    return y.astype(x.dtype)


@register("Activation", inputs=("data",), attr_spec={"act_type": (None, "relu")},
          infer_shape=_ID_INFER)
def _activation(attrs, x):
    t = attrs.get("act_type", "relu")
    if t == "relu":
        return jnp.maximum(x, 0)
    if t == "sigmoid":
        return jax.nn.sigmoid(x)
    if t == "tanh":
        return jnp.tanh(x)
    if t == "softrelu":
        return jax.nn.softplus(x)
    if t == "softsign":
        return x / (1 + jnp.abs(x))
    if t == "gelu":
        return gelu_exact(x)
    raise ValueError(f"act_type {t}")


def _lrelu_inputs(attrs):
    if attrs.get("act_type", "leaky") == "prelu":
        return ["data", "gamma"]
    return ["data"]


def _lrelu_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    if attrs.get("act_type", "leaky") == "prelu":
        g = (data_s[1],) if data_s is not None else None
        return [data_s, g], [data_s], []
    return in_shapes, [data_s], []


def _lrelu_fwd(attrs, inputs, aux, is_train, rng):
    t = attrs.get("act_type", "leaky")
    x = inputs[0]
    slope = parse_float(attrs.get("slope", 0.25))
    if t == "leaky":
        return [jnp.where(x > 0, x, slope * x)], []
    if t == "elu":
        return [jnp.where(x > 0, x, slope * (jnp.exp(x) - 1))], []
    if t == "prelu":
        gamma = inputs[1].reshape((1, -1) + (1,) * (x.ndim - 2))
        return [jnp.where(x > 0, x, gamma * x)], []
    if t == "rrelu":
        lo = parse_float(attrs.get("lower_bound", 0.125))
        hi = parse_float(attrs.get("upper_bound", 0.334))
        if is_train:
            slope_r = jax.random.uniform(rng, x.shape, dtype=x.dtype,
                                         minval=lo, maxval=hi)
        else:
            slope_r = (lo + hi) / 2.0
        return [jnp.where(x > 0, x, slope_r * x)], []
    if t == "gelu":
        # reference ships gelu through LeakyReLU(act_type='gelu')
        return [gelu_exact(x)], []
    raise ValueError(f"act_type {t}")


register("LeakyReLU", inputs=_lrelu_inputs, full=_lrelu_fwd, need_rng=True,
         attr_spec={"act_type": (None, "leaky"), "slope": (parse_float, 0.25),
                    "lower_bound": (parse_float, 0.125),
                    "upper_bound": (parse_float, 0.334)},
         infer_shape=_lrelu_infer)


@register("SoftmaxActivation", inputs=("data",),
          attr_spec={"mode": (None, "instance")}, infer_shape=_ID_INFER)
def _softmax_activation(attrs, x):
    if attrs.get("mode", "instance") == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register("softmax", inputs=("data",), shape_passthrough=True,
          attr_spec={"axis": (parse_int, -1),
                     "temperature": (None, None)})
def _softmax_op(attrs, x):
    t = attrs.get("temperature")
    if t not in (None, "None"):
        x = x / parse_float(t)
    return jax.nn.softmax(x, axis=attrs.get("axis", -1))


@register("log_softmax", inputs=("data",), shape_passthrough=True,
          attr_spec={"axis": (parse_int, -1)})
def _log_softmax_op(attrs, x):
    return jax.nn.log_softmax(x, axis=attrs.get("axis", -1))


# --------------------------------------------------------------------------
# BatchNorm (reference: batch_norm-inl.h; aux = moving_mean/moving_var,
# updated in-place during training via the executor's aux swap)
# --------------------------------------------------------------------------
def _bn_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    c = (data_s[1],) if data_s is not None else None
    return [data_s, c, c], [data_s, c, c], [c, c]


def _bn_fwd(attrs, inputs, aux, is_train, rng, channel_axis=1):
    """channel_axis=1 is the reference NCHW layout; the NHWC layout pass
    (ops/layout.py) calls with channel_axis=-1 so statistics reduce over
    the major axes and the per-channel affine rides the lane dimension."""
    data, gamma, beta = inputs
    moving_mean, moving_var = aux
    eps = parse_float(attrs.get("eps", 1e-3))
    momentum = parse_float(attrs.get("momentum", 0.9))
    fix_gamma = parse_bool(attrs.get("fix_gamma", True))
    use_global = parse_bool(attrs.get("use_global_stats", False))
    if channel_axis in (-1, data.ndim - 1):
        axes = tuple(range(data.ndim - 1))
        bshape = (1,) * (data.ndim - 1) + (-1,)
    else:
        axes = (0,) + tuple(range(2, data.ndim))
        bshape = (1, -1) + (1,) * (data.ndim - 2)
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if is_train and not use_global:
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes)
        var = jnp.var(x32, axis=axes)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape).astype(data.dtype)) * \
        (inv.reshape(bshape) * gamma.reshape(bshape)).astype(data.dtype) + \
        beta.reshape(bshape).astype(data.dtype)
    return [out, mean, var], [new_mean, new_var]


register("BatchNorm", inputs=("data", "gamma", "beta"),
         aux=("moving_mean", "moving_var"), full=_bn_fwd,
         num_outputs=3, output_names=["output", "mean", "var"],
         num_visible=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
         attr_spec={"eps": (parse_float, 1e-3), "momentum": (parse_float, 0.9),
                    "fix_gamma": (parse_bool, True),
                    "use_global_stats": (parse_bool, False),
                    "output_mean_var": (parse_bool, False)},
         infer_shape=_bn_infer)
alias("CuDNNBatchNorm", "BatchNorm")


def _in_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    c = (data_s[1],) if data_s is not None else None
    return [data_s, c, c], [data_s], []


@register("InstanceNorm", inputs=("data", "gamma", "beta"),
          attr_spec={"eps": (parse_float, 1e-3)}, infer_shape=_in_infer)
def _instance_norm(attrs, data, gamma, beta):
    eps = attrs.get("eps", 1e-3)
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) + \
        beta.reshape(bshape)


def _ln_infer(attrs, in_shapes):
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None, None, None], []
    axis = parse_int(attrs.get("axis", -1)) % len(data_s)
    c = (data_s[axis],)
    red = tuple(d for i, d in enumerate(data_s) if i != axis)
    return [data_s, c, c], [data_s, red, red], []


def _ln_fwd(attrs, inputs, aux, is_train, rng):
    """LayerNorm (reference: layer_norm-inl.h) — per-sample statistics
    over one axis. Outputs [out, mean, std]; statistics accumulate in
    float32 regardless of input dtype (same rule as BatchNorm)."""
    data, gamma, beta = inputs
    axis = parse_int(attrs.get("axis", -1)) % data.ndim
    eps = parse_float(attrs.get("eps", 1e-5))
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis)
    var = jnp.var(x32, axis=axis)
    std = jnp.sqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = -1
    me = jnp.expand_dims(mean, axis)
    rstd = jnp.expand_dims(lax.rsqrt(var + eps), axis)
    out = (x32 - me) * rstd * gamma.astype(jnp.float32).reshape(bshape) \
        + beta.astype(jnp.float32).reshape(bshape)
    return [out.astype(data.dtype), mean, std], []


register("LayerNorm", inputs=("data", "gamma", "beta"), full=_ln_fwd,
         num_outputs=3, output_names=["output", "mean", "std"],
         num_visible=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
         attr_spec={"axis": (parse_int, -1), "eps": (parse_float, 1e-5),
                    "output_mean_var": (parse_bool, False)},
         infer_shape=_ln_infer)


# --------------------------------------------------------------------------
# RoPE — rotary position embedding (the transformer workload's position
# encoding; the reference predates attention entirely). Split-half
# (GPT-NeoX) convention: head dim D splits into (x1, x2) halves and each
# pair (x1[i], x2[i]) rotates by angle pos * base^(-2i/D). Linear in x,
# so the VJP needs no saved activations beyond the (T, D/2) trig tables.
# --------------------------------------------------------------------------
def rope_apply(x, positions, base=10000.0):
    """Rotate ``x`` (..., T, D) by rotary angles at absolute
    ``positions`` — (T,) shared across the batch, or (B, T) per-batch
    positions (the slot-pooled decode path: every slot sits at its own
    cursor). Traced positions are fine (the KV-cache decode path
    rotates at the cache cursor). Trig in float32, cast back."""
    dh = x.shape[-1]
    half = dh // 2
    inv = jnp.asarray(base, jnp.float32) ** (
        -jnp.arange(0, half, dtype=jnp.float32) * (2.0 / dh))
    ang = positions.astype(jnp.float32)[..., :, None] * inv  # (..., T, half)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    if positions.ndim == 2:
        # per-slot positions broadcast against x (B, H, T, D): insert
        # the head axis so (B, T, half) -> (B, 1, T, half)
        cos, sin = cos[:, None], sin[:, None]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


@register("RoPE", inputs=("data",), shape_passthrough=True,
          attr_spec={"base": (parse_float, 10000.0),
                     "offset": (parse_int, 0)})
def _rope(attrs, x):
    """x: (B, H, T, D) — rotate every (t, pair) by its absolute position
    ``offset + t``. D must be even (pairs rotate)."""
    if x.shape[-1] % 2:
        raise ValueError(f"RoPE needs an even head dim, got {x.shape[-1]}")
    t_axis = x.shape[-2]
    positions = parse_int(attrs.get("offset", 0)) + jnp.arange(t_axis)
    return rope_apply(x, positions, parse_float(attrs.get("base", 10000.0)))


@register("L2Normalization", inputs=("data",),
          attr_spec={"eps": (parse_float, 1e-10), "mode": (None, "instance")},
          infer_shape=_ID_INFER)
def _l2_normalization(attrs, data):
    eps = attrs.get("eps", 1e-10)
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
        kd = True
    elif mode == "channel":
        axes, kd = (1,), True
    elif mode == "spatial":
        axes, kd = tuple(range(2, data.ndim)), True
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=kd) + eps)
    return data / norm


@register("LRN", inputs=("data",),
          attr_spec={"alpha": (parse_float, 1e-4), "beta": (parse_float, 0.75),
                     "knorm": (parse_float, 2.0), "nsize": (parse_int, 5)},
          num_outputs=2, num_visible=1, output_names=["output", "tmp_norm"],
          infer_shape=lambda attrs, s: (s, [s[0], s[0]], []))
def _lrn(attrs, data, channel_axis=1):
    """channel_axis=1 is the reference NCHW layout; the NHWC layout pass
    calls with channel_axis=-1 (window slides over the lane axis)."""
    nsize = attrs["nsize"]
    alpha, beta, knorm = attrs["alpha"], attrs["beta"], attrs["knorm"]
    sq = jnp.square(data)
    half = nsize // 2
    pads = [(0, 0)] * data.ndim
    ax = channel_axis % data.ndim
    pads[ax] = (half, half)
    padded = jnp.pad(sq, pads)
    c = data.shape[ax]
    idx = [slice(None)] * data.ndim
    windows = 0
    for i in range(nsize):
        idx[ax] = slice(i, i + c)
        windows = windows + padded[tuple(idx)]
    norm = (knorm + alpha / nsize * windows) ** beta
    return data / norm, norm


# --------------------------------------------------------------------------
# Dropout (reference: dropout-inl.h; functional rng)
# --------------------------------------------------------------------------
def _dropout_fwd(attrs, inputs, aux, is_train, rng):
    x = inputs[0]
    p = parse_float(attrs.get("p", 0.5))
    if not is_train or p <= 0.0:
        return [x, jnp.ones_like(x)], []
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape).astype(x.dtype) / keep
    return [x * mask, mask], []


register("Dropout", inputs=("data",), full=_dropout_fwd, need_rng=True,
         num_outputs=2, num_visible=1, output_names=["output", "mask"],
         attr_spec={"p": (parse_float, 0.5), "mode": (None, "training")},
         infer_shape=lambda attrs, s: (s, [s[0], s[0]], []))


# --------------------------------------------------------------------------
# Concat / SliceChannel / UpSampling / Crop
# --------------------------------------------------------------------------
def _concat_inputs(attrs):
    return [f"arg{i}" for i in range(parse_int(attrs.get("num_args", 2)))]


def _concat_infer(attrs, in_shapes):
    dim = parse_int(attrs.get("dim", 1))
    known = [s for s in in_shapes if s is not None]
    if not known:
        return in_shapes, [None], []
    total = sum(s[dim] for s in in_shapes if s is not None)
    if any(s is None for s in in_shapes):
        return in_shapes, [None], []
    out = list(known[0])
    out[dim] = total
    return in_shapes, [tuple(out)], []


@register("Concat", inputs=_concat_inputs,
          attr_spec={"num_args": (parse_int, 2), "dim": (parse_int, 1)},
          infer_shape=_concat_infer)
def _concat(attrs, *xs):
    return jnp.concatenate(xs, axis=parse_int(attrs.get("dim", 1)))

alias("concat", "Concat")


def _slice_channel_outputs(attrs):
    return parse_int(attrs.get("num_outputs", 1))


def _slice_channel_infer(attrs, in_shapes):
    n = parse_int(attrs.get("num_outputs", 1))
    axis = parse_int(attrs.get("axis", 1))
    squeeze = parse_bool(attrs.get("squeeze_axis", False))
    data_s = in_shapes[0]
    if data_s is None:
        return in_shapes, [None] * n, []
    out = list(data_s)
    out[axis] = out[axis] // n
    if squeeze and out[axis] == 1:
        out.pop(axis)
    return in_shapes, [tuple(out)] * n, []


@register("SliceChannel", inputs=("data",),
          attr_spec={"num_outputs": (parse_int, 1), "axis": (parse_int, 1),
                     "squeeze_axis": (parse_bool, False)},
          num_outputs=_slice_channel_outputs,
          infer_shape=_slice_channel_infer)
def _slice_channel(attrs, x):
    n = parse_int(attrs.get("num_outputs", 1))
    axis = parse_int(attrs.get("axis", 1))
    outs = jnp.split(x, n, axis=axis)
    if parse_bool(attrs.get("squeeze_axis", False)):
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs)

alias("split", "SliceChannel")


def _upsampling_inputs(attrs):
    n = parse_int(attrs.get("num_args", 1))
    if attrs.get("sample_type", "nearest") == "bilinear":
        return ["data", "weight"]
    return [f"arg{i}" for i in range(n)]


@register("UpSampling", inputs=_upsampling_inputs,
          attr_spec={"scale": (parse_int, 2), "num_filter": (parse_int, 0),
                     "sample_type": (None, "nearest"),
                     "multi_input_mode": (None, "concat"),
                     "num_args": (parse_int, 1), "workspace": (parse_int, 512)})
def _upsampling(attrs, *xs):
    scale = parse_int(attrs.get("scale", 2))
    stype = attrs.get("sample_type", "nearest")
    if stype == "nearest":
        # every input is upsampled to the common target size (first input's
        # spatial dims x scale), each by its own integer factor — reference
        # upsampling_nearest semantics for multi-resolution inputs
        th = xs[0].shape[2] * scale
        tw = xs[0].shape[3] * scale
        outs = []
        for x in xs:
            fh, fw = th // x.shape[2], tw // x.shape[3]
            outs.append(jnp.repeat(jnp.repeat(x, fh, axis=2), fw, axis=3))
        if len(outs) == 1:
            return outs[0]
        if attrs.get("multi_input_mode", "concat") == "sum":
            return sum(outs)
        return jnp.concatenate(outs, axis=1)
    # bilinear: per-channel (grouped) deconvolution with a learnable
    # bilinear kernel — reference lowers to Deconvolution with
    # num_group == channels (upsampling-inl.h)
    data, weight = xs
    k = 2 * scale - scale % 2
    pad = int(np.ceil((scale - 1) / 2.0))
    c = data.shape[1]
    dn = lax.conv_dimension_numbers((data.shape[0], 1) + data.shape[2:],
                                    (1, 1, k, k),
                                    ("NCHW", "OIHW", "NCHW"))
    pads = [(k - 1 - pad, k - 1 - pad)] * 2
    return _grouped_deconv(data, weight.astype(data.dtype),
                           (scale, scale), pads, dn, c)


@register("Crop", inputs=lambda attrs: ["data", "crop_like"][:parse_int(
    attrs.get("num_args", 1))],
    attr_spec={"num_args": (parse_int, 1), "offset": (parse_tuple, (0, 0)),
               "h_w": (parse_tuple, (0, 0)),
               "center_crop": (parse_bool, False)})
def _crop_op(attrs, data, crop_like=None):
    oy, ox = attrs.get("offset", (0, 0))
    if crop_like is not None:
        h, w = crop_like.shape[2], crop_like.shape[3]
    else:
        h, w = attrs.get("h_w", (0, 0))
    if parse_bool(attrs.get("center_crop", False)):
        oy = (data.shape[2] - h) // 2
        ox = (data.shape[3] - w) // 2
    return lax.dynamic_slice(data, (0, 0, oy, ox),
                             (data.shape[0], data.shape[1], h, w))


# --------------------------------------------------------------------------
# Sequence ops (reference: sequence_last/mask/reverse-inl.h; axis 0 = time)
# --------------------------------------------------------------------------
def _seq_inputs(attrs):
    if parse_bool(attrs.get("use_sequence_length", False)):
        return ["data", "sequence_length"]
    return ["data"]


@register("SequenceLast", inputs=_seq_inputs,
          attr_spec={"use_sequence_length": (parse_bool, False)})
def _sequence_last(attrs, data, sequence_length=None):
    if sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]


@register("SequenceMask", inputs=_seq_inputs,
          attr_spec={"use_sequence_length": (parse_bool, False),
                     "value": (parse_float, 0.0)})
def _sequence_mask(attrs, data, sequence_length=None):
    if sequence_length is None:
        return data
    t = data.shape[0]
    steps = jnp.arange(t).reshape((t,) + (1,) * (data.ndim - 1))
    mask = steps < sequence_length.astype(jnp.int32).reshape(
        (1, -1) + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, attrs.get("value", 0.0))


@register("SequenceReverse", inputs=_seq_inputs,
          attr_spec={"use_sequence_length": (parse_bool, False)})
def _sequence_reverse(attrs, data, sequence_length=None):
    if sequence_length is None:
        return jnp.flip(data, axis=0)
    t = data.shape[0]
    lengths = sequence_length.astype(jnp.int32)
    steps = jnp.arange(t)
    # per-batch reverse of the first `len` steps, identity elsewhere
    idx = jnp.where(steps[:, None] < lengths[None, :],
                    lengths[None, :] - 1 - steps[:, None], steps[:, None])
    return jnp.take_along_axis(
        data, idx.reshape(idx.shape + (1,) * (data.ndim - 2)), axis=0)


# --------------------------------------------------------------------------
# Spatial ops: ROIPooling, BilinearSampler, GridGenerator,
# SpatialTransformer, Correlation (reference: src/operator/<name>-inl.h)
# --------------------------------------------------------------------------
@register("ROIPooling", inputs=("data", "rois"),
          attr_spec={"pooled_size": (parse_tuple, None),
                     "spatial_scale": (parse_float, 1.0)})
def _roi_pooling(attrs, data, rois):
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]
    h, w = data.shape[2], data.shape[3]

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[batch_idx]

        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def pool_cell(py, px):
            hstart = y1 + (py * rh) // ph
            hend = y1 + ((py + 1) * rh + ph - 1) // ph
            wstart = x1 + (px * rw) // pw
            wend = x1 + ((px + 1) * rw + pw - 1) // pw
            ymask = (ys >= hstart) & (ys < jnp.minimum(hend, h))
            xmask = (xs >= wstart) & (xs < jnp.minimum(wend, w))
            mask = ymask[:, None] & xmask[None, :]
            masked = jnp.where(mask[None], img, -jnp.inf)
            out = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.any(mask), out, 0.0)

        cells = jax.vmap(lambda py: jax.vmap(
            lambda px: pool_cell(py, px))(jnp.arange(pw)))(jnp.arange(ph))
        return jnp.transpose(cells, (2, 0, 1))

    return jax.vmap(one_roi)(rois)


@register("BilinearSampler", inputs=("data", "grid"))
def _bilinear_sampler(attrs, data, grid):
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1) * (h - 1) / 2.0

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(img, yy, xx):
        yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
        valid = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
        out = img[:, yi, xi]
        return out * valid[None].astype(img.dtype)

    def one(img, x0_, y0_, wx_, wy_):
        v00 = gather(img, y0_, x0_)
        v01 = gather(img, y0_, x0_ + 1)
        v10 = gather(img, y0_ + 1, x0_)
        v11 = gather(img, y0_ + 1, x0_ + 1)
        return (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_) +
                v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)

    return jax.vmap(one)(data, x0, y0, wx, wy)


@register("GridGenerator", inputs=lambda attrs: (
    ["data"] if attrs.get("transform_type", "affine") == "affine"
    else ["data"]),
    attr_spec={"transform_type": (None, "affine"),
               "target_shape": (parse_tuple, (0, 0))})
def _grid_generator(attrs, data):
    ttype = attrs.get("transform_type", "affine")
    if ttype == "affine":
        h, w = attrs["target_shape"]
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys, xs = jnp.meshgrid(jnp.linspace(-1, 1, h), jnp.linspace(-1, 1, w),
                              indexing="ij")
        ones = jnp.ones_like(xs)
        coords = jnp.stack([xs.ravel(), ys.ravel(), ones.ravel()])  # (3, h*w)
        grid = jnp.einsum("nij,jk->nik", theta, coords)  # (n, 2, h*w)
        return grid.reshape(n, 2, h, w)
    # warp: data is (n, 2, h, w) flow field
    n, _, h, w = data.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                          jnp.arange(w, dtype=data.dtype), indexing="ij")
    gx = (data[:, 0] + xs) * 2 / (w - 1) - 1
    gy = (data[:, 1] + ys) * 2 / (h - 1) - 1
    return jnp.stack([gx, gy], axis=1)


@register("SpatialTransformer", inputs=("data", "loc"),
          attr_spec={"target_shape": (parse_tuple, (0, 0)),
                     "transform_type": (None, "affine"),
                     "sampler_type": (None, "bilinear")})
def _spatial_transformer(attrs, data, loc):
    grid = _grid_generator.__wrapped__(
        {"transform_type": "affine", "target_shape": attrs["target_shape"]},
        loc) if hasattr(_grid_generator, "__wrapped__") else None
    # direct composition: affine grid then bilinear sample
    h, w = attrs["target_shape"]
    n = loc.shape[0]
    theta = loc.reshape(n, 2, 3)
    ys, xs = jnp.meshgrid(jnp.linspace(-1, 1, h), jnp.linspace(-1, 1, w),
                          indexing="ij")
    ones = jnp.ones_like(xs)
    coords = jnp.stack([xs.ravel(), ys.ravel(), ones.ravel()])
    grid = jnp.einsum("nij,jk->nik", theta, coords).reshape(n, 2, h, w)
    return _bilinear_sampler_impl(data, grid)


def _bilinear_sampler_impl(data, grid):
    from .registry import get_op
    out, _ = get_op("BilinearSampler").forward({}, [data, grid], [], False, None)
    return out[0]


@register("Correlation", inputs=("data1", "data2"),
          attr_spec={"kernel_size": (parse_int, 1),
                     "max_displacement": (parse_int, 1),
                     "stride1": (parse_int, 1), "stride2": (parse_int, 1),
                     "pad_size": (parse_int, 0),
                     "is_multiply": (parse_bool, True)},
          num_outputs=2, num_visible=1, output_names=["output", "tmp"])
def _correlation(attrs, data1, data2):
    md = attrs["max_displacement"]
    s2 = attrs["stride2"]
    pad = attrs["pad_size"]
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    n, c, h, w = d1.shape
    disp = list(range(-md, md + 1, s2))
    outs = []
    for dy in disp:
        for dx in disp:
            shifted = jnp.roll(d2, (-dy, -dx), axis=(2, 3))
            prod = jnp.mean(d1 * shifted, axis=1)
            outs.append(prod)
    out = jnp.stack(outs, axis=1)
    crop = out[:, :, pad:h - pad if pad else h, pad:w - pad if pad else w]
    return crop, jnp.zeros((1,), dtype=data1.dtype)
