"""Profiler (reference: python/mxnet/profiler.py + src/engine/profiler.cc).

The reference collects per-op exec records into chrome://tracing JSON.
TPU-native: delegate to the JAX/XLA profiler (xplane traces, viewable in
TensorBoard/Perfetto — strictly richer than the reference's records: includes
fusion boundaries, HBM traffic, MXU utilization). API kept: profiler_set_config,
profiler_set_state, dump_profile.
"""
from __future__ import annotations

import logging

import jax

_STATE = {"mode": "symbolic", "filename": "profile.json", "running": False,
          "trace_dir": None}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """reference: profiler.py profiler_set_config."""
    _STATE["mode"] = mode
    _STATE["filename"] = filename


def profiler_set_state(state="stop"):
    """'run' starts a jax profiler trace; 'stop' ends it.
    reference: profiler.py profiler_set_state."""
    if state == "run" and not _STATE["running"]:
        import os
        trace_dir = os.path.splitext(_STATE["filename"])[0] + "_trace"
        _STATE["trace_dir"] = trace_dir
        jax.profiler.start_trace(trace_dir)
        _STATE["running"] = True
    elif state == "stop" and _STATE["running"]:
        jax.profiler.stop_trace()
        _STATE["running"] = False
        logging.info("profiler trace written to %s", _STATE["trace_dir"])
    elif state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")


def dump_profile():
    """reference: MXDumpProfile — here the trace is already on disk."""
    if _STATE["running"]:
        profiler_set_state("stop")
    return _STATE["trace_dir"]
