"""Profiler (reference: python/mxnet/profiler.py + src/engine/profiler.cc).

The reference collects per-op exec records into chrome://tracing JSON
surfaced by MXDumpProfile. Two trace sources serve that contract here:

* the **telemetry span tracer** (telemetry/) — framework-level spans
  (executor compile/run, per-op dispatch, kvstore collectives, IO,
  Module.fit batches) serialized to chrome://tracing JSON at the
  configured ``filename``, exactly the reference's artifact shape;
* the **JAX/XLA profiler** — xplane traces (fusion boundaries, HBM
  traffic, MXU utilization) written to ``<filename stem>_trace/``,
  viewable in TensorBoard/Perfetto — strictly richer than the
  reference's records at the op level.

API kept: profiler_set_config, profiler_set_state, dump_profile.
``profiler_set_state("run")`` turns the telemetry tracer on (so spans
from every instrumented layer start recording) and starts a JAX trace;
``dump_profile()`` writes the chrome://tracing JSON and returns its path.
"""
from __future__ import annotations

import logging
import os

import jax

from . import telemetry

_STATE = {"mode": "symbolic", "filename": "profile.json", "running": False,
          "trace_dir": None, "owns_telemetry": False, "jax_trace": True}


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """reference: profiler.py profiler_set_config."""
    _STATE["mode"] = mode
    _STATE["filename"] = filename


def trace_dir():
    """The JAX xplane trace directory of the current/last run (None when
    no trace ever started)."""
    return _STATE["trace_dir"]


def profiler_set_state(state="stop"):
    """'run' enables telemetry span recording and starts a jax profiler
    trace; 'stop' ends both. reference: profiler.py profiler_set_state."""
    if state == "run" and not _STATE["running"]:
        if not telemetry.enabled():
            telemetry.enable()
            _STATE["owns_telemetry"] = True
        trace_dir = os.path.splitext(_STATE["filename"])[0] + "_trace"
        _STATE["trace_dir"] = trace_dir
        try:
            jax.profiler.start_trace(trace_dir)
            _STATE["jax_trace"] = True
        except Exception as exc:  # spans still collect without xplane
            logging.warning("jax profiler trace unavailable (%s); "
                            "telemetry spans still recording", exc)
            _STATE["jax_trace"] = False
        _STATE["running"] = True
    elif state == "stop" and _STATE["running"]:
        if _STATE["jax_trace"]:
            jax.profiler.stop_trace()
            logging.info("profiler trace written to %s", _STATE["trace_dir"])
        if _STATE["owns_telemetry"]:
            telemetry.disable()
            _STATE["owns_telemetry"] = False
        _STATE["running"] = False
    elif state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")


def dump_profile():
    """Serialize collected spans to chrome://tracing JSON at the
    configured filename and return that path (reference: MXDumpProfile).

    Besides the executor/fit spans, the dump carries the request trace
    plane (``serve.trace/<id>`` tracks, one per traced request/decode
    session) and the training step-phase breakdown (``step.phase``
    track) whenever those planes recorded anything — docs/telemetry.md
    "Trace plane" / "Step-time attribution".

    Always returns the written file's path — including when no trace was
    ever started (the file then just carries an empty/partial span set),
    never a silent None. The JAX xplane trace dir (when one ran) is
    recorded in the JSON's ``otherData.jax_trace_dir``.
    """
    if _STATE["running"]:
        profiler_set_state("stop")
    path = _STATE["filename"]
    if not path:
        raise ValueError(
            "no profile filename configured; call profiler_set_config("
            "filename=...) first")
    meta = {"mode": _STATE["mode"]}
    if _STATE["trace_dir"]:
        meta["jax_trace_dir"] = os.path.abspath(_STATE["trace_dir"])
    return telemetry.chrome_trace.dump(path, metadata=meta)
