"""Data iterators (reference: python/mxnet/io.py, 747 LoC + src/io/).

The python-side iterator contract is preserved exactly: ``DataIter`` yields
``DataBatch(data=[NDArray], label=[NDArray], pad, index)``; ``provide_data``/
``provide_label`` are lists of ``DataDesc``. The C++ decode/augment pipeline
(reference: src/io/iter_image_recordio_2.cc) is replaced by (a) in-memory
iterators here, (b) a RecordIO-backed ImageRecordIter in image.py, and (c)
``PrefetchingIter`` which gives the background-thread double-buffering the
reference's PrefetcherIter provides (iter_prefetcher.h:129).
"""
from __future__ import annotations

import struct
import gzip
import os
import threading
import queue as _queue
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array
from . import faults as _faults
from . import telemetry as _telemetry

__all__ = ["DataDesc", "DataBatch", "StackedDataBatch", "DataIter",
           "NDArrayIter", "ResizeIter", "PrefetchingIter", "MNISTIter",
           "CSVIter"]


def _instrumented_next(next_fn):
    """Wrap a ``next`` implementation with telemetry: an ``io.next`` span
    (labeled with the concrete iterator class), a batches-served counter
    and a fetch-latency histogram — batches/sec falls out of the two.
    Disabled telemetry costs one extra call + branch per batch."""
    import functools

    @functools.wraps(next_fn)
    def next_with_telemetry(self):
        if not _telemetry.enabled():
            return next_fn(self)
        cls = type(self).__name__
        with _telemetry.span("io.next", _hist="io.next.seconds", iter=cls):
            batch = next_fn(self)
        _telemetry.counter("io.batches", iter=cls).inc()
        return batch
    return next_with_telemetry


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """reference: io.py:19 — (name, shape) + dtype/layout attributes."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """reference: io.py:82."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class StackedDataBatch(DataBatch):
    """K consecutive batches stacked on a new leading axis — one
    scan-dispatch window for ``Module.fit(steps_per_dispatch=K)``.

    ``data``/``label`` hold arrays of shape ``(steps, batch, ...)``;
    ``pads`` keeps the per-step pad values. ``split()`` recovers
    per-step ``DataBatch`` views (the single-step fallback path for
    partial tail windows).
    """

    def __init__(self, data, label=None, pads=None, index=None):
        steps = int(data[0].shape[0])
        pads = list(pads) if pads is not None else [0] * steps
        super().__init__(data, label, pad=pads[-1] if pads else 0,
                         index=index)
        self.steps = steps
        self.pads = pads

    def split(self):
        out = []
        for k in range(self.steps):
            out.append(DataBatch(
                [NDArray(d.asjax()[k]) for d in self.data],
                [NDArray(l.asjax()[k]) for l in (self.label or [])],
                pad=self.pads[k] if k < len(self.pads) else 0))
        return out


class DataIter:
    """Base iterator. reference: io.py:130."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    @_instrumented_next
    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize epoch length. reference: io.py:220."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iters.

    reference: io.py:285 (python) mirroring the C++ PrefetcherIter
    (src/io/iter_prefetcher.h): a producer thread stays one batch ahead so
    host decode overlaps device compute.

    Decode-failure policy (docs/faults.md): ``on_decode_error``
    (default ``MXNET_IO_ON_DECODE_ERROR``, else ``"raise"``) decides
    what a failing batch fetch does. ``"raise"`` propagates to the
    consumer (the pre-existing behavior); ``"skip"`` records the
    failure (``io.decode.skipped`` counter, ``io.decode.skip`` ring
    record, ``skipped_batches`` attribute) and moves on to the next
    batch — at pod scale one corrupt record must not kill an epoch.
    A run of more than ``MXNET_IO_DECODE_MAX_SKIP`` (default 100)
    *consecutive* failures is a broken pipeline, not bad records, and
    raises regardless. The ``io.decode`` injection point sits after
    each fetch so tier-1 drives both paths deterministically.
    """

    def __init__(self, iters, rename_data=None, rename_label=None,
                 device=None, on_decode_error=None, max_decode_skip=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self._on_decode_error = (
            on_decode_error if on_decode_error is not None
            else os.environ.get("MXNET_IO_ON_DECODE_ERROR", "raise"))
        if self._on_decode_error not in ("raise", "skip"):
            raise MXNetError(
                f"on_decode_error={self._on_decode_error!r} "
                "(want 'raise' or 'skip')")
        try:
            self._max_decode_skip = int(
                max_decode_skip if max_decode_skip is not None
                else os.environ.get("MXNET_IO_DECODE_MAX_SKIP", "") or 100)
        except ValueError:
            self._max_decode_skip = 100
        self.skipped_batches = 0        # cumulative skip bookkeeping
        self._consecutive_skips = 0
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        # prefetch-to-device double buffering (the C++ pipeline's pinned
        # staging + async H2D copy, iter_prefetcher.h): the producer
        # thread lands each batch in HBM while the consumer computes on
        # the previous one, so the train step never waits on the copy
        self._device = device
        self._stack_k = 1      # >1: producer stacks K-batch scan windows
        self.batch_size = self.provide_data[0].shape[0]
        self._queue = _queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r[x.name], str) else r[x.name]
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r[x.name], str) else r[x.name]
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def ensure_device(self, device):
        """Enable prefetch-to-device staging if it wasn't configured.

        Lets training wrappers (examples/common/fit.py) upgrade an
        already-prefetching iterator — e.g. ImageRecordIter's default
        ``PrefetchingIter(it)`` — to stage batches onto the training
        device without double-wrapping. No-op when a device is set."""
        if self._device is None:
            self._device = device
        return self

    def stack_windows(self, k, device=None):
        """Producer-side K-batch stacking for scan-fused training.

        With ``k > 1`` the background thread groups every ``k``
        consecutive batches into one :class:`StackedDataBatch` (leading
        axis = step) and — when a device is set — lands the stacked
        buffers in device memory off-thread, so ``Module.fit``'s K-step
        scan dispatch consumes HBM-resident windows without a per-batch
        host round trip. A short tail yields a partial window
        (``steps < k``). ``k=1`` restores per-batch mode. Returns self.
        """
        if device is not None:
            self._device = device
        k = max(1, int(k))
        if k != self._stack_k:
            self._stack_k = k
            self.reset()       # restart the producer in the new mode
        return self

    def _merge(self, batches):
        """Merge one batch from each inner iter (multi-iter fan-in)."""
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([(b.label or []) for b in batches], []),
            pad=batches[0].pad, index=batches[0].index)

    def _stack(self, window):
        """Stack K merged batches into one StackedDataBatch, staged onto
        the configured device (the off-thread H2D copy)."""
        import jax
        import jax.numpy as jnp
        dev = None
        if self._device is not None:
            dev = self._device.jax_device() if hasattr(
                self._device, "jax_device") else self._device

        def put(slot_arrays):
            arr = jnp.stack([a.asjax() if isinstance(a, NDArray)
                             else jnp.asarray(np.asarray(a))
                             for a in slot_arrays])
            if dev is not None:
                arr = jax.device_put(arr, dev)
            return NDArray(arr)

        data = [put([b.data[i] for b in window])
                for i in range(len(window[0].data))]
        label = [put([b.label[i] for b in window])
                 for i in range(len(window[0].label or []))]
        return StackedDataBatch(data, label,
                                pads=[b.pad or 0 for b in window],
                                index=window[0].index)

    def _next_batches(self):
        """One batch per inner iter, through the decode-failure policy:
        the ``io.decode`` injection point fires after the fetch (the
        batch is consumed either way, so a skip is a true skip, not a
        silent retry of the same cursor), and a failure under the
        ``skip`` policy records and moves on. StopIteration always
        propagates — end-of-epoch is not a failure."""
        # benign race with reset()'s re-zero: reset() joins the producer
        # first (so overlap needs a >1s wedged join), and the value is a
        # GIL-atomic int only this counter's own error path reads — a
        # lost reset costs one extra counted skip, never control flow
        while True:
            try:
                batches = [i.next() for i in self.iters]
                _faults.point("io.decode")
                self._consecutive_skips = 0  # mxlint: guarded-by(gil)
                return batches
            except StopIteration:
                raise
            except Exception as exc:
                if self._on_decode_error != "skip":
                    raise
                self._consecutive_skips += 1
                self.skipped_batches += 1
                _telemetry.counter("io.decode.skipped").inc()
                _telemetry.flightrec.note(
                    "io.decode.skip", skipped=self.skipped_batches,
                    error=f"{type(exc).__name__}: {exc}")
                if self._consecutive_skips > self._max_decode_skip:
                    raise MXNetError(
                        f"{self._consecutive_skips} consecutive decode "
                        "failures exceed MXNET_IO_DECODE_MAX_SKIP="
                        f"{self._max_decode_skip}: the pipeline is "
                        "broken, not the records") from exc

    def _producer(self):
        # _stack_k/_device are GIL-atomic snapshots of caller-side
        # config (stage()/ensure_device() both restart the producer via
        # reset() after writing); a stale read can only affect batches
        # the restart discards with the old queue
        while not self._stop.is_set():
            try:
                k = self._stack_k  # mxlint: guarded-by(gil)
                if k <= 1:
                    batches = self._next_batches()
                    if self._device is not None:  # mxlint: guarded-by(gil)
                        batches = [self._to_device(b) for b in batches]
                    self._queue.put(batches)
                    continue
                window, exhausted = [], False
                for _ in range(k):
                    try:
                        window.append(self._merge(self._next_batches()))
                    except StopIteration:
                        exhausted = True
                        break
                if window:
                    self._queue.put(self._stack(window))
                if exhausted:
                    self._queue.put(None)
                    return
            except StopIteration:
                self._queue.put(None)
                return
            except BaseException as exc:  # surface in the consumer, don't
                self._queue.put(("__error__", exc))  # die into a hang
                return

    def _to_device(self, batch):
        import jax
        dev = self._device.jax_device() if hasattr(
            self._device, "jax_device") else self._device

        def put(arr):
            return NDArray(jax.device_put(arr.asjax(), dev))
        return DataBatch([put(d) for d in batch.data],
                         [put(l) for l in (batch.label or [])],
                         pad=batch.pad, index=batch.index)

    def _start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def __del__(self):
        stop = getattr(self, "_stop", None)     # ctor may have raised
        if stop is not None:                    # before creating it
            stop.set()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        for i in self.iters:
            i.reset()
        self._consecutive_skips = 0
        self._queue = _queue.Queue(maxsize=2)
        self._start()

    @_instrumented_next
    def next(self):
        batches = self._queue.get()
        if batches is None:
            raise StopIteration
        if isinstance(batches, tuple) and batches and \
                batches[0] == "__error__":
            raise batches[1]
        if isinstance(batches, StackedDataBatch):   # stack_windows mode
            return batches
        return self._merge(batches)


def _init_data(data, allow_empty, default_name):
    """Normalize input to list of (name, numpy). reference: io.py:395."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    ret = {}
    for k, v in data.items():
        if isinstance(v, NDArray):
            ret[k] = v.asnumpy()
        else:
            ret[k] = np.asarray(v)
    return list(ret.items())


class NDArrayIter(DataIter):
    """In-memory iterator. reference: io.py:457."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]
        self.data_list = [x[1] for x in self.data] + \
            [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    @_instrumented_next
    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor:self.cursor + self.batch_size])
                    for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [array(np.concatenate(
            (x[1][self.cursor:], x[1][:pad]), axis=0)) for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class MNISTIter(DataIter):
    """idx-format MNIST reader (reference: src/io/iter_mnist.cc:61-241)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, part_index=0, num_parts=1, **kwargs):
        super().__init__(batch_size)
        imgs = self._read_idx_images(image)
        labels = self._read_idx_labels(label)
        if num_parts > 1:
            n = imgs.shape[0] // num_parts
            imgs = imgs[part_index * n:(part_index + 1) * n]
            labels = labels[part_index * n:(part_index + 1) * n]
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, imgs.shape[1],
                                imgs.shape[2])
        imgs = imgs.astype(np.float32) / 255.0
        self._inner = NDArrayIter(imgs, labels.astype(np.float32),
                                  batch_size, shuffle)

    @staticmethod
    def _open(path):
        if path.endswith(".gz"):
            return gzip.open(path, "rb")
        return open(path, "rb")

    @classmethod
    def _read_idx_images(cls, path):
        with cls._open(path) as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            return np.frombuffer(f.read(num * rows * cols),
                                 dtype=np.uint8).reshape(num, rows, cols)

    @classmethod
    def _read_idx_labels(cls, path):
        with cls._open(path) as f:
            magic, num = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return np.frombuffer(f.read(num), dtype=np.uint8)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class CSVIter(DataIter):
    """CSV reader (reference: src/io/iter_csv.cc:41-132)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=128, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="roll_over" if round_batch else "pad")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()
