"""CustomOp: python-defined operators inside nd and sym graphs.

Reference mechanism (reference: python/mxnet/operator.py:396-660 +
src/operator/custom/custom.cc): a ``CustomOpProp`` subclass registered
under a name; the graph node ``Custom(op_type=name)`` calls back into
python for forward/backward, executed as ``kAsync`` engine callbacks.

TPU-native bridge: the python body runs on host via
``jax.pure_callback`` — inside jitted graphs XLA inserts the host
round-trip at exactly this op, while everything around it stays fused on
device. The declared backward is wired through ``jax.custom_vjp`` so
``jax.vjp`` of the whole graph (our Gradient pass) flows through the
python ``backward``. SURVEY.md §7 M6 names this mapping.

Usage is reference-identical::

    @mx.operator.register("mysigmoid")
    class MySigmoidProp(mx.operator.CustomOpProp):
        def list_arguments(self): return ['data']
        def infer_shape(self, in_shape): return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes):
            return MySigmoid()

    y = mx.nd.Custom(x, op_type="mysigmoid")
    s = mx.sym.Custom(data, op_type="mysigmoid", name="sig")
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import register as _register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "PythonOp", "NumpyOp", "NDArrayOp"]

_CUSTOM_PROPS: dict = {}


class CustomOp:
    """Base class for python operator bodies (forward/backward on host)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute outputs; write them with ``self.assign(out_data[i],
        req[i], value)``."""
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute input gradients into ``in_grad`` (default: zero)."""
        for i, g in enumerate(in_grad):
            self.assign(g, req[i] if i < len(req) else "write",
                        np.zeros_like(g.asnumpy()))

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into the NDArray ``dst`` honoring the req."""
        if req == "null":
            return
        from .ndarray import NDArray
        val = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
        if req in ("write", "inplace"):
            dst._set(jnp.asarray(val.reshape(dst.shape), dtype=dst.dtype))
        elif req == "add":
            dst._set(dst.asjax() + jnp.asarray(val.reshape(dst.shape),
                                               dtype=dst.dtype))
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Declarative metadata for a CustomOp (names, shapes, factory)."""

    def __init__(self, need_top_grad=False):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        return CustomOp()


def register(reg_name):
    """Class decorator registering a CustomOpProp under ``reg_name``."""
    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() expects a CustomOpProp subclass")
        _CUSTOM_PROPS[reg_name] = prop_cls
        return prop_cls
    return deco


def get_all_registered():
    return dict(_CUSTOM_PROPS)


# ---------------------------------------------------------------- plumbing
def _prop_for(attrs):
    op_type = attrs.get("op_type")
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    try:
        cls = _CUSTOM_PROPS[op_type]
    except KeyError:
        raise MXNetError(
            f"no CustomOpProp registered as {op_type!r} "
            f"(registered: {sorted(_CUSTOM_PROPS)})") from None
    kwargs = {k: str(v) for k, v in attrs.items()
              if k not in ("op_type",) and not k.startswith("__")}
    return cls(**kwargs)


def _nd(arrays):
    from .ndarray import NDArray
    return [NDArray(jnp.asarray(a)) for a in arrays]


def _run_forward_host(prop, is_train, n_in, *host_arrays):
    """Host-side forward: build NDArray cells, run the user's CustomOp."""
    in_data = _nd(host_arrays[:n_in])
    aux = _nd(host_arrays[n_in:])
    in_shapes = [list(a.shape) for a in in_data]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    _, out_types, _ = prop.infer_type(
        [np.dtype(a.dtype) for a in in_data] or [np.dtype(np.float32)])
    out_data = _nd([np.zeros(tuple(s), dt)
                    for s, dt in zip(out_shapes, out_types)])
    op = prop.create_operator(None, in_shapes,
                              [np.dtype(a.dtype) for a in in_data])
    op.forward(bool(is_train), ["write"] * len(out_data), in_data,
               out_data, aux)
    return tuple(o.asnumpy() for o in out_data)


def _run_backward_host(prop, n_in, n_out, n_aux, *host_arrays):
    """Host-side backward: out_grads + saved (in, out, aux) -> in_grads."""
    k = 0
    out_grad = _nd(host_arrays[k:k + n_out]); k += n_out
    in_data = _nd(host_arrays[k:k + n_in]); k += n_in
    out_data = _nd(host_arrays[k:k + n_out]); k += n_out
    aux = _nd(host_arrays[k:k + n_aux])
    in_grad = _nd([np.zeros(a.shape, a.dtype) for a in in_data])
    op = prop.create_operator(None, [list(a.shape) for a in in_data],
                              [np.dtype(a.dtype) for a in in_data])
    op.backward(["write"] * len(in_grad), out_grad, in_data, out_data,
                in_grad, aux)
    return tuple(g.asnumpy() for g in in_grad)


@functools.lru_cache(maxsize=None)
def _custom_call(attrs_key, is_train):
    """Build (once per attrs/is_train) the custom_vjp'd jax function."""
    attrs = dict(attrs_key)
    prop = _prop_for(attrs)
    n_in = len(prop.list_arguments())
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())

    def out_struct(inputs):
        in_shapes = [list(np.shape(a)) for a in inputs]
        _, out_shapes, _ = prop.infer_shape(in_shapes)
        _, out_types, _ = prop.infer_type(
            [np.dtype(a.dtype) for a in inputs] or [np.dtype(np.float32)])
        return tuple(jax.ShapeDtypeStruct(tuple(s), dt)
                     for s, dt in zip(out_shapes, out_types))

    @jax.custom_vjp
    def call(inputs, aux):
        return jax.pure_callback(
            functools.partial(_run_forward_host, prop, is_train, n_in),
            out_struct(inputs), *inputs, *aux)

    def call_fwd(inputs, aux):
        outs = call(inputs, aux)
        return outs, (inputs, outs, aux)

    def call_bwd(res, out_grads):
        inputs, outs, aux = res
        grad_struct = tuple(
            jax.ShapeDtypeStruct(np.shape(a), a.dtype) for a in inputs)
        in_grads = jax.pure_callback(
            functools.partial(_run_backward_host, prop, n_in, n_out, n_aux),
            grad_struct, *out_grads, *inputs, *outs, *aux)
        aux_grads = tuple(jnp.zeros(np.shape(a), a.dtype) for a in aux)
        return tuple(in_grads), aux_grads

    call.defvjp(call_fwd, call_bwd)
    return call, prop


def _attrs_key(attrs):
    return tuple(sorted((k, str(v)) for k, v in attrs.items()
                        if not k.startswith("__")))


def _custom_forward(attrs, inputs, aux, is_train, rng):
    call, _ = _custom_call(_attrs_key(attrs), bool(is_train))
    outs = call(tuple(inputs), tuple(aux))
    return list(outs), list(aux)


def _custom_inputs(attrs):
    return _prop_for(attrs).list_arguments()


def _custom_aux(attrs):
    return _prop_for(attrs).list_auxiliary_states()


def _custom_num_outputs(attrs):
    return len(_prop_for(attrs).list_outputs())


def _custom_output_names(attrs):
    return _prop_for(attrs).list_outputs()


def _custom_infer_shape(attrs, in_shapes):
    prop = _prop_for(attrs)
    n_in = len(prop.list_arguments())
    ins = [list(s) if s is not None else None for s in in_shapes[:n_in]]
    if any(s is None or 0 in s for s in ins):
        raise MXNetError("Custom op needs complete input shapes")
    new_in, out_shapes, aux_shapes = prop.infer_shape(ins)
    return ([tuple(s) for s in new_in],
            [tuple(s) for s in out_shapes],
            [tuple(s) for s in (aux_shapes or [])])


_register_op("Custom", inputs=_custom_inputs, aux=_custom_aux,
             num_outputs=_custom_num_outputs,
             output_names=_custom_output_names,
             infer_shape=_custom_infer_shape,
             full=_custom_forward,
             doc="Python-defined operator (op_type= selects the "
                 "registered CustomOpProp)")


# ------------------------------------------------- legacy PythonOp family
class PythonOp:
    """DEPRECATED reference API (reference: operator.py:19-130 — kept so
    pre-CustomOp scripts run): subclass, override forward/backward/
    infer_shape/list_*, call the instance on input symbols. Realized as
    a thin adapter over the CustomOp bridge (same pure_callback +
    custom_vjp plumbing); prefer CustomOp/CustomOpProp for new code."""

    _counter = [0]

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = need_top_grad

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError("Must override this")

    def forward(self, in_data, out_data):
        out_data[0][:] = in_data[0]

    def backward(self, out_grad, in_data, out_data, in_grad):
        in_grad[0][:] = 1.0

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_

    # -------------------------------------------------------- adapter
    def _register(self):
        """Wrap this instance in a CustomOpProp and register it under a
        unique name; memoized on the instance (``info_``, the
        reference's slot for this) so repeat get_symbol calls reuse one
        registration and one compiled bridge."""
        if self.info_ is not None:
            return self.info_
        outer = self
        PythonOp._counter[0] += 1
        op_type = f"_python_op_{type(self).__name__}_{self._counter[0]}"

        class _LegacyAdapter(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                ins, outs = outer._adapt(in_data), outer._adapt(out_data)
                outer.forward(in_data=ins, out_data=outs)
                for dst, r, val in zip(out_data, req, outs):
                    self.assign(dst, r or "write", val)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                og, ind, outd, ing = (outer._adapt(out_grad),
                                      outer._adapt(in_data),
                                      outer._adapt(out_data),
                                      outer._adapt(in_grad))
                outer.backward(out_grad=og, in_data=ind, out_data=outd,
                               in_grad=ing)
                for dst, r, val in zip(in_grad, req, ing):
                    self.assign(dst, r or "write", val)

        class _LegacyProp(CustomOpProp):
            def __init__(self, **_ignored):
                super().__init__(need_top_grad=outer.need_top_grad())

            def list_arguments(self):
                return outer.list_arguments()

            def list_outputs(self):
                return outer.list_outputs()

            def infer_shape(self, in_shape):
                res = outer.infer_shape(in_shape)
                ishape, oshape = res[0], res[1]
                aux = res[2] if len(res) > 2 else []
                return ishape, oshape, aux

            def create_operator(self, ctx, in_shapes, in_dtypes=None):
                return _LegacyAdapter()

        register(op_type)(_LegacyProp)
        self.info_ = op_type
        return op_type


class NumpyOp(PythonOp):
    """DEPRECATED: PythonOp whose forward/backward see numpy arrays
    (reference: operator.py NumpyOp). Mutate ``out_data[i][:]`` /
    ``in_grad[i][:]`` in place; the adapter copies the buffers back."""

    def _adapt(self, arrays):
        from .ndarray import NDArray
        # writable copies: asnumpy() views of device buffers are
        # read-only, and this API's contract is in-place mutation
        return [np.array(a.asnumpy() if isinstance(a, NDArray) else a)
                for a in arrays]

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym
        return sym.Custom(*args, op_type=self._register(), **kwargs)


class NDArrayOp(PythonOp):
    """DEPRECATED: PythonOp whose forward/backward see NDArrays
    (reference: operator.py NDArrayOp)."""

    def _adapt(self, arrays):
        return list(arrays)          # already NDArray cells

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym
        return sym.Custom(*args, op_type=self._register(), **kwargs)
