"""Ring attention: exact attention over sequence-sharded inputs.

The reference predates attention entirely (SURVEY.md §5.7: sequence scaling
by bucketing + layer placement). This module is the framework's long-context
story: the sequence axis is sharded over the mesh's ``seq`` axis and exact
softmax attention is computed blockwise while K/V shards rotate around the
ring (``lax.ppermute`` over adjacent ICI links), overlapping each block's
FLOPs with the neighbor transfer — the Ring Attention construction
(Liu et al. 2023) on XLA collectives.

Numerics: flash-style online softmax — carry running max ``m`` and
normalizer ``l`` per query block in float32; rescale the accumulator when
the max moves. Exact (not approximate) attention for any number of shards.

Also provides the single-device reference ``attention`` and a causal
variant; tests check ring == full on an 8-device CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .collectives import axis_size as _axis_size

__all__ = ["attention", "ring_attention", "ring_attention_sharded"]


def attention(q, k, v, causal=False, scale=None):
    """Plain softmax attention. q,k,v: (B, H, T, D)."""
    if scale is None:
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        precision=lax.Precision.HIGHEST,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), tk - tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32),
                     precision=lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _block_attn_update(q, k, v, m, l, acc, scale, mask=None):
    """One K/V block of online-softmax attention.

    q (B,H,Tq,D), k/v (B,H,Tk,D); m,l (B,H,Tq) float32 running max and
    normalizer; acc (B,H,Tq,D) float32 unnormalized accumulator.
    """
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        precision=lax.Precision.HIGHEST,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    m_block = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_block)
    # guard fully-masked rows (m_new == -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    correction = jnp.where(jnp.isfinite(correction), correction, 0.0)
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + \
        jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                   precision=lax.Precision.HIGHEST)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name="seq", causal=False, scale=None,
                   use_flash=None):
    """Exact attention with sequence-sharded q/k/v (call inside shard_map).

    Each device holds contiguous sequence shards (B, H, T/n, D). K/V blocks
    rotate around the ring; n_dev block updates produce the exact softmax.
    For ``causal=True``, blocks are masked by their absolute offset
    (device order along the axis = sequence order).

    The local block is computed by the Pallas flash kernel
    (rtc.flash_attention_partial) whenever the shard shape tiles —
    its unnormalized (acc, m, l) merges into the ring's online-softmax
    carry, so VMEM holds one K tile while FLOPs overlap the neighbor
    transfer. Auto-selected on the TPU backend (``MXNET_RING_FLASH=0``
    disables); on CPU the kernel runs in Pallas interpret mode, which
    only composes with ``shard_map(check_vma=False)`` (as
    ``ring_attention_sharded(use_flash=True)`` arranges), so the auto
    default there is the pure-XLA block update.
    """
    import os
    T = q.shape[2]
    if use_flash is None:
        blk = min(128, T)
        use_flash = (jax.default_backend() == "tpu"
                     and os.environ.get("MXNET_RING_FLASH", "1") != "0"
                     and T % blk == 0 and k.shape[2] == T)
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal, scale)
    return _ring_attention_xla(q, k, v, axis_name, causal, scale)


def _ring_attention_flash(q, k, v, axis_name, causal, scale):
    """Ring attention with the Pallas flash kernel as the local block.

    Forward: per ring step the kernel returns the shard's unnormalized
    (acc, m, l); the carry merge is the standard two-block online-softmax
    combine. Backward: custom_vjp recomputes through the XLA ring (the
    flash recompute strategy — the kernel itself is not differentiated).
    """
    from ..rtc import flash_attention_partial

    n_dev = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    B, H, T, D = q.shape
    if scale is None:
        scale = 1.0 / float(D) ** 0.5
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    @jax.custom_vjp
    def run(q, k, v):
        m = jnp.full((B, H, T), -jnp.inf, dtype=jnp.float32)
        l = jnp.zeros((B, H, T), dtype=jnp.float32)
        acc = jnp.zeros((B, H, T, D), dtype=jnp.float32)
        k_blk, v_blk = k, v
        for step in range(n_dev):          # static unroll, n_dev small
            # At ring step s this device holds the shard of device
            # (my_idx - s) mod n_dev. For causal masking only the
            # relative offset matters and it has exactly two cases:
            # a past-or-present shard (my_idx >= s) at static offset
            # -s*T, or a wrapped future shard — fully masked. Keeping
            # the kernel offsets static (q_off = s*T, k_off = 0) and
            # gating the wrapped case outside keeps traced values out
            # of the Pallas scalar prefetch.
            acc_s, m_s, l_s = flash_attention_partial(
                q, k_blk, v_blk, step * T if causal else 0, 0,
                causal=causal, scale=scale)
            if causal and step > 0:
                valid = (my_idx >= step).astype(jnp.float32)
                m_s = jnp.where(valid > 0, m_s, -jnp.inf)
                l_s = l_s * valid
                acc_s = acc_s * valid
            m_new = jnp.maximum(m, m_s)
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            c_old = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
            c_new = jnp.where(jnp.isfinite(m_s), jnp.exp(m_s - m_safe), 0.0)
            l = l * c_old + l_s * c_new
            acc = acc * c_old[..., None] + acc_s * c_new[..., None]
            m = m_new
            if step < n_dev - 1:
                k_blk = lax.ppermute(k_blk, axis_name, perm)
                v_blk = lax.ppermute(v_blk, axis_name, perm)
        out = acc / jnp.maximum(l[..., None], 1e-20)
        return out.astype(q.dtype)

    def fwd(q, k, v):
        return run(q, k, v), (q, k, v)

    def bwd(res, ct):
        q, k, v = res
        _, vjp_fn = jax.vjp(
            lambda a, b, c: _ring_attention_xla(a, b, c, axis_name,
                                                causal, scale), q, k, v)
        return vjp_fn(ct)

    run.defvjp(fwd, bwd)
    return run(q, k, v)


def _ring_attention_xla(q, k, v, axis_name="seq", causal=False, scale=None):
    """The pure-XLA ring (also the backward recompute path)."""
    n_dev = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / float(q.shape[-1]) ** 0.5
    B, H, T, D = q.shape

    m = jnp.full((B, H, T), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((B, H, T), dtype=jnp.float32)
    acc = jnp.zeros((B, H, T, D), dtype=jnp.float32)

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def body(step, carry):
        m, l, acc, k_blk, v_blk = carry
        src_idx = (my_idx - step) % n_dev  # which shard we hold this step
        if causal:
            q_pos = my_idx * T + jnp.arange(T)[:, None]
            k_pos = src_idx * T + jnp.arange(T)[None, :]
            mask = (q_pos >= k_pos)[None, None]
        else:
            mask = None
        m, l, acc = _block_attn_update(q, k_blk, v_blk, m, l, acc, scale,
                                       mask)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt

    carry = (m, l, acc, k, v)
    for step in range(n_dev):  # unrolled: n_dev is static, small
        carry = body(step, carry)
    m, l, acc, _, _ = carry
    out = acc / jnp.maximum(l[..., None], 1e-20)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, causal=False, seq_axis="seq",
                           use_flash=None):
    """Convenience wrapper: shard (B,H,T,D) arrays over the mesh's seq axis
    and run ring attention under shard_map.

    ``use_flash=True`` forces the Pallas-block ring even on CPU (the
    kernel then runs in interpret mode, which requires this wrapper's
    shard_map to drop vma checking)."""
    spec = P(None, None, seq_axis, None)
    kwargs = {}
    if use_flash:
        kwargs["check_vma"] = False

    from .collectives import shard_map as _shard_map
    @functools.partial(
        _shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, **kwargs)
    def run(q_s, k_s, v_s):
        return ring_attention(q_s, k_s, v_s, axis_name=seq_axis,
                              causal=causal, use_flash=use_flash)

    qs = jax.device_put(q, NamedSharding(mesh, spec))
    ks = jax.device_put(k, NamedSharding(mesh, spec))
    vs = jax.device_put(v, NamedSharding(mesh, spec))
    return run(qs, ks, vs)
