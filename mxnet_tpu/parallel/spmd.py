"""SpmdPlan: one GSPMD program over the named mesh.

The training path's central object in SPMD mode
(``Module.bind/fit(spmd=True)`` / ``MXNET_SPMD=1``): it owns the
first-class ``jax.sharding.Mesh`` (axes from ``MeshConfig`` /
``MXNET_MESH_*`` env overrides, default a 1-D ``data`` axis over the
bound contexts) and the ``PartitionSpec`` for every bound array —
data batch-sharded on ``data``, params sharded per ``placement.py``'s
lowering of ``ctx_group`` annotations onto the ``model`` axis
(replicated by default), optimizer state riding the param's spec, or
``P(data)`` over the canonical flat (n, chunk) layout once ZeRO-1 is
enabled. The executor group reads ONLY specs/shardings from this plan;
XLA's SPMD partitioner emits every collective (gradient all-reduce or
reduce-scatter, boundary all-gathers) from them — no kvstore, no
host-side reduction loop (SNIPPETS.md [2]/[3] pattern; ROADMAP item 1).

ZeRO-1 under this plan is exactly a spec change: ``enable_zero()``
flips ``state_spec`` from the param's spec to ``P(data_axis)`` and the
fused step routes the update through ``zero.apply_spec_update`` — the
same flat layout, state shapes, and bit-identical math as the
kvstore-era ``ZeroPlan``, minus the plan object threaded through the
step.

Everything that determines the traced collective structure is folded
into ``cache_token()`` so a compiled program can never be reused across
meshes or spec sets (program_cache key discipline).
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import MeshConfig, build_mesh, mesh_token
from .placement import param_partition_specs
from .zero import FlatShardLayout

__all__ = ["SpmdPlan", "active_plan", "plan_scope"]

# the plan "ambient" during a traced op dispatch: kernel_tier enters
# this scope around plan-dependent variants (the attention op's ring
# lowering reads the mesh/axes from here — the variant signature has no
# plan slot). Thread-local: traces are single-threaded per program.
_TLS = threading.local()


def active_plan():
    """The SpmdPlan armed for the op dispatch currently tracing (or
    None outside a plan scope)."""
    return getattr(_TLS, "plan", None)


@contextlib.contextmanager
def plan_scope(plan):
    """Install ``plan`` as the active plan for the duration."""
    prev = getattr(_TLS, "plan", None)
    _TLS.plan = plan
    try:
        yield plan
    finally:
        _TLS.plan = prev


class SpmdPlan:
    """Mesh + PartitionSpecs for one SPMD binding."""

    def __init__(self, mesh, param_specs=None, unsharded_tagged=None,
                 data_axis="data", model_axis="model", batch_axis=0,
                 seq_axis="seq"):
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis
        #: name -> PartitionSpec for params that are NOT fully replicated
        self.param_specs = dict(param_specs or {})
        #: name -> reason, for ctx_group-tagged params that degraded to
        #: replicated (the SH602 lint rule reads this)
        self.unsharded_tagged = dict(unsharded_tagged or {})
        self.zero = False               # flipped by enable_zero()
        self.replicated = NamedSharding(mesh, P())
        self._state_layout = None

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, symbol, devices, arg_shapes_by_name, config=None,
              batch_axis=0):
        """Plan for one binding: mesh from ``config`` (else the
        ``MXNET_MESH_*`` env overrides, else a 1-D data axis over
        ``devices``), params lowered from the symbol's ctx_group tags
        onto the model axis when one exists."""
        plan = cls(cls.build_mesh_for(devices, config),
                   batch_axis=batch_axis)
        plan.derive_param_specs(symbol, arg_shapes_by_name)
        return plan

    @staticmethod
    def build_mesh_for(devices, config=None):
        """The binding's mesh: explicit MeshConfig > MXNET_MESH_* env >
        a 1-D data axis over every bound device."""
        if config is None:
            config = MeshConfig.from_env(len(devices))
        if config is None:
            config = MeshConfig(data=len(devices))
        return build_mesh(config, devices=devices)

    def derive_param_specs(self, symbol, arg_shapes_by_name):
        """(Re)lower the symbol's ctx_group tags onto the model axis —
        called at bind time once arg shapes are known (and again on
        reshape, since divisibility is shape-dependent)."""
        self.param_specs.clear()
        self.unsharded_tagged.clear()
        n_model = self.mesh.shape.get(self.model_axis, 1)
        if n_model > 1:
            for name, (spec, reason) in param_partition_specs(
                    symbol, arg_shapes_by_name, n_model,
                    axis_name=self.model_axis).items():
                if reason:
                    self.unsharded_tagged[name] = reason
                else:
                    self.param_specs[name] = spec
        return self

    # ------------------------------------------------------------- specs
    def param_spec(self, name):
        return self.param_specs.get(name, P())

    def param_sharding(self, name):
        return NamedSharding(self.mesh, self.param_spec(name))

    def data_sharding(self, stacked=False):
        """Batch sharded over the data axis; ``stacked`` prepends the
        K-step scan axis (unsharded) before the batch axis."""
        spec = [None] * (self.batch_axis + 1)
        spec[self.batch_axis] = self.data_axis
        if stacked:
            spec = [None] + spec
        return NamedSharding(self.mesh, P(*spec))

    def data_spec_for(self, shape, stacked=False):
        """Shape-aware batch spec: ``P(data)`` on the batch axis and —
        when the mesh carries a nonempty ``seq`` axis and the next dim
        divides — ``P(data, seq)`` on (batch, sequence). This is the
        long-context activation layout (SNIPPETS [2]/[3] shape): token
        batches shard both ways, ring attention consumes the seq
        shards in place."""
        nd0 = 1 if stacked else 0
        spec = [None] * len(shape)
        b = nd0 + self.batch_axis
        if b < len(shape):
            spec[b] = self.data_axis
        n_seq = self.n_seq_shards()
        s = b + 1
        if n_seq > 1 and s < len(shape) and shape[s] >= n_seq and \
                shape[s] % n_seq == 0:
            spec[s] = self.seq_axis
        return P(*spec)

    def data_sharding_for(self, shape, stacked=False):
        return NamedSharding(self.mesh,
                             self.data_spec_for(shape, stacked=stacked))

    def state_spec(self, name):
        """Optimizer-state spec for one watched param's leaves: the
        param's own spec, or — ZeRO-1 — ``P(data_axis)`` over the flat
        (n, chunk) layout. This one method IS the ZeRO-1 toggle."""
        if self.zero:
            return P(self.data_axis)
        return self.param_spec(name)

    def state_sharding(self, name):
        return NamedSharding(self.mesh, self.state_spec(name))

    # -------------------------------------------------------------- zero
    def can_zero(self):
        return self.mesh.shape.get(self.data_axis, 1) > 1

    def enable_zero(self):
        """ZeRO-1 as a spec change: state leaves move to the flat
        (n, chunk) layout sharded over the data axis."""
        self.zero = True
        self._state_layout = FlatShardLayout(self.mesh, self.data_axis)

    @property
    def state_layout(self):
        """FlatShardLayout for state transport (checkpoints, defuse)
        when ZeRO is on; None means param-shaped state."""
        return self._state_layout

    # ------------------------------------------------------------ tokens
    def cache_token(self):
        """Program-cache token: mesh topology + the exact spec set.
        Two bindings differing in either trace different collective
        structure (the ZeRO comm plan is keyed separately, via the
        fused key's ``("comm", ...)`` token)."""
        return (mesh_token(self.mesh),
                tuple(sorted((nm, str(sp))
                             for nm, sp in self.param_specs.items())))

    def describe(self):
        """Human/lint-facing summary (diagnostics, docs examples)."""
        return {
            "mesh": {a: self.mesh.shape[a] for a in self.mesh.axis_names},
            "data_axis": self.data_axis,
            "sharded_params": {nm: str(sp)
                               for nm, sp in self.param_specs.items()},
            "replicated_tagged": dict(self.unsharded_tagged),
            "zero": self.zero,
        }

    def param_shard_fraction(self, name, shape):
        """Fraction of one param resident per device under its spec —
        the static memory planner's layout-awareness (analysis/
        memplan.py): a replicated param costs 1.0 everywhere, a
        model-axis-sharded one 1/axis_size on the sharded dim."""
        spec = self.param_spec(name)
        frac = 1.0
        for dim, axes in enumerate(tuple(spec)):
            if axes is None or dim >= len(shape):
                continue
            for ax in (axes if isinstance(axes, tuple) else (axes,)):
                n = self.mesh.shape.get(ax, 1)
                if n > 1 and shape[dim] % n == 0:
                    frac /= n
        return frac

    # ----------------------------------------------------------- placing
    def place_param(self, name, value):
        return jax.device_put(value, self.param_sharding(name))

    def n_data_shards(self):
        return int(self.mesh.shape.get(self.data_axis, 1))

    def n_seq_shards(self):
        return int(self.mesh.shape.get(self.seq_axis, 1))

    def n_devices(self):
        return int(np.prod([self.mesh.shape[a]
                            for a in self.mesh.axis_names]))
