"""Collective wrappers.

The reference's communication backend is copy+sum through the engine
(intra-node, comm.h) and ps-lite ZPush/ZPull (inter-node, kvstore_dist.h).
Here every collective is an XLA collective over the mesh: these wrappers
are the thin naming layer used inside ``shard_map``-ped functions (outside
jit, they fall back to host equivalents so the same code runs everywhere).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def shard_map(f=None, **kwargs):
    """Version-portable ``jax.shard_map``.

    Newer jax exposes ``jax.shard_map`` (with ``check_vma``); 0.4.x only
    has ``jax.experimental.shard_map.shard_map`` (with ``check_rep``).
    Accepts both decorator-factory (``@shard_map(mesh=...)``) and direct
    (``shard_map(fn, mesh=...)``) call styles and translates the
    vma/rep-checking knob to whatever the installed jax understands.
    """
    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return lambda fn: impl(fn, **kwargs)
    return impl(f, **kwargs)


def axis_size(axis_name):
    """Size of a mesh axis from inside shard_map (version-portable:
    ``lax.axis_size`` only exists in newer jax; ``psum(1, axis)`` is the
    classic spelling and folds to a compile-time constant)."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def all_reduce(x, axis_name="data", op="sum"):
    """psum/pmean/pmax over a mesh axis (inside shard_map/jit)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown op {op}")


def all_gather(x, axis_name="data", axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="data", axis=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                            tiled=True)


def ppermute(x, axis_name, perm):
    """Neighbor exchange — the primitive under ring attention / pipeline."""
    return lax.ppermute(x, axis_name, perm)


def barrier(name="barrier"):
    """Host-level barrier across processes (DCN)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
