"""Parallelism layer: device meshes, sharding, collectives.

The reference's parallelism is DP over a parameter server + manual layer
placement (SURVEY.md §2.4); this framework is mesh-native: every form of
parallelism is a sharding of one jitted program over a
``jax.sharding.Mesh`` — data (dp), tensor (tp), sequence (sp), pipeline
(pp stages as mesh axis), expert (ep) — with XLA inserting the collectives
over ICI/DCN (psum/all_gather/reduce_scatter/ppermute).
"""
from .mesh import (MeshConfig, build_mesh, current_mesh, mesh_scope,
                   data_sharding, replicated, shard, mesh_token,
                   DEFAULT_AXES)
from .collectives import (all_reduce, all_gather, reduce_scatter, ppermute,
                          barrier, shard_map)
from .zero import ZeroPlan, FlatShardLayout, apply_spec_update
from .spmd import SpmdPlan
