"""ZeRO stage 1: sharded optimizer updates inside the jitted train step.

The data-parallel baseline all-reduces every gradient and then runs the
identical optimizer update on every device — N redundant copies of the
update FLOPs and, worse, N redundant copies of the optimizer state
(Adam doubles parameter memory *per device*). ZeRO stage 1
(Rajbhandari et al., SC 2020) replaces that with reduce-scatter +
shard-update + all-gather: each device owns 1/N of every parameter's
flat buffer, receives only its shard of the summed gradient, updates
only its shard of the parameters and optimizer state, and the updated
parameter shards are all-gathered back to replicated. Optimizer-state
memory drops N-fold; total collective bytes match the all-reduce
(reduce-scatter + all-gather = one all-reduce's two phases, split
around the update).

Realization here: the fused/scan train step stays ONE jitted SPMD
program. The gradient/parameter are reshaped to a ``(n_shard, chunk)``
padded flat view pinned to a mesh axis with
``lax.with_sharding_constraint`` — the XLA SPMD partitioner then
materializes the vjp gradient *directly as a reduce-scatter* (the
all-reduce it would have inserted sinks into the sharded consumer),
runs the elementwise update shard-locally, and turns the replicated (or
model-sharded, under the SPMD path) constraint on the new weights into
the all-gather. Because the collectives live inside the program, XLA's
latency-hiding scheduler overlaps the gradient reduce-scatter of late
layers with the still-running backward of early layers — the in-program
form of comm/compute overlap (docs/performance.md).

Two consumers share this module:

* the kvstore-era fused path keeps :class:`ZeroPlan` — layout + apply
  in one object, selected by ``Module.fit(zero_stage=1)``;
* the SPMD path (``parallel/spmd.py``) treats ZeRO-1 as a
  *PartitionSpec change on the optimizer-state leaves*: the plan's
  ``state_spec`` switches from the param's spec to ``P(data_axis)``
  over the canonical flat layout, and the fused step applies it through
  :func:`apply_spec_update` — no plan object threaded through the step,
  just specs. :class:`FlatShardLayout` carries the layout/transport
  half (state init, checkpoint export/import) for both.

The update must be elementwise over (w, g, state) for the flat-shard
view to be exact — true for the fused SGD/momentum/Adam plans
(``Optimizer.fused_update_elementwise``); non-elementwise optimizers
keep the replicated plan. Shard-local math is bit-identical to the
replicated update (same reduced values, same scalar ops), pinned by
tests/test_zero.py and tests/test_spmd.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ZeroPlan", "FlatShardLayout", "flat_shards", "unflat_shards",
           "apply_spec_update"]


# ------------------------------------------------------- flat-shard views
def flat_shards(x, n):
    """(n, chunk) zero-padded flat view (traced or concrete).

    The padding MUST be ``jnp.pad``, not a ``jnp.concatenate`` with a
    zeros tensor: on a multi-axis mesh the XLA SPMD partitioner
    (jax 0.4.37) mis-reshards concatenate-fed values when the result is
    pinned to one axis — each element comes back multiplied by the size
    of the other axes (verified: pad partitions correctly, concat
    doubles on a (data=4, model=2) mesh).
    """
    size = int(np.prod(x.shape)) if x.shape else 1
    chunk = -(-size // n)                   # ceil(size / n)
    pad = chunk * n - size
    f = jnp.ravel(x)
    if pad:
        f = jnp.pad(f, (0, pad))
    return f.reshape(n, -1)


def unflat_shards(f, shape):
    """Inverse of :func:`flat_shards` (drops the zero padding)."""
    size = int(np.prod(shape)) if shape else 1
    flat = jnp.ravel(f)
    if flat.shape[0] != size:
        flat = flat[:size]
    return flat.reshape(shape)


def apply_spec_update(update, w, g, s, lr, wd, mesh, state_spec,
                      out_spec=None):
    """One elementwise optimizer update on 1/n flat shards, driven by
    PartitionSpecs alone (the SPMD path's ZeRO-1).

    ``state_spec`` names the mesh axis the (n, chunk) flat layout shards
    over (its first entry — e.g. ``P('data')``); ``out_spec`` is the
    updated parameter's own spec (``P()`` replicates = the all-gather;
    a model-sharded param keeps its spec). ``s`` is the persistent
    state pytree already in (n, chunk) sharded form. Returns (new_w in
    the original shape, new_s still flat-sharded).
    """
    axis = state_spec[0]
    n = mesh.shape[axis]
    sharded = NamedSharding(mesh, state_spec)
    shape = w.shape
    wf = jax.lax.with_sharding_constraint(flat_shards(w, n), sharded)
    # the constraint below is where the partitioner turns the vjp
    # gradient's pending all-reduce into a reduce-scatter
    gf = jax.lax.with_sharding_constraint(flat_shards(g, n), sharded)
    new_wf, new_s = update(wf, gf, s, lr, wd)
    new_s = jax.tree.map(
        lambda x: jax.lax.with_sharding_constraint(x, sharded), new_s)
    # constraint on the updated shards = the all-gather back to the
    # parameter's own layout
    out_sharding = NamedSharding(mesh, out_spec if out_spec is not None
                                 else P())
    new_w = jax.lax.with_sharding_constraint(
        unflat_shards(new_wf, shape), out_sharding)
    return new_w, new_s


class FlatShardLayout:
    """(n, chunk) flat-shard state layout over one mesh axis: creation,
    checkpoint transport, and defuse projections — everything about the
    layout EXCEPT the in-program update (ZeroPlan.apply or
    :func:`apply_spec_update`)."""

    def __init__(self, mesh, axis="data"):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.spec = P(axis)
        self.sharded = NamedSharding(mesh, self.spec)
        self.replicated = NamedSharding(mesh, P())

    # ------------------------------------------------------------ layout
    def _chunk(self, size):
        return -(-size // self.n)           # ceil(size / n)

    def _flat(self, x):
        return flat_shards(x, self.n)

    def _unflat(self, f, shape):
        return unflat_shards(f, shape)

    # -------------------------------------------------------------- state
    def init_state(self, init_state, w):
        """Optimizer state for one param, created directly in the
        (n, chunk) sharded layout — each device materializes only its
        1/n slice (the N-fold state-memory cut of ZeRO-1)."""
        wf = self._flat(jnp.asarray(w))
        state = init_state(wf)
        return jax.tree.map(
            lambda x: jax.device_put(x, self.sharded), state)

    def export_state(self, state, shape):
        """Host-format (param-shaped numpy) view of a sharded state
        pytree — the checkpoint representation, identical to what the
        replicated plan would have saved."""
        return jax.tree.map(
            lambda x: np.asarray(self._unflat(jnp.asarray(x), shape)),
            state)

    def import_state(self, state_host):
        """Inverse of ``export_state``: param-shaped host arrays back to
        the (n, chunk) sharded device layout."""
        return jax.tree.map(
            lambda x: jax.device_put(self._flat(jnp.asarray(np.asarray(x))),
                                     self.sharded),
            state_host)

    def device_state_to_param_shape(self, state, shape):
        """Device-side unflatten (for defusing into the staged updater)."""
        return jax.tree.map(
            lambda x: self._unflat(jnp.asarray(x), shape), state)


class ZeroPlan(FlatShardLayout):
    """Flat-shard transform over one mesh axis for optimizer updates
    (layout + in-program apply, the kvstore-era fused path's plan)."""

    def describe(self):
        """Ordered in-program collective sequence one parameter update
        traces under this plan — what the collective-order analysis
        pass (analysis rule CO302) and diagnostics render. The order is
        structural (baked into the traced program), hence identical on
        every worker by construction."""
        return (("reduce_scatter", self.axis, self.n),
                ("all_gather", self.axis, self.n))

    # ------------------------------------------------------------- update
    def apply(self, update, w, g, s, lr, wd):
        """Run one elementwise optimizer update on 1/n shards.

        ``w``/``g`` are full (replicated-layout) traced arrays; ``s`` is
        the persistent state pytree already in (n, chunk) sharded form
        (see ``init_state``). Returns (new_w in the original shape,
        new_s still flat-sharded)."""
        return apply_spec_update(update, w, g, s, lr, wd,
                                 self.mesh, self.spec)
