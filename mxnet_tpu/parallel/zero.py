"""ZeRO stage 1: sharded optimizer updates inside the jitted train step.

The data-parallel baseline all-reduces every gradient and then runs the
identical optimizer update on every device — N redundant copies of the
update FLOPs and, worse, N redundant copies of the optimizer state
(Adam doubles parameter memory *per device*). ZeRO stage 1
(Rajbhandari et al., SC 2020) replaces that with reduce-scatter +
shard-update + all-gather: each device owns 1/N of every parameter's
flat buffer, receives only its shard of the summed gradient, updates
only its shard of the parameters and optimizer state, and the updated
parameter shards are all-gathered back to replicated. Optimizer-state
memory drops N-fold; total collective bytes match the all-reduce
(reduce-scatter + all-gather = one all-reduce's two phases, split
around the update).

Realization here: the fused/scan train step stays ONE jitted SPMD
program. ``ZeroPlan.apply`` reshapes each gradient/parameter to a
``(n_shard, chunk)`` padded flat view and pins it to the mesh's data
axis with ``lax.with_sharding_constraint`` — the XLA SPMD partitioner
then materializes the vjp gradient *directly as a reduce-scatter*
(the all-reduce it would have inserted sinks into the sharded
consumer), runs the elementwise update shard-locally, and turns the
replicated constraint on the new weights into the all-gather. Because
the collectives live inside the program, XLA's latency-hiding
scheduler overlaps the gradient reduce-scatter of late layers with the
still-running backward of early layers — the in-program form of
comm/compute overlap (docs/performance.md).

The update must be elementwise over (w, g, state) for the flat-shard
view to be exact — true for the fused SGD/momentum/Adam plans
(``Optimizer.fused_update_elementwise``); non-elementwise optimizers
keep the replicated plan. Shard-local math is bit-identical to the
replicated update (same reduced values, same scalar ops), pinned by
tests/test_zero.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ZeroPlan"]


class ZeroPlan:
    """Flat-shard transform over one mesh axis for optimizer updates."""

    def __init__(self, mesh, axis="data"):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.sharded = NamedSharding(mesh, P(axis))
        self.replicated = NamedSharding(mesh, P())

    def describe(self):
        """Ordered in-program collective sequence one parameter update
        traces under this plan — what the collective-order analysis
        pass (analysis rule CO302) and diagnostics render. The order is
        structural (baked into the traced program), hence identical on
        every worker by construction."""
        return (("reduce_scatter", self.axis, self.n),
                ("all_gather", self.axis, self.n))

    # ------------------------------------------------------------ layout
    def _chunk(self, size):
        return -(-size // self.n)           # ceil(size / n)

    def _flat(self, x):
        """(n, chunk) zero-padded flat view (traced or concrete)."""
        size = int(np.prod(x.shape)) if x.shape else 1
        pad = self._chunk(size) * self.n - size
        f = jnp.ravel(x)
        if pad:
            f = jnp.concatenate([f, jnp.zeros((pad,), f.dtype)])
        return f.reshape(self.n, -1)

    def _unflat(self, f, shape):
        size = int(np.prod(shape)) if shape else 1
        flat = jnp.ravel(f)
        if flat.shape[0] != size:
            flat = flat[:size]
        return flat.reshape(shape)

    # ------------------------------------------------------------- update
    def apply(self, update, w, g, s, lr, wd):
        """Run one elementwise optimizer update on 1/n shards.

        ``w``/``g`` are full (replicated-layout) traced arrays; ``s`` is
        the persistent state pytree already in (n, chunk) sharded form
        (see ``init_state``). Returns (new_w in the original shape,
        new_s still flat-sharded)."""
        shape = w.shape
        wf = jax.lax.with_sharding_constraint(self._flat(w), self.sharded)
        # the constraint below is where the partitioner turns the vjp
        # gradient's pending all-reduce into a reduce-scatter
        gf = jax.lax.with_sharding_constraint(self._flat(g), self.sharded)
        new_wf, new_s = update(wf, gf, s, lr, wd)
        new_s = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, self.sharded),
            new_s)
        # replicated constraint on the updated shards = the all-gather
        new_wf = jax.lax.with_sharding_constraint(new_wf, self.replicated)
        return self._unflat(new_wf, shape), new_s

    # -------------------------------------------------------------- state
    def init_state(self, init_state, w):
        """Optimizer state for one param, created directly in the
        (n, chunk) sharded layout — each device materializes only its
        1/n slice (the N-fold state-memory cut of ZeRO-1)."""
        wf = self._flat(jnp.asarray(w))
        state = init_state(wf)
        return jax.tree.map(
            lambda x: jax.device_put(x, self.sharded), state)

    def export_state(self, state, shape):
        """Host-format (param-shaped numpy) view of a sharded state
        pytree — the checkpoint representation, identical to what the
        replicated plan would have saved."""
        return jax.tree.map(
            lambda x: np.asarray(self._unflat(jnp.asarray(x), shape)),
            state)

    def import_state(self, state_host):
        """Inverse of ``export_state``: param-shaped host arrays back to
        the (n, chunk) sharded device layout."""
        return jax.tree.map(
            lambda x: jax.device_put(self._flat(jnp.asarray(np.asarray(x))),
                                     self.sharded),
            state_host)

    def device_state_to_param_shape(self, state, shape):
        """Device-side unflatten (for defusing into the staged updater)."""
        return jax.tree.map(
            lambda x: self._unflat(jnp.asarray(x), shape), state)
