"""Device-mesh management.

Replaces the reference's ``group2ctx`` + kvstore-type device topology
(reference: graph_executor.cc:242-331 AssignContext, kvstore.cc:17-45) with
one first-class object: a named ``jax.sharding.Mesh``. Canonical axes:

  * ``data``   — batch sharding (dp); gradient psum rides ICI
  * ``model``  — tensor parallelism (tp); matmul-sharded layers
  * ``seq``    — sequence/context parallelism (sp); ring attention
  * ``pipe``   — pipeline stages (pp)
  * ``expert`` — expert parallelism (ep)

``build_mesh`` lays axes out so that the fastest-varying (most-communicating)
axis maps to adjacent devices — on a TPU slice that keeps tp/sp collectives
on nearest-neighbor ICI links (the scaling-book recipe: mesh ordering is the
physical layout declaration).
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXES = ("data", "model", "seq", "pipe", "expert")

#: env knobs overriding per-axis mesh sizes (docs/env_var.md)
ENV_AXIS_VARS = {a: f"MXNET_MESH_{a.upper()}" for a in DEFAULT_AXES}

_LOCAL = threading.local()


@dataclass
class MeshConfig:
    """Axis-size spec; unlisted axes get size 1 and are dropped."""
    data: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1
    extras: dict = field(default_factory=dict)

    def sizes(self):
        base = {"data": self.data, "model": self.model, "seq": self.seq,
                "pipe": self.pipe, "expert": self.expert}
        base.update(self.extras)
        return {k: v for k, v in base.items() if v > 1}

    @classmethod
    def from_env(cls, n_devices=None):
        """MeshConfig from the MXNET_MESH_* env overrides, or None when
        no axis is set. Unset axes default to 1; a mesh built from the
        result therefore consumes exactly the product of the set axes
        (callers typically default the data axis to the device count
        when no override is present)."""
        sizes = {}
        for axis, var in ENV_AXIS_VARS.items():
            raw = os.environ.get(var, "")
            if raw:
                try:
                    sizes[axis] = int(raw)
                except ValueError:
                    raise ValueError(f"{var}={raw!r} is not an integer")
        if not sizes:
            return None
        if n_devices is not None and "data" not in sizes:
            other = int(np.prod(list(sizes.values())))
            if other and n_devices % other == 0 and n_devices // other > 1:
                sizes["data"] = n_devices // other
        return cls(**sizes)


def build_mesh(config=None, devices=None, **axis_sizes):
    """Build a Mesh. ``build_mesh(data=4, model=2)`` or from a MeshConfig.

    Axis order follows DEFAULT_AXES with ``model``/``seq`` innermost
    (fastest-varying) so tensor/sequence collectives ride adjacent ICI
    links while the data axis spans the slower outer links/DCN.
    """
    if config is not None:
        sizes = config.sizes()
    else:
        sizes = {k: v for k, v in axis_sizes.items() if v > 1}
    if devices is None:
        devices = jax.devices()
    if not sizes:
        sizes = {"data": len(devices)}
    total = int(np.prod(list(sizes.values())))
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    devices = devices[:total]
    # order axes: outer = data/pipe (less chatty), inner = model/seq/expert
    order = [a for a in ("pipe", "data", "expert", "seq", "model")
             if a in sizes] + [a for a in sizes if a not in DEFAULT_AXES]
    shape = [sizes[a] for a in order]
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, tuple(order))


def mesh_scope(mesh):
    """Context manager installing a current mesh."""
    class _Scope:
        def __enter__(self):
            stack = getattr(_LOCAL, "stack", None)
            if stack is None:
                _LOCAL.stack = []
            _LOCAL.stack.append(mesh)
            return mesh

        def __exit__(self, *a):
            _LOCAL.stack.pop()
    return _Scope()


def current_mesh():
    stack = getattr(_LOCAL, "stack", None)
    if stack:
        return stack[-1]
    return None


def mesh_token(mesh):
    """Stable program-cache token naming a mesh's topology: platform,
    axis layout, and the exact device assignment. Two bindings whose
    meshes differ in ANY of these must never share a compiled program —
    traced collective structure (psum/reduce-scatter shapes, ZeRO shard
    counts) bakes the topology in (docs/performance.md; the PR-7
    program-cache hazard fix)."""
    devs = tuple(int(getattr(d, "id", -1)) for d in mesh.devices.flat)
    plat = getattr(next(iter(mesh.devices.flat)), "platform", "?")
    return ("mesh", plat, tuple(zip(mesh.axis_names,
                                    (mesh.shape[a]
                                     for a in mesh.axis_names))), devs)


def data_sharding(mesh, batch_axis=0):
    """NamedSharding splitting `batch_axis` over the 'data' mesh axis."""
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = "data"
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard(arr, mesh, spec):
    """Place an array with a PartitionSpec on the mesh."""
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)
                                             if isinstance(spec, (tuple,
                                                                  list))
                                             else spec))
