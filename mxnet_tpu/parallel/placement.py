"""Model parallelism: lower ctx_group annotations onto mesh shardings.

Reference mechanism (reference: src/executor/graph_executor.cc:242-331
``AssignContext``): ``with AttrScope(ctx_group='g')`` tags nodes, bind's
``group2ctx={'g': ctx}`` maps groups to devices, the PlaceDevice pass
pins ops and inserts ``_CrossDeviceCopy`` at boundaries
(example/model-parallel-lstm/lstm.py:48-112).

TPU-native lowering — there is no per-op device pinning in SPMD/XLA;
the mesh equivalent is *parameter sharding*: the devices named by
``group2ctx`` become a 1-D ``model`` mesh axis, every parameter tagged
with a ctx_group is sharded across that axis along the dimension its
consumer makes safe (a matmul-like op's weight shards on its OUTPUT dim,
never a contraction dim), and activations crossing a group boundary get
a replication constraint (``lax.with_sharding_constraint`` — the
compiler inserts the all-gather that replaces ``_CrossDeviceCopy``).
XLA then partitions one program over all the devices, which both
distributes the memory the way the reference's layer placement did and
overlaps the per-group compute.

Numerics are unchanged by construction — shardings never alter values —
which is exactly the reference's contract for moving a model from one
GPU to several.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ModelParallelPlan", "build_plan", "param_partition_specs"]


class ModelParallelPlan:
    """Shardings derived from (symbol, group2ctx) for one executor."""

    def __init__(self, mesh, param_shardings, boundary_nodes, replicated):
        self.mesh = mesh
        self.param_shardings = param_shardings   # arg name -> NamedSharding
        self.boundary_nodes = boundary_nodes     # id(node) -> NamedSharding
        self.replicated = replicated             # NamedSharding, P()

    def place(self, name, value):
        """Device-put an arg/aux value according to the plan."""
        sh = self.param_shardings.get(name, self.replicated)
        return jax.device_put(value, sh)

    def constrain(self, node_id, arrays):
        """Apply the boundary (cross-group) replication constraint."""
        sh = self.boundary_nodes.get(node_id)
        if sh is None:
            return arrays
        return [jax.lax.with_sharding_constraint(a, sh) for a in arrays]


# consumer-aware shard axes: the OUTPUT dimension of each matmul-like
# op's weight — sharding a contraction dim would force a partial-sum
# collective on every apply (op, input slot) -> axis to shard
_PREFERRED_AXIS = {
    ("FullyConnected", "weight"): 0, ("FullyConnected", "bias"): 0,
    ("Convolution", "weight"): 0, ("Convolution", "bias"): 0,
    ("Deconvolution", "weight"): 1, ("Deconvolution", "bias"): 0,
    ("Embedding", "weight"): 1,
}


def _shard_spec(shape, n_dev, consumer=None, axis_name="model"):
    """Pick the shard axis from how the param is consumed.

    Known matmul-like consumers shard their weight's output dimension;
    1-D params (per-channel vectors) shard elementwise; anything else is
    replicated — never guess at a 2-D+ tensor's contraction structure.
    Returns (PartitionSpec, reason) where reason is non-empty exactly
    when the spec degraded to replicated.
    """
    axis = _PREFERRED_AXIS.get(consumer) if consumer else None
    if axis is None and len(shape) == 1:
        axis = 0
    if axis is None:
        return P(), ("no consumer with a known output dimension "
                     "(conflicting or unknown matmul-like consumers)")
    if axis >= len(shape):
        return P(), f"preferred axis {axis} out of range for {shape}"
    if shape[axis] % n_dev != 0 or shape[axis] < n_dev:
        return P(), (f"dim {axis} of {shape} is not divisible by the "
                     f"{n_dev}-way {axis_name!r} axis")
    spec = [None] * len(shape)
    spec[axis] = axis_name
    return P(*spec), ""


def param_partition_specs(symbol, arg_shapes_by_name, n_dev,
                          axis_name="model"):
    """ctx_group-tagged params -> {name: (PartitionSpec, reason)}.

    The spec-derivation core shared by ``build_plan`` (legacy 1-D model
    mesh from group2ctx devices) and the SPMD path (``parallel/spmd.py``
    lowering onto a named mesh's ``model`` axis): each tagged param
    shards along the output dimension its consumers agree on, and
    degrades to replicated — with the reason recorded, surfaced by the
    SH602 lint rule — when no safe axis exists.
    """
    nodes = symbol._topo_nodes()

    # every consumer of each tagged param, with its input slot
    consumers_of = {}
    for node in nodes:
        if node.is_variable:
            continue
        in_names = node.opdef().input_names(node.attrs)
        for (inp, _), slot in zip(node.inputs, in_names):
            if inp.is_variable:
                consumers_of.setdefault(id(inp), []).append(
                    (node.op, slot))

    def _resolve_consumer(pid):
        """Agree on one preferred axis across all consumers; a tied param
        whose consumers want different axes replicates (sharding either
        way would put a contraction dim on the wire for one of them)."""
        axes = {_PREFERRED_AXIS.get(c) for c in consumers_of.get(pid, [])}
        axes.discard(None)
        if len(axes) != 1:
            return None
        for c in consumers_of[pid]:
            if _PREFERRED_AXIS.get(c) is not None:
                return c
        return None

    specs = {}
    for node in nodes:
        if not node.is_variable or not node._extra.get("ctx_group"):
            continue
        shape = arg_shapes_by_name.get(node.name)
        if shape is None:
            continue
        specs[node.name] = _shard_spec(
            shape, n_dev, consumer=_resolve_consumer(id(node)),
            axis_name=axis_name)
    return specs


def build_plan(symbol, group2ctx, arg_shapes_by_name):
    """Build a ModelParallelPlan, or None when group2ctx is empty/unused.

    ``group2ctx``: dict group-name -> Context; the distinct devices (in
    group-name order) form the model axis. Nodes/params without a
    ctx_group ride along replicated.
    """
    if not group2ctx:
        return None
    nodes = symbol._topo_nodes()
    grouped = [n for n in nodes if n._extra.get("ctx_group")]
    if not grouped:
        return None

    devices, seen = [], set()
    for g in sorted(group2ctx):
        dev = group2ctx[g].jax_device()
        if id(dev) not in seen:
            seen.add(id(dev))
            devices.append(dev)
    mesh = Mesh(np.array(devices), ("model",))
    n_dev = len(devices)
    replicated = NamedSharding(mesh, P())

    param_shardings = {
        name: NamedSharding(mesh, spec)
        for name, (spec, _reason) in param_partition_specs(
            symbol, arg_shapes_by_name, n_dev).items()}

    # cross-group edges: the producer's outputs must be gathered before a
    # different group consumes them (the _CrossDeviceCopy analog)
    boundary = {}
    for node in nodes:
        g_self = node._extra.get("ctx_group")
        for inp, _ in node.inputs:
            g_in = inp._extra.get("ctx_group")
            if g_in is not None and g_in != g_self and not inp.is_variable:
                boundary[id(inp)] = replicated
    return ModelParallelPlan(mesh, param_shardings, boundary, replicated)
