"""Device context abstraction.

The reference models devices as ``Context(device_type, device_id)`` with
``mx.cpu()`` / ``mx.gpu(i)`` (reference: python/mxnet/context.py,
include/mxnet/base.h Context struct). Here a Context wraps a JAX device:
``mx.cpu()`` -> the host CPU backend, ``mx.tpu(i)`` -> TPU chip *i*.
``mx.gpu`` is kept as a compatibility alias for the accelerator so
reference scripts run unchanged on TPU.

Unlike the reference there is no stream/device-ordinal plumbing to do —
XLA owns placement — so a Context is a value object used for:
  * selecting where NDArray buffers live (``jax.device_put``),
  * the ``with ctx:`` current-context scope,
  * the ``group2ctx``/model-parallel mapping onto mesh axes (see
    mxnet_tpu/parallel/).
"""
from __future__ import annotations

import logging
import os
import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus"]

_compilation_cache_wired = False


def _init_compilation_cache():
    """Wire the persistent XLA compilation cache at context init.

    ``MXNET_COMPILATION_CACHE_DIR`` names an on-disk cache of compiled
    XLA executables (jax's ``jax_compilation_cache_dir``): a warm
    restart of the same training program skips its XLA compiles
    entirely — the third leg of the dispatch/compile amortization layer
    next to the process-wide program cache (program_cache.py) and the
    K-step scan dispatch. ``MXNET_COMPILATION_CACHE_MIN_COMPILE_SECS``
    optionally lowers jax's minimum-compile-time persistence threshold
    (set 0 to persist even sub-second programs). Runs once; a user who
    already configured jax's cache (e.g. bench.py's repo-local default
    via ``JAX_COMPILATION_CACHE_DIR``) is left untouched.
    """
    global _compilation_cache_wired
    if _compilation_cache_wired:
        return
    _compilation_cache_wired = True
    path = os.environ.get("MXNET_COMPILATION_CACHE_DIR")
    if not path:
        return
    try:
        if jax.config.jax_compilation_cache_dir:
            return          # already configured (env/bench/user code)
    except AttributeError:
        pass
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        min_secs = os.environ.get("MXNET_COMPILATION_CACHE_MIN_COMPILE_SECS")
        if min_secs is not None:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_secs))
    except Exception as exc:   # cache is an optimization, never fatal
        logging.warning("persistent compilation cache unavailable "
                        "(%s): %s", path, exc)


class Context:
    """Device context. reference: python/mxnet/context.py:15-120."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
    _local = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise ValueError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id

    @property
    def device_type(self):
        return self.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- JAX mapping ------------------------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete jax.Device.

        Multi-process: a Context names a device of THIS process —
        ``jax.devices()`` would enumerate the whole job's devices and
        hand other processes' (non-addressable) ones to low ids."""
        _init_compilation_cache()
        if self.device_type in ("cpu", "cpu_pinned"):
            devs = _local_cpu_devices()
        else:
            # "gpu" is a compat alias for the accelerator backend: on a TPU
            # machine it resolves to TPU chips so reference scripts using
            # mx.gpu(i) run unchanged.
            devs = _accelerator_devices()
            if not devs:
                devs = _local_cpu_devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def __enter__(self):
        if not hasattr(Context._local, "stack"):
            Context._local.stack = []
        Context._local.stack.append(self)
        return self

    def __exit__(self, *args):
        Context._local.stack.pop()


def _local_cpu_devices():
    """THIS process's CPU devices. ``jax.local_devices()`` with no
    backend only enumerates the default backend, so on an accelerator
    machine the cpu devices must be asked for explicitly."""
    try:
        return jax.local_devices(backend="cpu")
    except RuntimeError:
        return jax.devices("cpu")


def _accelerator_devices():
    # this process's chips only (multi-process: remote chips are
    # non-addressable and must not be bind targets)
    devs = [d for d in jax.local_devices() if d.platform not in ("cpu",)]
    return devs


def current_context():
    stack = getattr(Context._local, "stack", None)
    if stack:
        return stack[-1]
    return Context("cpu", 0)


def cpu(device_id=0):
    """Return a CPU context. reference: python/mxnet/context.py cpu()."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Accelerator context (compat alias -> TPU on TPU hosts)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """TPU context — the native accelerator of this framework."""
    return Context("tpu", device_id)


def num_gpus():
    """Number of accelerator devices visible (compat helper)."""
    return len(_accelerator_devices())
