"""RNN cell library: symbolic-unrolling recurrent cells.

reference: python/mxnet/rnn/rnn_cell.py (962 LoC): RNNCell/LSTMCell/GRUCell
compose Symbol graphs per time step; ``FusedRNNCell`` wraps the cuDNN fused
RNN op with a packed parameter blob; pack/unpack converts between fused and
unfused layouts for checkpoint compatibility (rnn-inl.h:30-67 layout).

TPU-native notes: unrolled cells compile to one XLA program where matmuls
batch onto the MXU; ``FusedRNNCell`` here unrolls the same math (XLA fuses
across steps — on TPU there is no cuDNN kernel to call, and `lax.scan`
lowering is used by the imperative RNN op in ops/rnn_op.py) while keeping
the packed-parameter layout contract so checkpoints interoperate.
"""
from __future__ import annotations

import numpy as np

from .. import symbol
from .. import ndarray as nd
from ..ndarray import NDArray, concatenate
from ..base import MXNetError

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell",
           "ModifierCell"]


class RNNParams:
    """Container for cell weights. reference: rnn_cell.py:21-60."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.var(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """reference: rnn_cell.py:63-200."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_shape(self):
        raise NotImplementedError()

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """reference: rnn_cell.py:159 — default initial states are ZERO
        symbols (not arguments) with partial shape (0, H); the unknown
        batch dim resolves during the fixpoint InferShape pass and the
        executor bakes the concrete shape at bind."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called "\
            "directly. Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            kw = dict(kwargs)
            # declare the partial state shape (0 = unknown batch) so the
            # fixpoint InferShape pass can fill it (reference convention)
            if info and "shape" in info and "shape" not in kw:
                kw["shape"] = info["shape"]
            state = func(name=f"{self._prefix}begin_state_"
                         f"{self._init_counter}", **kw)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split packed fused blob -> per-gate dict. Default: identity."""
        return args.copy()

    def pack_weights(self, args):
        return args.copy()

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Unroll over `length` steps. reference: rnn_cell.py:140-200."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.var(f"{input_prefix}t{i}_data")
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1, \
                "unroll doesn't allow grouped symbol as input. Pass a list "\
                "of symbols instead."
            inputs = list(symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1))
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()

        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=axis) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, states

    # internal
    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell. reference: rnn_cell.py:203-250."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name=f"{name}h2h")
        output = self._get_activation(i2h + h2h, self._activation,
                                      name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell. reference: rnn_cell.py:253-330. Gate order i,f,c,o
    matches the fused layout (rnn-inl.h:30-67)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def state_shape(self):
        return [(0, self._num_hidden), (0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name=f"{name}h2h")
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name=f"{name}slice")
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name=f"{name}i")
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name=f"{name}f")
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name=f"{name}c")
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name=f"{name}o")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh",
                                              name=f"{name}state")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell. reference: rnn_cell.py:333-400. Gate order r,z,o matches
    the fused layout."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        seq_idx = self._counter
        name = f"{self._prefix}t{seq_idx}_"
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name=f"{name}h2h")
        i2h_r, i2h_z, i2h = symbol.SliceChannel(
            i2h, num_outputs=3, name=f"{name}i2h_slice")
        h2h_r, h2h_z, h2h = symbol.SliceChannel(
            h2h, num_outputs=3, name=f"{name}h2h_slice")
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name=f"{name}r_act")
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name=f"{name}z_act")
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type="tanh", name=f"{name}h_act")
        # cuDNN/reference convention: h' = (1-z)*n + z*h_prev
        next_h = next_h_tmp + update_gate * (prev_state_h - next_h_tmp)
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN with a packed parameter blob.

    reference: rnn_cell.py:403-560 wrapping the cuDNN RNN op
    (cudnn_rnn-inl.h). Here ``unroll`` expands to per-layer unfused cells
    reading slices of the packed blob — numerically identical, and XLA
    fuses the unrolled steps (the MXU-friendly path). The packed layout
    (all i2h weights, then h2h, per layer/direction, then biases) follows
    rnn-inl.h:30-67 for pack/unpack checkpoint compat.
    """

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._directions = ["l", "r"] if bidirectional else ["l"]
        self._parameter = self.params.get("parameters")

    @property
    def state_info(self):
        b = self._bidirectional + 1
        n = (self._mode == "lstm") + 1
        return [{"shape": (b * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"} for _ in range(n)]

    @property
    def state_shape(self):
        b = self._bidirectional + 1
        n = (self._mode == "lstm") + 1
        return [(b * self._num_layers, 0, self._num_hidden)] * n

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _weight_layout(self, li):
        """Traversal order of (name, shape) blocks in the packed blob.

        Gate blocks are contiguous within each i2h/h2h matrix, so the blob
        slices directly into the FUSED per-layer weights the unfused cells
        consume (lstm_l0_i2h_weight of (m*H, in) etc.) — layout per
        reference rnn-inl.h:30-67: all weights (layer-major, i2h then h2h),
        then all biases in the same order.
        """
        lh = self._num_hidden
        m = self._num_gates
        b = len(self._directions)
        blocks = []
        for layer in range(self._num_layers):
            for direction in self._directions:
                in_dim = li if layer == 0 else b * lh
                base = f"{self._prefix}{direction}{layer}"
                blocks.append((f"{base}_i2h_weight", (m * lh, in_dim)))
                blocks.append((f"{base}_h2h_weight", (m * lh, lh)))
        for layer in range(self._num_layers):
            for direction in self._directions:
                base = f"{self._prefix}{direction}{layer}"
                blocks.append((f"{base}_i2h_bias", (m * lh,)))
                blocks.append((f"{base}_h2h_bias", (m * lh,)))
        return blocks

    def _slice_weights(self, arr, li, lh):
        args = {}
        p = 0
        for name, shape in self._weight_layout(li):
            size = int(np.prod(shape))
            args[name] = arr[p:p + size].reshape(shape)
            p += size
        assert p == arr.size, "Invalid parameters size for FusedRNNCell"
        return args

    def unpack_weights(self, args):
        args = args.copy()
        arr = args.pop(self._parameter.name)
        b = len(self._directions)
        m = self._num_gates
        h = self._num_hidden
        num_input = int(arr.size // b // h // m -
                        (self._num_layers - 1) * (h + b * h + 2) - h - 2)
        nargs = self._slice_weights(arr, num_input, self._num_hidden)
        args.update({name: nd_arr.copy() if isinstance(nd_arr, NDArray)
                     else nd_arr for name, nd_arr in nargs.items()})
        return args

    def pack_weights(self, args):
        args = args.copy()
        first_dir = self._directions[0]
        w0 = args[f"{self._prefix}{first_dir}0_i2h_weight"]
        num_input = w0.shape[1]
        pieces = []
        for name, shape in self._weight_layout(num_input):
            x = args.pop(name)
            flat = x.asjax().reshape(-1) if isinstance(x, NDArray) else \
                np.asarray(x).reshape(-1)
            pieces.append(flat)
        import jax.numpy as jnp
        args[self._parameter.name] = NDArray(jnp.concatenate(
            [jnp.asarray(p) for p in pieces]))
        return args

    def _num_params(self, num_input):
        b = len(self._directions)
        m = self._num_gates
        h = self._num_hidden
        size = b * h * m * (num_input + h + 2)
        for _ in range(1, self._num_layers):
            size += b * h * m * (b * h + h + 2)
        return size

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Expand to stacked (bi)directional unfused cells over the packed
        blob slices."""
        self.reset()
        stack = self._to_unfused()
        return stack.unroll(length, inputs=inputs, begin_state=begin_state,
                            input_prefix=input_prefix, layout=layout,
                            merge_outputs=merge_outputs)

    def _to_unfused(self):
        """Build the equivalent SequentialRNNCell of unfused cells sharing
        this cell's params via name-compatible vars."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p,
                                       forget_bias=self._forget_bias),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for layer in range(self._num_layers):
            if self._dropout > 0 and layer > 0:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{layer}_"))
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell(f"{self._prefix}l{layer}_"),
                    get_cell(f"{self._prefix}r{layer}_"),
                    output_prefix=f"{self._prefix}bi_{layer}_"))
            else:
                stack.add(get_cell(f"{self._prefix}l{layer}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack cells. reference: rnn_cell.py:563-640."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child cells,"\
                " not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    @property
    def state_shape(self):
        return sum([c.state_shape for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def reset(self):
        super().reset()
        for cell in getattr(self, "_cells", []):
            cell.reset()


class BidirectionalCell(BaseRNNCell):
    """reference: rnn_cell.py:643-740."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    @property
    def state_shape(self):
        return sum([c.state_shape for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.var(f"{input_prefix}t{i}_data")
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1
            inputs = list(symbol.SliceChannel(inputs, axis=axis,
                                              num_outputs=length,
                                              squeeze_axis=1))
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()

        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name=f"{self._output_prefix}t{i}")
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=axis) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell. reference: rnn_cell.py:743."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    @property
    def state_shape(self):
        return self.base_cell.state_shape

    def begin_state(self, init_sym=symbol.var, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(init_sym, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class DropoutCell(BaseRNNCell):
    """reference: rnn_cell.py:790."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    @property
    def state_shape(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """reference: rnn_cell.py:830."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. Please unfuse first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't "\
            "support step. Please add ZoneoutCell to the cells underneath "\
            "instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None
        if hasattr(self, "base_cell"):
            self.base_cell.reset()

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: symbol.Dropout(
            symbol.ones_like(like), p=p))

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = (symbol.where(mask(p_outputs, next_output), next_output,
                               prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([symbol.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self.prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """reference: rnn_cell.py:900."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs)
        return output, states
