"""RNN cells + bucketing IO (reference: python/mxnet/rnn/)."""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ZoneoutCell, ResidualCell, ModifierCell)
from .io import BucketSentenceIter, encode_sentences


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """reference: rnn/rnn.py save_rnn_checkpoint — unpack fused weights."""
    if isinstance(cells, BaseRNNCell):
        cells = [cells]
    for cell in cells:
        arg_params = cell.unpack_weights(arg_params)
    from ..model import save_checkpoint
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """reference: rnn/rnn.py load_rnn_checkpoint — pack into fused blobs."""
    from ..model import load_checkpoint
    sym, arg, aux = load_checkpoint(prefix, epoch)
    if isinstance(cells, BaseRNNCell):
        cells = [cells]
    for cell in cells:
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """reference: rnn/rnn.py do_rnn_checkpoint."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
