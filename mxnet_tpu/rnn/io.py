"""Bucketed sequence iterator for variable-length text.

API parity with reference python/mxnet/rnn/io.py (``encode_sentences`` +
``BucketSentenceIter`` feeding ``BucketingModule``), restructured: one
flat index of (bucket, row-range) batch slots built once, per-bucket
storage as padded 2-D arrays, and next-token labels derived by a single
roll at reset. Sequences are binned to the smallest bucket that fits;
overflow sequences are dropped (and counted).
"""
from __future__ import annotations

import bisect
import logging
import random

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from ..ndarray import array

__all__ = ["BucketSentenceIter", "encode_sentences"]

log = logging.getLogger(__name__)


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Integer-encode token sequences, growing ``vocab`` when it's ours.

    Matches reference rnn/io.py:15-50: if ``vocab`` is given, unknown
    tokens are an error; otherwise a fresh vocabulary is assigned ids
    from ``start_label``, skipping ``invalid_label``.
    """
    grow = vocab is None
    if grow:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    encoded = []
    for sent in sentences:
        row = []
        for tok in sent:
            if tok not in vocab:
                if not grow:
                    raise KeyError(f"token {tok!r} not in the given vocab")
                if next_id == invalid_label:
                    next_id += 1
                vocab[tok] = next_id
                next_id += 1
            row.append(vocab[tok])
        encoded.append(row)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Serve fixed-shape (batch, bucket_len) slices of padded sequences,
    one bucket per batch, with next-token labels."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__(batch_size)
        if not buckets:
            # default policy: one bucket per length that has at least a
            # full batch of examples
            counts = np.bincount([len(s) for s in sentences])
            buckets = [length for length, c in enumerate(counts)
                       if c >= batch_size]
        self.buckets = sorted(buckets)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.major_axis = 0  # NT layout

        # bin sentences into per-bucket padded matrices
        rows = [[] for _ in self.buckets]
        dropped = 0
        for sent in sentences:
            b = bisect.bisect_left(self.buckets, len(sent))
            if b == len(self.buckets):
                dropped += 1
                continue
            padded = np.full(self.buckets[b], invalid_label, dtype=dtype)
            padded[:len(sent)] = sent
            rows[b].append(padded)
        if dropped:
            log.warning("BucketSentenceIter: dropped %d sequences longer "
                        "than the largest bucket (%d)", dropped,
                        self.buckets[-1])
        self._bucket_data = [
            np.asarray(r, dtype=dtype).reshape(-1, blen)
            for r, blen in zip(rows, self.buckets)]

        # one slot per full batch within each bucket
        self._slots = [(b, start)
                       for b, mat in enumerate(self._bucket_data)
                       for start in range(0, len(mat) - batch_size + 1,
                                          batch_size)]
        self._cursor = 0

        self.default_bucket_key = self.buckets[-1]
        self.provide_data = [
            DataDesc(data_name, (batch_size, self.default_bucket_key))]
        self.provide_label = [
            DataDesc(label_name, (batch_size, self.default_bucket_key))]
        self.reset()

    def reset(self):
        self._cursor = 0
        random.shuffle(self._slots)
        self._nd_data, self._nd_label = [], []
        for mat in self._bucket_data:
            np.random.shuffle(mat)
            # label = input shifted left one step; tail padded invalid
            lab = np.roll(mat, -1, axis=1)
            lab[:, -1] = self.invalid_label
            self._nd_data.append(array(mat, dtype=self.dtype))
            self._nd_label.append(array(lab, dtype=self.dtype))

    def next(self):
        if self._cursor >= len(self._slots):
            raise StopIteration
        b, start = self._slots[self._cursor]
        self._cursor += 1
        data = self._nd_data[b][start:start + self.batch_size]
        label = self._nd_label[b][start:start + self.batch_size]
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[b],
            provide_data=[DataDesc(self.data_name, data.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)])
