"""Auto-generation of mx.nd.* imperative functions from the op registry.

The reference builds every binding's op functions at import from C-side
registry metadata (reference: python/mxnet/ndarray.py:875
``_init_ndarray_module`` via MXSymbolGetAtomicSymbolInfo). Here the registry
is Python, so generation is a direct closure over ``imperative_invoke``.
"""
from __future__ import annotations

from .ndarray import NDArray, imperative_invoke
from .ops.registry import OP_REGISTRY, get_op


def _make_ndarray_function(op_name):
    opdef = get_op(op_name)

    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        # split kwargs into tensor inputs vs attrs
        tensor_kwargs = {k: v for k, v in kwargs.items()
                         if isinstance(v, NDArray)}
        params = {k: v for k, v in kwargs.items()
                  if not isinstance(v, NDArray)}
        inputs = list(args)
        if tensor_kwargs:
            attrs = opdef.normalize_attrs(params)
            in_names = opdef.input_names(attrs)
            by_name = [None] * len(in_names)
            for i, a in enumerate(inputs):
                by_name[i] = a
            for k, v in tensor_kwargs.items():
                if k in in_names:
                    by_name[in_names.index(k)] = v
                else:
                    try:
                        by_name[by_name.index(None)] = v
                    except ValueError:
                        by_name.append(v)
            inputs = [a for a in by_name if a is not None]
        if callable(opdef._inputs) and "num_args" in opdef.attr_spec \
                and "num_args" not in params:
            params["num_args"] = len(inputs)
        return imperative_invoke(op_name, *inputs, out=out, **params)

    fn.__name__ = op_name
    fn.__doc__ = opdef.doc or f"imperative {op_name}"
    return fn


def init_ndarray_module(namespace):
    for op_name in list(OP_REGISTRY):
        if op_name.startswith("_backward"):
            continue
        if op_name in namespace:
            continue  # don't clobber hand-written factories (zeros, sort, ..)
        namespace[op_name] = _make_ndarray_function(op_name)
