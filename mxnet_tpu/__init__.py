"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new implementation on JAX/XLA/Pallas/pjit of the full reference API
surface (reference: anirudh2290/mxnet, NNVM-era v0.9 — see SURVEY.md):
NDArray + Symbol hybrid, Module training stack, KVStore data parallelism
(as ICI/DCN collectives), RecordIO data pipeline, optimizers/initializers/
metrics/RNN cells. Import as ``import mxnet_tpu as mx``.
"""
from . import base
from .base import MXNetError
from . import telemetry  # pure-stdlib; every layer records into it
from . import faults  # deterministic fault-injection plane + retry/breaker
from .context import Context, cpu, gpu, tpu, current_context, num_gpus
from . import ops  # populates the op registry (must precede nd/sym autogen)
from . import ndarray
from . import ndarray as nd
from . import _op_gen
_op_gen.init_ndarray_module(ndarray.__dict__)
from . import symbol
from . import symbol as sym
symbol._init_symbol_module(symbol.__dict__)
from .symbol import Group
from . import random
from .attribute import AttrScope
from .name import NameManager, Prefix
from .executor import Executor
from . import program_cache
from . import remat  # fused-step rematerialization/donation policy
from . import analysis  # bind-time graph verifier & hazard linter
from . import io
from . import recordio
from . import initializer
from .initializer import init_registry  # noqa: F401
from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import monitor
from .monitor import Monitor
from . import kvstore as kv
from . import model
from .model import FeedForward
from . import module
from . import module as mod
from . import checkpoint  # async checkpointing + elastic recovery
from . import rnn
from . import visualization
from . import visualization as viz
from . import profiler
from . import test_utils
from . import autograd
from . import parallel
from . import contrib
from . import rtc
# contrib/rtc register their ops after the first autogen pass — pick them
# up so mx.nd.fft / mx.sym.MultiBoxPrior etc. exist like every registry op
_op_gen.init_ndarray_module(ndarray.__dict__)
symbol._init_symbol_module(symbol.__dict__)
from . import image
from . import predict
from .predict import export_model, Predictor
from . import serve  # continuous-batching inference server (serve/)

__version__ = "0.1.0"
