"""Checkpointing & elastic recovery (ROADMAP item 5).

Three layers compose the fault-tolerance story:

* ``checkpoint.state`` — full-training-state capture/restore (params,
  layout-independent optimizer state + update counts, rng chain, data
  cursor) with a fast device-side capture phase and a slow host phase
  for the writer thread;
* ``checkpoint.manager`` — :class:`CheckpointManager`: async
  snapshotting off the training thread, versioned atomically-committed
  checkpoint directories, ``keep_last`` retention, the ``MXNET_CKPT_*``
  env surface. ``Module.fit(checkpoint=..., resume=...)`` drives it;
* ``checkpoint.recovery`` — :class:`DeadWorkerError` +
  :func:`survivor_env`/:func:`reexec_survivor`: when the dist heartbeat
  layer reports a dead peer, survivors save, raise instead of hanging,
  and re-form the job over the remaining workers to resume from the
  last committed checkpoint (tests/chaos_worker.py end-to-end).

See docs/checkpoint.md for the on-disk format, the atomic-commit
protocol, resume semantics (window boundaries under
``steps_per_dispatch``), and the recovery flow.
"""
from . import state
from . import manager
from . import recovery
from .state import capture, restore, to_host, FORMAT_VERSION
from .manager import (CheckpointManager, latest_checkpoint,
                      restore_module, read_committed_payload)
from .recovery import (DeadWorkerError, recovery_generation, survivor_env,
                       reexec_survivor)

__all__ = [
    "state", "manager", "recovery",
    "capture", "restore", "to_host", "FORMAT_VERSION",
    "CheckpointManager", "latest_checkpoint", "restore_module",
    "read_committed_payload",
    "DeadWorkerError", "recovery_generation", "survivor_env",
    "reexec_survivor",
]
