"""Elastic recovery: dead worker -> save -> re-form -> resume.

The failure model (BENCH_r05, SURVEY §5.3): at pod scale a worker
dying mid-epoch is routine. Pre-recovery behavior was a hang — the
survivors' next collective waits forever for a peer that will never
arrive (or, with gloo, dies with "Connection closed by peer" and takes
the whole job down). The recovery story composed here:

1. **Detect** — the dist kvstore's heartbeat layer
   (``KVStore.on_dead_node``) flags the death, or the survivor's own
   collective fails fast and ``Module.fit`` confirms against the
   liveness layer. Either way fit saves what it safely can and raises
   :class:`DeadWorkerError` (``clean=True`` when detected at a batch
   boundary — state consistent, an emergency checkpoint was cut;
   ``clean=False`` when a collective already failed mid-batch — resume
   MUST come from the last *committed* checkpoint, since survivors may
   have partially applied the broken batch).

2. **Re-form** — the surviving processes re-exec themselves
   (:func:`reexec_survivor`) with a deterministically remapped cluster:
   survivors keep their relative order (new rank = index among
   survivors), worker 0 of the new ordering hosts the coordination
   service on a generation-bumped port. Re-exec rather than in-process
   re-init is deliberate: the XLA distributed backend in a running
   process is bound to the dead topology (device client, gloo
   connections, coordination service), and tearing it down under a
   half-failed collective is exactly the kind of "clean shutdown of a
   broken thing" that hangs. A fresh process over the survivor env is
   the torch-elastic/agent-restart shape, minus the agent.

3. **Resume** — the re-exec'd survivors run the same training script;
   ``Module.fit(resume=...)`` restores the last committed checkpoint
   (params, optimizer state + counts, rng chain, cursor) and continues
   from the cursor. tests/chaos_worker.py is the canonical composition.

Everything here is pure env/process plumbing — deterministic given
(dead set, prior env) on every survivor, with no cross-worker
coordination needed beyond already agreeing on who died.
"""
from __future__ import annotations

import os
import sys

from ..base import MXNetError
from .. import telemetry as _telemetry

__all__ = ["DeadWorkerError", "recovery_generation", "survivor_env",
           "reexec_survivor"]


class DeadWorkerError(MXNetError):
    """A training peer died mid-run; raised by ``Module.fit`` instead
    of hanging in the next collective. ``dead_ranks`` names the dead
    workers (input to :func:`survivor_env`); ``clean`` says whether the
    module's state was consistent at detection (batch boundary) — when
    False, resume only from the last committed checkpoint."""

    def __init__(self, dead_ranks, clean=True):
        self.dead_ranks = sorted(int(r) for r in dead_ranks)
        self.clean = bool(clean)
        state = "at a batch boundary (state consistent)" if clean \
            else "mid-batch (resume from the last committed checkpoint)"
        super().__init__(
            f"dist worker(s) {self.dead_ranks} died; detected {state}")


def recovery_generation(env=None):
    """How many re-forms this process lineage has been through (0 on a
    first launch; bumped by :func:`survivor_env` on every re-exec)."""
    env = os.environ if env is None else env
    try:
        return int(env.get("MXNET_RECOVERY_GENERATION", "0") or 0)
    except ValueError:
        return 0


def survivor_env(dead_ranks, env=None):
    """The re-formed cluster's env for THIS surviving process.

    Deterministic on every survivor from (dead set, prior env) alone:

    * ``DMLC_NUM_WORKER`` — the survivor count;
    * ``DMLC_WORKER_ID`` — this rank's index among the sorted
      survivors (relative order preserved, so survivor data shards
      stay stable when keyed off a launch-time identity);
    * ``DMLC_PS_ROOT_PORT`` — the ORIGINAL port plus the new
      generation, so the re-formed coordination service can never
      collide with the old job's socket (survivor 0 may be a re-exec'd
      process whose predecessor owned the old port);
    * ``MXNET_RECOVERY_GENERATION`` / ``MXNET_RECOVERY_BASE_PORT`` /
      ``MXNET_RECOVERY_DEAD_RANKS`` — lineage bookkeeping.

    Multi-host note: ``DMLC_PS_ROOT_URI`` is left as-is; if the dead
    worker hosted the coordinator, the launcher must point survivors at
    a surviving host's address (single-host jobs — 127.0.0.1 — need
    nothing).
    """
    base = dict(os.environ if env is None else env)
    n = int(base.get("DMLC_NUM_WORKER", "1"))
    rank = int(base.get("DMLC_WORKER_ID", "0"))
    dead = sorted({int(r) for r in dead_ranks})
    if not dead:
        raise MXNetError("survivor_env() needs a non-empty dead set")
    if any(r < 0 or r >= n for r in dead):
        raise MXNetError(f"dead ranks {dead} outside the {n}-worker job")
    if rank in dead:
        raise MXNetError(f"rank {rank} is in the dead set {dead}; a "
                         "dead worker has no survivor env")
    survivors = [r for r in range(n) if r not in dead]
    gen = recovery_generation(base) + 1
    port = int(base.get("DMLC_PS_ROOT_PORT", "9091"))
    root = int(base.get("MXNET_RECOVERY_BASE_PORT", str(port)))
    base.update({
        "DMLC_NUM_WORKER": str(len(survivors)),
        "DMLC_WORKER_ID": str(survivors.index(rank)),
        "DMLC_PS_ROOT_PORT": str(root + gen),
        "MXNET_RECOVERY_BASE_PORT": str(root),
        "MXNET_RECOVERY_GENERATION": str(gen),
        "MXNET_RECOVERY_DEAD_RANKS": ",".join(str(r) for r in dead),
    })
    return base


def reexec_survivor(dead_ranks, argv=None):
    """Replace this process with a fresh one joined to the re-formed
    cluster (``os.execve`` of the same interpreter + argv under
    :func:`survivor_env`). Does not return. The caller should close its
    kvstore (``kv.close(abort=True)``) and checkpoint manager first so
    pending commits land and no threads hold locks across exec."""
    env = survivor_env(dead_ranks)
    _telemetry.counter("recovery.reexec").inc()
    _telemetry.flightrec.note(
        "recovery.reexec", dead=sorted(int(r) for r in dead_ranks),
        generation=env["MXNET_RECOVERY_GENERATION"],
        new_rank=env["DMLC_WORKER_ID"],
        new_nworker=env["DMLC_NUM_WORKER"])
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable] + list(argv or sys.argv),
              env)
