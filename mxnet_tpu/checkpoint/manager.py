"""CheckpointManager: async snapshots, atomic commits, retention.

Design (the Orbax/torch-elastic shape, adapted to this framework's
executor):

* **Async by default** — ``save()`` captures device-side copies on the
  training thread (cheap: async dispatches, see checkpoint/state.py)
  and hands the snapshot to ONE background writer thread that does the
  device→host transfer, serialization, fsync and commit. The training
  step never waits for disk; the measured exposed stall is the capture
  dispatch plus any back-pressure wait (the snapshot queue is bounded
  at 2 so a slow disk can hold at most two full param copies in
  flight). ``MXNET_CKPT_ASYNC=0`` (or ``async_write=False``) writes
  inline — the A/B the checkpoint-stall benchmark measures.

* **Atomic commit** — each checkpoint is a directory
  ``ckpt-<seq>/{state.pkl, manifest.json}`` renamed into place from a
  ``.tmp-`` staging dir after both files are fsynced; ``manifest.json``
  is written last inside the staging dir, and the rename is the commit
  point. A reader (``latest()``/``restore()``) only ever sees
  directories that are complete; a crash mid-write leaves a ``.tmp-``
  dir the next manager sweeps.

* **Retention** — after every commit the oldest committed checkpoints
  beyond ``keep_last`` are deleted.

* **Failure policy** (docs/faults.md) — each commit retries under the
  shared ``faults.retry`` policy (``MXNET_RETRY_CKPT``: exponential
  backoff, deadline budget) with the staging dir swept per attempt; a
  seq that exhausts its retries is *quarantined* (``quarantined`` list,
  ``ckpt.quarantined``/``ckpt.failures`` counters, ``ckpt.quarantine``
  ring record, deferred ``wait()`` raise) and the writer thread keeps
  serving the queue. Reads are damage-tolerant: ``restore_module``
  falls back commit-by-commit past unreadable checkpoints
  (``ckpt.damaged``) and never loads a partial state. The
  ``ckpt.write`` / ``ckpt.d2h`` fault-injection points make both paths
  deterministically testable (tests/test_faults.py).

Telemetry: ``ckpt.exposed_stall.seconds`` (training-thread cost per
save), ``ckpt.snapshot.seconds`` (background transfer+write+commit),
counters ``ckpt.snapshots`` / ``ckpt.commits`` / ``ckpt.failures``,
gauge ``ckpt.last_seq``, and flight-ring records
``ckpt.snapshot`` / ``ckpt.commit`` / ``ckpt.fail`` / ``ckpt.restore``
so crash dumps show the checkpoint cadence (tools/diagnose.py).

Env surface (docs/env_var.md): ``MXNET_CKPT_DIR``,
``MXNET_CKPT_KEEP_LAST``, ``MXNET_CKPT_ASYNC``, ``MXNET_CKPT_EVERY``,
``MXNET_CKPT_ELASTIC``, ``MXNET_CKPT_DEAD_PATIENCE``.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import re
import shutil
import threading
import time

from ..base import MXNetError
from .. import faults as _faults
from .. import telemetry as _telemetry
from . import state as _state

__all__ = ["CheckpointManager", "latest_checkpoint", "restore_module",
           "read_committed_payload"]

_CKPT_RE = re.compile(r"^ckpt-(\d{8})$")


def _committed(directory):
    """[(seq, path)] of complete checkpoints in ``directory``, oldest
    first. A directory counts only when its manifest says complete —
    the atomic-commit contract (rename-after-manifest) makes the
    manifest's presence inside a ``ckpt-*`` name sufficient, but the
    flag guards against foreign dirs that happen to match."""
    out = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return out
    for name in entries:
        m = _CKPT_RE.match(name)
        if not m:
            continue
        path = os.path.join(directory, name)
        manifest = os.path.join(path, "manifest.json")
        try:
            with open(manifest) as f:
                if json.load(f).get("complete"):
                    out.append((int(m.group(1)), path))
        except (OSError, ValueError):
            continue
    out.sort()
    return out


def latest_checkpoint(directory):
    """(seq, path) of the newest committed checkpoint, or None."""
    committed = _committed(directory)
    return committed[-1] if committed else None


def read_committed_payload(directory, kind=None):
    """(seq, path, payload) of the newest committed checkpoint whose
    payload actually READS BACK (and, when ``kind`` is given, matches
    it), or None.

    The damage-tolerance half of the atomic-commit contract: a commit
    can rename cleanly and still be unreadable later (torn disk,
    truncation, bit rot). Reading falls back commit-by-commit — newest
    first — past any directory whose pickle fails to load, recording
    each fallback (``ckpt.damaged`` counter + flight-ring record +
    warning) and NEVER surfacing a partially-read state.
    """
    log_ = logging.getLogger(__name__)
    for seq, path in reversed(_committed(directory)):
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                sha = json.load(f).get("sha256")
            with open(os.path.join(path, "state.pkl"), "rb") as f:
                payload = _state.loads_payload(f.read(), sha256=sha)
        except Exception as exc:
            _telemetry.counter("ckpt.damaged").inc()
            _telemetry.flightrec.note(
                "ckpt.damaged", seq=seq,
                error=f"{type(exc).__name__}: {exc}")
            log_.warning(
                "checkpoint %s is damaged (%s: %s); falling back to "
                "the previous commit", path, type(exc).__name__, exc)
            continue
        if kind is not None and payload.get("kind", "train") != kind:
            continue
        return seq, path, payload
    return None


def restore_module(module, directory):
    """Restore a bound module from the newest *readable* committed
    checkpoint in ``directory``; returns the cursor dict or None when
    no committed checkpoint survives (a first run resuming over an
    empty — or wholly damaged — dir starts fresh, with a warning for
    the damaged case)."""
    found = read_committed_payload(directory, kind="train")
    if found is None:
        return None
    seq, path, payload = found
    cursor = _state.restore(module, payload)
    _telemetry.flightrec.note("ckpt.restore", seq=seq, **cursor)
    logging.getLogger(__name__).info(
        "resumed from checkpoint %s (epoch %d, batch %d)",
        path, cursor["epoch"], cursor["nbatch"])
    return cursor


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class CheckpointManager:
    """Versioned, atomically-committed training checkpoints.

    Parameters (each defaulting from its ``MXNET_CKPT_*`` env var):

    directory : str — checkpoint root (``MXNET_CKPT_DIR``; required
        one way or the other).
    keep_last : int — committed checkpoints retained
        (``MXNET_CKPT_KEEP_LAST``, default 3).
    async_write : bool — background writer on/off
        (``MXNET_CKPT_ASYNC``, default on).
    every_n_batches : int — ``Module.fit`` save cadence in retired
        batches (``MXNET_CKPT_EVERY``; 0 = epoch-end saves only).
    retry_policy : faults.RetryPolicy — per-commit retry behavior
        (default from ``MXNET_RETRY_CKPT``; see docs/faults.md).
    """

    def __init__(self, directory=None, keep_last=None, async_write=None,
                 every_n_batches=None, logger=None, retry_policy=None):
        directory = directory or os.environ.get("MXNET_CKPT_DIR")
        if not directory:
            raise MXNetError("CheckpointManager needs a directory "
                             "(argument or MXNET_CKPT_DIR)")
        self.directory = directory
        self.keep_last = _env_int("MXNET_CKPT_KEEP_LAST", 3) \
            if keep_last is None else int(keep_last)
        self.async_write = (os.environ.get("MXNET_CKPT_ASYNC", "1")
                            not in ("0", "false", "no", "off")) \
            if async_write is None else bool(async_write)
        self.every_n_batches = _env_int("MXNET_CKPT_EVERY", 0) \
            if every_n_batches is None else int(every_n_batches)
        self.logger = logger or logging.getLogger(__name__)

        os.makedirs(self.directory, exist_ok=True)
        # sweep staging dirs a crashed writer left behind
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
        committed = _committed(self.directory)
        self._seq = committed[-1][0] + 1 if committed else 1

        self._queue = queue.Queue(maxsize=2)
        self._thread = None
        # guards the writer thread's shared failure state (_error and
        # the quarantined list) against the caller-side wait()/readers
        self._state_lock = threading.Lock()
        self._error = None              # first writer failure, for wait()
        self._ticks = 0                 # fit-loop cadence counter
        self._closed = False
        # commit-failure policy: each write retries per MXNET_RETRY_CKPT
        # (transient full-disk/EIO survive); an exhausted seq is
        # QUARANTINED — recorded here, writer stays alive — instead of
        # killing the writer thread and silently backing up the queue
        self._retry_policy = retry_policy if retry_policy is not None \
            else _faults.RetryPolicy.from_env(
                "CKPT", attempts=3, base_s=0.05, max_s=1.0,
                deadline_s=30.0)
        self.quarantined = []           # seqs abandoned after retries

    # ------------------------------------------------------------- saving
    def tick(self, module, epoch, nbatch):
        """Per-retired-batch cadence hook (called by ``Module.fit``);
        ``nbatch`` is the NEXT batch index. Saves when
        ``every_n_batches`` divides the tick count."""
        self._ticks += 1
        if self.every_n_batches and \
                self._ticks % self.every_n_batches == 0:
            self.save(module, epoch, nbatch)

    def save(self, module, epoch=0, nbatch=0, block=False):
        """Snapshot now; commit in the background (or inline when
        ``async_write`` is off or ``block=True`` — block additionally
        waits for every previously queued snapshot)."""
        if self._closed:
            raise MXNetError("CheckpointManager is closed")
        t0 = time.perf_counter()
        snap = _state.capture(module, epoch, nbatch)
        seq = self._seq
        self._seq += 1
        if self.async_write:
            self._ensure_writer()
            self._queue.put((seq, snap))    # bounded: back-pressure
        else:
            self._write(seq, snap)
        stall = time.perf_counter() - t0
        _telemetry.counter("ckpt.snapshots").inc()
        if _telemetry.enabled():
            _telemetry.histogram("ckpt.exposed_stall.seconds").observe(
                stall)
        _telemetry.flightrec.note("ckpt.snapshot", seq=seq, epoch=epoch,
                                  nbatch=nbatch,
                                  exposed_us=int(stall * 1e6))
        if block and self.async_write:
            self.wait()
        return seq

    def save_payload(self, payload, block=False):
        """Queue one arbitrary host-side payload dict for an atomic
        commit through the same writer/retry/quarantine machinery —
        the serve warm-restart path (serve/warm.py). The payload should
        carry ``version`` (:data:`state.FORMAT_VERSION`) so readers
        accept it, and a ``kind`` distinguishing it from training state
        (``restore_module`` skips non-train kinds)."""
        if self._closed:
            raise MXNetError("CheckpointManager is closed")
        seq = self._seq
        self._seq += 1
        item = (seq, {"__host_payload__": payload})
        if self.async_write:
            self._ensure_writer()
            self._queue.put(item)
        else:
            self._write(*item)
        _telemetry.counter("ckpt.snapshots").inc()
        _telemetry.flightrec.note("ckpt.snapshot", seq=seq,
                                  payload=payload.get("kind", "payload"))
        if block and self.async_write:
            self.wait()
        return seq

    def _ensure_writer(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="mxnet-ckpt-writer")
            self._thread.start()

    def _writer_loop(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                self._write(*item)
            except Exception as exc:
                # quarantine the seq: the writer thread SURVIVES (the
                # next queued snapshot still commits), the failure is
                # loud — counter + ring record + wait()'s deferred
                # raise — and nothing partial is left on disk (_write
                # sweeps its staging dir per attempt)
                try:
                    seq = item[0]
                    with self._state_lock:
                        if self._error is None:
                            self._error = exc
                        self.quarantined.append(seq)
                    _telemetry.counter("ckpt.failures").inc()
                    _telemetry.counter("ckpt.quarantined").inc()
                    _telemetry.flightrec.note(
                        "ckpt.quarantine", seq=seq,
                        error=f"{type(exc).__name__}: {exc}")
                    self.logger.warning(
                        "checkpoint %d failed after retries, "
                        "quarantined: %s", seq, exc)
                except Exception:       # bookkeeping must never kill
                    pass                # the writer thread either
            finally:
                self._queue.task_done()

    def _write(self, seq, snap):
        t0 = time.perf_counter()
        span = _telemetry.span("ckpt.snapshot",
                               _hist="ckpt.snapshot.seconds", seq=seq) \
            if _telemetry.enabled() else _telemetry.null_span
        with span:
            payload = _faults.retry_call(
                lambda: self._commit_once(seq, snap),
                self._retry_policy, site="ckpt.write",
                logger=self.logger)
        dur = time.perf_counter() - t0
        cursor = payload.get("cursor") or {}
        _telemetry.counter("ckpt.commits").inc()
        _telemetry.gauge("ckpt.last_seq").set(seq)
        _telemetry.flightrec.note("ckpt.commit", seq=seq,
                                  dur_us=int(dur * 1e6), **cursor)
        self._retain()

    def _commit_once(self, seq, snap):
        """One commit attempt: D2H (already-host payloads skip it),
        serialize, fsync, rename. Every failure path removes the
        staging dir before re-raising, so a retried or quarantined seq
        never leaves a partial ``.tmp-`` dir for the init sweep."""
        if isinstance(snap, dict) and "__host_payload__" in snap:
            payload = snap["__host_payload__"]
        else:
            payload = _state.to_host(snap)
        tmp = os.path.join(self.directory,
                           f".tmp-ckpt-{seq:08d}-{os.getpid()}")
        final = os.path.join(self.directory, f"ckpt-{seq:08d}")
        try:
            _faults.point("ckpt.write", seq=seq)
            os.makedirs(tmp, exist_ok=True)
            state_path = os.path.join(tmp, "state.pkl")
            buf = _state.dumps_payload(payload)
            with open(state_path, "wb") as f:
                f.write(buf)
                f.flush()
                os.fsync(f.fileno())
            manifest = {
                "complete": True, "seq": seq,
                "version": payload.get("version",
                                       _state.FORMAT_VERSION),
                "kind": payload.get("kind", "train"),
                "sha256": hashlib.sha256(buf).hexdigest(),
                "cursor": payload.get("cursor") or {},
                "opt": {k: v for k, v in (payload.get("opt") or
                                          {}).items() if k != "counts"},
                "time": time.time(),
                "n_params": len((payload.get("device") or
                                 {}).get("arg_params") or ()),
            }
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):
                # an UNCOMMITTED leftover squatting on this seq (e.g. a
                # damaged dir that lost its manifest) is garbage this
                # commit supersedes; a COMMITTED one must never be
                # silently replaced
                if any(s == seq for s, _ in _committed(self.directory)):
                    raise MXNetError(
                        f"checkpoint seq {seq} already committed at "
                        f"{final}; refusing to overwrite")
                shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)           # the commit point
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        try:
            dirfd = os.open(self.directory, os.O_RDONLY)
            os.fsync(dirfd)
            os.close(dirfd)
        except OSError:
            pass                            # platform without dir fsync
        return payload

    def _retain(self):
        committed = _committed(self.directory)
        for _seq, path in committed[:max(0, len(committed) -
                                         self.keep_last)]:
            shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------ reading
    def list_committed(self):
        return _committed(self.directory)

    def latest(self):
        return latest_checkpoint(self.directory)

    def restore(self, module):
        """Restore ``module`` from the newest committed checkpoint;
        returns the cursor dict or None when the directory is empty."""
        return restore_module(module, self.directory)

    # ----------------------------------------------------------- lifecycle
    def wait(self):
        """Block until every queued snapshot is committed; raises the
        first writer failure (once)."""
        if self._thread is not None:
            self._queue.join()
        with self._state_lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def close(self):
        """Drain pending writes and stop the writer. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=120)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
