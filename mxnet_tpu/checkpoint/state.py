"""Full-training-state capture and exact restore.

The reference's ``save_checkpoint`` writes symbol + params; everything
else a training run IS — optimizer state, update counts (Adam bias
correction, lr schedules), the rng chain feeding dropout, the
epoch/batch cursor — dies with the process. This module captures the
whole of it, in two phases shaped by JAX's functional arrays:

* :func:`capture` runs on the TRAINING thread and is cheap: every
  device array is snapshotted as an async on-device copy (dispatch
  returns immediately; the copy itself runs at HBM bandwidth behind the
  next step). The copy is mandatory, not defensive — the fused train
  step donates its param/state buffers, so a bare reference would be
  invalidated one step later. Host-side scalars (counts, cursors, rng
  tuples) are read synchronously; they are bytes, not buffers.
* :func:`to_host` runs on the checkpoint WRITER thread and does the
  slow part: device→host transfer of the captured copies, yielding a
  pure-numpy payload for serialization.

Optimizer state is stored in the canonical layout-independent form —
param-shaped arrays keyed by parameter NAME — via the same transport
the ZeRO/spmd plans use for their checkpoints
(``export_fused_states``/``FlatShardLayout``), so a snapshot taken
under any arrangement (staged updater, fused, ZeRO-sharded, spmd)
restores into any other.

:func:`restore` is the inverse: params, optimizer state + counts, rng
chain (host key, device chain, numpy + stdlib generators — the last two
drive data shuffling/augmentation), returning the cursor so
``Module.fit(resume=...)`` can continue bit-for-bit.
"""
from __future__ import annotations

import logging
import pickle
import random as _pyrandom

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray import NDArray
from .. import faults as _faults
from .. import random as _mxrandom

__all__ = ["FORMAT_VERSION", "capture", "to_host", "restore",
           "write_payload", "read_payload"]

FORMAT_VERSION = 1

log = logging.getLogger(__name__)


def _copy_leaf(x):
    """A REAL op per leaf (never identity): jit passes unmodified
    outputs through as the input array object, which would alias the
    snapshot to buffers the fused step donates one step later. add-zero
    (or-False for bools) forces a distinct output buffer."""
    if jnp.issubdtype(x.dtype, jnp.bool_):
        return jnp.logical_or(x, False)
    return x + jnp.zeros((), x.dtype)


@jax.jit
def _copy_tree(tree):
    """Exclusively-owned on-device copies of every leaf in ONE
    dispatch. Per-leaf eager copies cost a dispatch each (~170 for a
    ResNet-20's params+aux+states — tens of ms of exposed stall);
    one jitted program makes the capture a single async dispatch.
    Compiled once per (treedef, shapes) — i.e. once per model."""
    return jax.tree.map(_copy_leaf, tree)


def _canon_state(v):
    """One param's optimizer state in canonical form: ``()`` for
    stateless, a device-array ref for single-buffer state, a tuple for
    multi-buffer (Adam) — the same pytree shapes the fused plans use,
    so fused and staged captures are interchangeable. (Refs only; the
    caller copies the whole tree in one dispatch.)"""
    if v is None:
        return ()
    if isinstance(v, (tuple, list)):
        return tuple(_canon_state(x) for x in v)
    if isinstance(v, NDArray):
        return v.asjax()
    return jnp.asarray(v)


def _staged_states_by_name(module, updater):
    """Staged (per-index) updater states -> canonical by-name form."""
    names = module._param_names
    out = {}
    for idx, st in (getattr(updater, "states", None) or {}).items():
        if isinstance(idx, int) and 0 <= idx < len(names):
            out[names[idx]] = _canon_state(st)
    return out


def capture(module, epoch=0, nbatch=0):
    """Snapshot the module's full training state (device-side, fast).

    ``nbatch`` is the NEXT batch index of ``epoch`` — the cursor a
    resumed fit starts from. Returns the snapshot dict ``to_host``
    finishes off-thread.
    """
    assert module.binded and module.params_initialized, \
        "capture() needs a bound, initialized module"
    eg = getattr(module, "_exec_group", None)
    if eg is None:
        raise MXNetError(
            "checkpoint capture needs a Module bound to an executor "
            "group (Sequential/Bucketing modules are not supported yet)")
    exe = eg.executor

    arg = {nm: exe.arg_dict[nm].asjax()
           for nm in module._param_names if nm in exe.arg_dict}
    aux = {nm: a.asjax() for nm, a in exe.aux_dict.items()}

    opt_mode, opt_states, opt_counts, opt_class = None, None, None, None
    layout = None
    if getattr(module, "optimizer_initialized", False):
        opt_class = type(module._optimizer).__name__
        if hasattr(module, "_opt_counts"):
            opt_counts = module._opt_counts()
        if getattr(module, "_fused_armed", False):
            opt_mode = "fused"
            # raw layout form (flat-sharded under ZeRO): the writer
            # thread unflattens to the canonical param shape off the
            # training thread (to_host)
            opt_states = dict(eg._fused_states)
            if eg._state_layout is not None:
                layout = (eg._state_layout,
                          {nm: exe.arg_dict[nm].shape
                           for nm in opt_states})
        elif getattr(module, "_update_on_kvstore", False):
            opt_mode = "kvstore"
            opt_states = _staged_states_by_name(
                module, getattr(module._kvstore, "_updater", None))
        elif getattr(module, "_updater", None) is not None:
            opt_mode = "staged"
            opt_states = _staged_states_by_name(module, module._updater)

    device = _copy_tree({"arg_params": arg, "aux_params": aux,
                         "opt_states": opt_states})

    rng = {
        "mx": _mxrandom.get_state(),
        "device_chain": eg.rng_chain() if hasattr(eg, "rng_chain")
        else None,
        "numpy": np.random.get_state(),
        "python": _pyrandom.getstate(),
    }

    return {
        "version": FORMAT_VERSION,
        "cursor": {"epoch": int(epoch), "nbatch": int(nbatch)},
        "device": device,
        "_state_layout": layout,        # device-side only, not serialized
        "opt": {"mode": opt_mode, "class": opt_class,
                "counts": opt_counts},
        "rng": rng,
    }


def to_host(snapshot):
    """Device→host the captured arrays (blocks; run on the writer
    thread). ZeRO/spmd flat-sharded optimizer states unflatten to the
    canonical param shape here — device-side transform on the writer
    thread, over copies the training thread no longer touches. Returns
    the pure-numpy payload ``write_payload`` pickles."""
    _faults.point("ckpt.d2h")
    payload = {k: v for k, v in snapshot.items()
               if k != "_state_layout"}
    device = dict(snapshot["device"])
    layout = snapshot.get("_state_layout")
    if layout is not None:
        lay, shapes = layout
        device["opt_states"] = {
            nm: lay.device_state_to_param_shape(st, shapes[nm])
            for nm, st in device["opt_states"].items()}
    payload["device"] = jax.tree.map(np.asarray, device)
    return payload


def write_payload(payload, fobj):
    pickle.dump(payload, fobj, protocol=pickle.HIGHEST_PROTOCOL)


def dumps_payload(payload):
    """Serialized payload bytes — the writer hashes these into the
    manifest (``sha256``) so a read can tell torn/bit-rotted state from
    intact state: a flipped byte mid-pickle often still *unpickles*,
    just into silently wrong arrays."""
    return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)


def _check_version(payload):
    version = payload.get("version")
    if version != FORMAT_VERSION:
        raise MXNetError(
            f"checkpoint format version {version!r} is not supported "
            f"by this build (expected {FORMAT_VERSION})")
    return payload


def loads_payload(data, sha256=None):
    """Inverse of :func:`dumps_payload`; verifies the manifest checksum
    first when one is recorded (pre-checksum checkpoints skip it)."""
    if sha256 is not None:
        import hashlib
        got = hashlib.sha256(data).hexdigest()
        if got != sha256:
            raise MXNetError(
                f"checkpoint state.pkl checksum mismatch "
                f"(manifest {sha256[:12]}…, file {got[:12]}…): "
                "damaged on disk")
    return _check_version(pickle.loads(data))


def read_payload(fobj):
    return _check_version(pickle.load(fobj))


def _to_staged_state(v):
    """Canonical state -> the staged updater's representation."""
    if isinstance(v, (tuple, list)):
        if len(v) == 0:
            return None                      # stateless (plain SGD)
        return tuple(_to_staged_state(x) for x in v)
    return NDArray(jnp.asarray(np.asarray(v)))


def restore(module, payload):
    """Reinstate a ``to_host`` payload into a bound module; returns the
    cursor dict ``{"epoch": e, "nbatch": b}``.

    The module must already be through bind/init_params (and
    init_optimizer, for optimizer state to land) — i.e. exactly where
    ``Module.fit`` is right after ``_prepare_fit``. Restoring is
    layout-independent: the canonical param-shaped states project onto
    whatever arrangement THIS module armed (staged, fused replicated,
    ZeRO-sharded, spmd)."""
    dev = payload["device"]

    arg = {nm: NDArray(jnp.asarray(np.asarray(v)))
           for nm, v in dev["arg_params"].items()}
    aux = {nm: NDArray(jnp.asarray(np.asarray(v)))
           for nm, v in dev["aux_params"].items()}
    module.set_params(arg, aux, allow_missing=False, force_init=True)

    opt = payload.get("opt") or {}
    states = dev.get("opt_states")
    if states is not None and getattr(module, "optimizer_initialized",
                                      False):
        saved_cls = opt.get("class")
        now_cls = type(module._optimizer).__name__
        if saved_cls and saved_cls != now_cls:
            log.warning("checkpoint optimizer state is %s but the run "
                        "uses %s; restoring anyway (state pytrees must "
                        "match)", saved_cls, now_cls)
        eg = module._exec_group
        if getattr(module, "_fused_armed", False):
            fused = getattr(eg, "_fused_states", {})
            missing = [nm for nm in fused if nm not in states]
            if missing:
                raise MXNetError(
                    "checkpoint optimizer state is missing parameters "
                    f"{missing[:4]}{'...' if len(missing) > 4 else ''} "
                    "required by this binding")
            eg.import_fused_states({nm: states[nm] for nm in fused})
        else:
            updater = module._kvstore._updater \
                if getattr(module, "_update_on_kvstore", False) \
                else module._updater
            if updater is not None:
                idx = {nm: i for i, nm in enumerate(module._param_names)}
                for nm, st in states.items():
                    if nm in idx:
                        updater.states[idx[nm]] = _to_staged_state(st)
        if opt.get("counts") and hasattr(module, "_restore_opt_counts"):
            module._restore_opt_counts(opt["counts"])

    rng = payload.get("rng") or {}
    if rng.get("mx") is not None:
        _mxrandom.set_state(rng["mx"])
    if rng.get("numpy") is not None:
        np.random.set_state(rng["numpy"])
    if rng.get("python") is not None:
        _pyrandom.setstate(rng["python"])
    chain = rng.get("device_chain")
    if chain is not None and getattr(module, "_fused_armed", False):
        module._exec_group.set_rng_chain(chain)

    return dict(payload["cursor"])
