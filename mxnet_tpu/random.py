"""Global RNG state.

The reference seeds per-device mshadow random streams via ``mx.random.seed``
(reference: python/mxnet/random.py, src/resource.cc kRandom). JAX randomness
is functional (explicit keys), so this module keeps ONE host-side key that is
split on demand: imperative sampling ops and executors draw fresh subkeys via
``next_key()``; jitted training steps thread a key through the step function.
Seeding is deterministic and device-independent.
"""
from __future__ import annotations

import jax
import numpy as np

# lazy: materializing a key initializes the XLA backend, which must not
# happen at import time (jax.distributed.initialize comes after import)
# ``generation`` bumps on every seed() so device-chained key consumers
# (the fused train step keeps its rng on device between steps) can
# detect a reseed and re-draw from the fresh chain
_STATE = {"key": None, "generation": 0}


def seed(seed_state):
    """Seed the global generator. reference: python/mxnet/random.py seed()."""
    _STATE["key"] = jax.random.PRNGKey(int(seed_state))
    _STATE["generation"] += 1


def generation():
    """Monotonic count of seed() calls (device-chain invalidation tag)."""
    return _STATE["generation"]


def next_key():
    """Split and return a fresh subkey (host-side, stateful)."""
    if _STATE["key"] is None:
        _STATE["key"] = jax.random.PRNGKey(0)
    _STATE["key"], sub = jax.random.split(_STATE["key"])
    return sub


def get_state():
    """Picklable snapshot of the host rng chain, for exact-resume
    checkpoints (mxnet_tpu/checkpoint): the raw key material (or None
    when never seeded/drawn) plus the generation tag. Restoring it with
    :func:`set_state` reproduces the same subkey sequence from this
    point — the dropout/augmentation streams of a resumed run continue
    exactly where the killed run stopped."""
    key = _STATE["key"]
    return {"key": None if key is None else np.asarray(key),
            "generation": int(_STATE["generation"])}


def set_state(state):
    """Restore a :func:`get_state` snapshot. Always bumps the generation
    so device-chained consumers (the fused train step keeps its rng on
    device between steps) re-draw from the restored chain rather than
    continuing a stale one — restorers that also reinstate the device
    chain (checkpoint resume) re-record the generation afterwards."""
    key = state.get("key")
    _STATE["key"] = None if key is None else \
        jax.numpy.asarray(np.asarray(key))
    _STATE["generation"] += 1
