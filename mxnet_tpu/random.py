"""Global RNG state.

The reference seeds per-device mshadow random streams via ``mx.random.seed``
(reference: python/mxnet/random.py, src/resource.cc kRandom). JAX randomness
is functional (explicit keys), so this module keeps ONE host-side key that is
split on demand: imperative sampling ops and executors draw fresh subkeys via
``next_key()``; jitted training steps thread a key through the step function.
Seeding is deterministic and device-independent.
"""
from __future__ import annotations

import jax

# lazy: materializing a key initializes the XLA backend, which must not
# happen at import time (jax.distributed.initialize comes after import)
# ``generation`` bumps on every seed() so device-chained key consumers
# (the fused train step keeps its rng on device between steps) can
# detect a reseed and re-draw from the fresh chain
_STATE = {"key": None, "generation": 0}


def seed(seed_state):
    """Seed the global generator. reference: python/mxnet/random.py seed()."""
    _STATE["key"] = jax.random.PRNGKey(int(seed_state))
    _STATE["generation"] += 1


def generation():
    """Monotonic count of seed() calls (device-chain invalidation tag)."""
    return _STATE["generation"]


def next_key():
    """Split and return a fresh subkey (host-side, stateful)."""
    if _STATE["key"] is None:
        _STATE["key"] = jax.random.PRNGKey(0)
    _STATE["key"], sub = jax.random.split(_STATE["key"])
    return sub
