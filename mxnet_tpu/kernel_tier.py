"""Kernel-tier selection: per-(op, backend, shape/dtype) implementation
choice with one-shot autotuning.

The registry (ops/registry.py) keeps exactly one *semantic* definition
per op, but an op may carry alternative *implementations* — today an
XLA composition (``OpDef.forward``, always present, always correct) and
optionally a Pallas kernel (``OpDef.variants["pallas"]``). Which one
wins is an empirical, shape-dependent question: VERDICT §5 measured the
same flash-attention kernel beating XLA in one session and losing by
13% in another, so a static "Pallas wins" table is wrong by
construction. This module makes the choice *measured*:

* ``MXNET_KERNEL_TIER=xla``    — force the XLA composition everywhere
  (bit-exact with the pre-tier framework);
* ``MXNET_KERNEL_TIER=pallas`` — force the Pallas variant wherever one
  is registered and eligible (interpret mode off-TPU);
* ``MXNET_KERNEL_TIER=auto``   — the default: XLA everywhere except on
  a TPU backend, where the first encounter of each (op, attrs, shapes,
  dtypes) key runs a one-shot autotune — numerics-gate the Pallas
  kernel against the XLA composition, time both on device, cache the
  winner process-wide. Off-TPU, auto resolves to XLA without timing,
  so CPU results are bit-identical to ``xla``.

Tier selection composes unchanged under the SPMD mesh
(``Module.fit(spmd=True)``): dispatch happens inside the traced runner
per op, before XLA partitions the program, so the chosen implementation
is sharding-agnostic — the partitioner splits whichever kernel won
exactly as it would the composition (pinned by tests/test_spmd.py's
tier-parity gate; per-shape autotune keys see the *global* logical
shapes, not the per-device shards).

Winners are cached in-process alongside the program cache and follow
the same keying discipline (``program_cache.attr_cache_stable``: attrs
that would churn or collide a cache key make the op untunable and it
falls back to XLA). Set ``MXNET_AUTOTUNE_CACHE_DIR`` to persist
decisions as JSON keyed by (device kind, op, attrs, shapes, dtypes) so
warm restarts skip re-timing, mirroring the persistent XLA compile
cache. Every decision lands in an audit log (``decisions()``), the
``kernel_tier.*`` telemetry counters, and the flight-recorder ring.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import telemetry as _telemetry
from .program_cache import attr_cache_stable

__all__ = ["mode", "dispatch", "resolve", "autotune", "numerics_gate",
           "decisions", "clear", "cache_info"]

_lock = threading.Lock()
_selection = {}          # key -> variant name ("xla" | "pallas" | ...)
_decisions = []          # audit log: dicts, append order
_persist_loaded = False
_persist = {}            # str(key) -> persisted decision dict

#: per-dtype absolute tolerances for the autotune numerics gate (the
#: registration-test gates in tests/ use the same table)
NUMERIC_TOL = {
    "float32": 2e-4,
    "float64": 1e-8,
    "bfloat16": 2e-2,
    "float16": 1e-2,
}


def mode():
    """Current tier mode: 'xla' | 'pallas' | 'auto' (the default)."""
    m = os.environ.get("MXNET_KERNEL_TIER", "auto").lower()
    if m not in ("xla", "pallas", "auto"):
        m = "auto"
    return m


def _backend():
    import jax
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def _device_kind():
    import jax
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def _reps():
    try:
        return max(1, int(os.environ.get("MXNET_AUTOTUNE_REPS", "5")))
    except ValueError:
        return 5


# ------------------------------------------------------------------ keys
def _attr_token(attrs):
    """Stable sorted attr tuple, or None when any attr value is not
    cache-key safe (same discipline as the program cache / RC401)."""
    items = []
    for k in sorted(attrs):
        v = attrs[k]
        ok, _why = attr_cache_stable(v)
        if not ok:
            return None
        items.append((k, tuple(v) if isinstance(v, list) else v))
    return tuple(items)


def _key(opdef, attrs, shapes, dtypes, is_train):
    tok = _attr_token(attrs)
    if tok is None:
        return None
    # the remat policy rides the key alongside the program-cache token:
    # under "all"/"dots" a kernel's forward re-executes inside the
    # backward, so a winner measured under "none" is not evidence — a
    # persisted selection must never leak across policies (the same
    # rule the fused-step program cache applies)
    from . import remat as _remat
    return (opdef.name, _backend(), tok,
            tuple(tuple(s) for s in shapes), tuple(dtypes), bool(is_train),
            ("remat", _remat.active()))


# ------------------------------------------------------ persisted winners
def _persist_path():
    d = os.environ.get("MXNET_AUTOTUNE_CACHE_DIR")
    if not d:
        return None
    return os.path.join(d, "kernel_tier.json")


def _load_persist():
    global _persist_loaded, _persist
    if _persist_loaded:
        return
    _persist_loaded = True
    path = _persist_path()
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            _persist = {k: v for k, v in doc.items()
                        if isinstance(v, dict) and "variant" in v}
    except (OSError, ValueError):
        _persist = {}


def _save_persist():
    path = _persist_path()
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(_persist, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass                     # persistence is best-effort


def _persist_key(key):
    # device kind (not just backend) keys the persisted file: a v5e
    # winner is not a v6e winner
    return repr((_device_kind(),) + key[:1] + key[2:])


# ---------------------------------------------------------- synth inputs
def _synth_inputs(opdef, attrs, shapes, dtypes):
    """Deterministic host-generated operands for gating/timing.

    Standard-normal (not zeros: zeros make softmax/BN degenerate and
    hide real numeric divergence), fixed seed so every process times
    the same problem. Inputs whose declared name marks them as
    second-moment state (Adam's ``var``, RMSProp's ``n``, BatchNorm's
    ``moving_var``) are made non-negative — a negative synthetic
    variance would NaN both sides and fail the gate on noise. The same
    applies to decode cursors (``*cache_pos``): a negative position
    makes the causal mask empty and softmax all -inf.
    """
    import numpy as np
    import jax.numpy as jnp
    try:
        names = list(opdef.input_names(attrs)) + \
            list(opdef.aux_names(attrs))
    except Exception:
        names = []
    rng = np.random.RandomState(0)
    vals = []
    for i, (s, dt) in enumerate(zip(shapes, dtypes)):
        a = rng.standard_normal(tuple(s)).astype("float32")
        name = names[i] if i < len(names) else ""
        if name in ("var", "n") or "var" in name.split("_") \
                or name.endswith("cache_pos"):
            a = np.abs(a)
        vals.append(jnp.asarray(a).astype(dt))
    return vals


def _run_variant(opdef, attrs, variant, regular, aux, is_train):
    """One jitted execution closure for a variant at concrete operands."""
    import jax
    fn = opdef.variant_fn(variant)
    rng = jax.random.PRNGKey(0)

    def run(r, x):
        outs, new_aux = fn(attrs, list(r), list(x), is_train, rng)
        return list(outs), list(new_aux)

    return jax.jit(run)


def numerics_gate(opdef, attrs, shapes, dtypes, variant="pallas",
                  is_train=True, n_aux=None, tol=None, inputs=None):
    """Compare a variant against the XLA composition at one shape.

    Returns ``(ok, max_abs_err)``. This is the registration-test gate
    (tests call it per fused op per dtype) and the first stage of every
    autotune: a kernel that fails it can never be selected. ``inputs``
    overrides the synthetic operands (regular + aux, in order) when a
    test needs specific well-formed state.
    """
    import numpy as np
    import jax

    if n_aux is None:
        n_aux = len(opdef.aux_names(attrs))
    vals = list(inputs) if inputs is not None else \
        _synth_inputs(opdef, attrs, shapes, dtypes)
    regular = vals[:len(vals) - n_aux] if n_aux else vals
    aux = vals[len(vals) - n_aux:] if n_aux else []
    ref = _run_variant(opdef, attrs, "xla", regular, aux, is_train)(
        regular, aux)
    got = _run_variant(opdef, attrs, variant, regular, aux, is_train)(
        regular, aux)
    max_err = 0.0
    for side_r, side_g in zip(ref, got):
        for r, g in zip(side_r, side_g):
            err = float(np.max(np.abs(
                np.asarray(jax.device_get(r), dtype="float32") -
                np.asarray(jax.device_get(g), dtype="float32"))))
            max_err = max(max_err, err)
    if tol is None:
        tol = max(NUMERIC_TOL.get(str(dt), 2e-4) for dt in dtypes)
    return max_err <= tol, max_err


def _time_variant(run, regular, aux, reps):
    import jax
    out = run(regular, aux)                        # compile + warm
    jax.block_until_ready(out)
    laps = []
    # benchmark timing is the one legitimate wall-clock read here: the
    # laps are the measurement itself, not a replayable decision input
    for _ in range(reps):
        tic = time.perf_counter()  # mxlint: allow(DT401)
        jax.block_until_ready(run(regular, aux))
        laps.append(time.perf_counter() - tic)  # mxlint: allow(DT401)
    laps.sort()
    return laps[len(laps) // 2]


def autotune(opdef, attrs, shapes, dtypes, is_train):
    """Measure pallas vs xla at one key; returns (winner, record).

    Never raises: any failure (Mosaic lowering error, numerics-gate
    miss, timing trouble) resolves to "xla" with the reason recorded —
    an inconsistent kernel can regress nothing.
    """
    n_aux = len(opdef.aux_names(attrs))
    rec = {"op": opdef.name, "shapes": [list(s) for s in shapes],
           "dtypes": [str(d) for d in dtypes], "is_train": bool(is_train),
           "backend": _backend()}
    try:
        ok, err = numerics_gate(opdef, attrs, shapes, dtypes,
                                is_train=is_train, n_aux=n_aux)
        rec["max_abs_err"] = err
        if not ok:
            rec.update(variant="xla", reason="numerics-gate failed")
            return "xla", rec
        vals = _synth_inputs(opdef, attrs, shapes, dtypes)
        regular = vals[:len(vals) - n_aux] if n_aux else vals
        aux = vals[len(vals) - n_aux:] if n_aux else []
        reps = _reps()
        t_xla = _time_variant(
            _run_variant(opdef, attrs, "xla", regular, aux, is_train),
            regular, aux, reps)
        t_pl = _time_variant(
            _run_variant(opdef, attrs, "pallas", regular, aux, is_train),
            regular, aux, reps)
        rec["xla_ms"] = round(t_xla * 1e3, 4)
        rec["pallas_ms"] = round(t_pl * 1e3, 4)
        if t_pl < t_xla:
            rec.update(variant="pallas",
                       reason=f"measured {t_xla / t_pl:.2f}x faster")
            return "pallas", rec
        rec.update(variant="xla",
                   reason=f"pallas measured {t_pl / t_xla:.2f}x slower")
        return "xla", rec
    except Exception as e:        # noqa: BLE001 — fall back, never break
        rec.update(variant="xla",
                   reason=f"autotune error: {type(e).__name__}: {e}")
        return "xla", rec


def _note_decision(rec, source):
    rec = dict(rec, source=source)
    with _lock:
        _decisions.append(rec)
    _telemetry.counter("kernel_tier.selection", op=rec["op"],
                       variant=rec.get("variant", "xla")).inc()
    _telemetry.flightrec.note("kernel_tier.decision", op=rec["op"],
                              variant=rec.get("variant", "xla"),
                              source=source,
                              reason=rec.get("reason", ""))


# -------------------------------------------------------------- selection
_ring_noted = set()       # (op, shapes) keys already audit-logged


def _resolve_ring(opdef, attrs, shapes, dtypes, spmd_plan):
    """Plan-driven lowering: when the binding's SpmdPlan carries a
    nonempty ``seq`` mesh axis and the op registers a ``ring`` variant
    that is eligible at these shapes, the sequence-sharded ring
    lowering wins — the whole point of sharding the sequence axis.
    ``MXNET_KERNEL_TIER=xla`` still forces compositions everywhere
    (the bit-exact contract), handled by the caller."""
    if spmd_plan is None or "ring" not in opdef.variants:
        return None
    try:
        n_seq = int(spmd_plan.n_seq_shards())
    except Exception:
        return None
    if n_seq <= 1:
        return None
    from .parallel import spmd as _spmd_mod
    with _spmd_mod.plan_scope(spmd_plan):
        if not opdef.variant_eligible("ring", attrs, shapes, dtypes):
            return None
    note_key = (opdef.name, tuple(tuple(s) for s in shapes),
                tuple(dtypes))
    if note_key not in _ring_noted:
        _ring_noted.add(note_key)
        _note_decision(
            {"op": opdef.name, "variant": "ring",
             "shapes": [list(s) for s in shapes],
             "dtypes": [str(d) for d in dtypes],
             "backend": _backend(),
             "reason": f"sequence-sharded plan (seq={n_seq}): ring "
                       "attention over lax.ppermute"},
            source="plan")
    return "ring"


def resolve(opdef, attrs, shapes, dtypes, is_train, spmd_plan=None):
    """Variant name for one (op, attrs, shapes, dtypes, train) site."""
    m = mode()
    if m != "xla":
        ring = _resolve_ring(opdef, attrs, shapes, dtypes, spmd_plan)
        if ring is not None:
            return ring
    if m == "xla" or not opdef.variants or "pallas" not in opdef.variants:
        return "xla"
    if m == "pallas":
        return "pallas" if opdef.variant_eligible(
            "pallas", attrs, shapes, dtypes) else "xla"
    # auto: Pallas is eligible only on a TPU backend, and only after
    # winning its one-shot per-shape measurement
    if _backend() != "tpu" or not opdef.variant_eligible(
            "pallas", attrs, shapes, dtypes):
        return "xla"
    key = _key(opdef, attrs, shapes, dtypes, is_train)
    if key is None:
        return "xla"             # uncacheable attrs: never autotune
    with _lock:
        hit = _selection.get(key)
    if hit is not None:
        _telemetry.counter("kernel_tier.cache.hit").inc()
        return hit
    _telemetry.counter("kernel_tier.cache.miss").inc()
    _load_persist()
    pkey = _persist_key(key)
    prec = _persist.get(pkey)
    if prec is not None:
        winner = prec["variant"]
        _note_decision(prec, source="persisted")
    else:
        _telemetry.counter("kernel_tier.autotune.runs").inc()
        winner, rec = autotune(opdef, attrs, shapes, dtypes, is_train)
        _note_decision(rec, source="autotune")
        with _lock:
            _persist[pkey] = {k: rec[k] for k in
                              ("op", "variant", "reason", "shapes",
                               "dtypes", "is_train") if k in rec}
            for k in ("xla_ms", "pallas_ms", "max_abs_err"):
                if k in rec:
                    _persist[pkey][k] = rec[k]
        _save_persist()
    with _lock:
        _selection[key] = winner
    return winner


def dispatch(opdef, attrs, inputs, aux, is_train, rng, spmd_plan=None):
    """Run one op through the tier; the single choke point both the
    executor's graph runner and imperative invoke call instead of
    ``opdef.forward``. Zero-variant ops pass straight through.
    ``spmd_plan`` (the binding's SpmdPlan, threaded from the executor)
    arms plan-driven lowerings — the ring variant runs inside a
    ``plan_scope`` so it can read the mesh/axes."""
    if not opdef.variants:
        return opdef.forward(attrs, inputs, aux, is_train, rng)
    shapes = [tuple(v.shape) for v in inputs] + \
        [tuple(v.shape) for v in aux]
    dtypes = [str(v.dtype) for v in inputs] + [str(v.dtype) for v in aux]
    variant = resolve(opdef, attrs, shapes, dtypes, is_train,
                      spmd_plan=spmd_plan)
    fn = opdef.variant_fn(variant)
    if variant == "ring" and spmd_plan is not None:
        from .parallel import spmd as _spmd_mod
        with _spmd_mod.plan_scope(spmd_plan):
            return fn(attrs, inputs, aux, is_train, rng)
    return fn(attrs, inputs, aux, is_train, rng)


# ------------------------------------------------------------- inspection
def decisions():
    """Audit log of every selection decision this process made."""
    with _lock:
        return [dict(r) for r in _decisions]


def cache_info():
    with _lock:
        return {"selections": len(_selection),
                "decisions": len(_decisions),
                "persisted": len(_persist)}


def clear():
    """Drop in-memory selections + audit log (tests). The persisted
    file, if any, is left on disk; it reloads on the next resolve."""
    global _persist_loaded
    with _lock:
        _selection.clear()
        del _decisions[:]
        _persist.clear()
        _ring_noted.clear()
    _persist_loaded = False
