"""KVStore: parameter synchronization.

Reference architecture (reference: src/kvstore/): ``local``/``device`` reduce
gradients across local GPUs through the engine (comm.h), ``dist_*`` go
through a ZMQ parameter server (ps-lite, kvstore_dist.h). The *API* —
init/push/pull/set_optimizer/rank/num_workers/barrier — is the compatibility
surface (SURVEY.md §5.8).

TPU-native design: there is no parameter server. Within a host, "reduce"
is a jnp sum (one fused XLA op across device copies); across hosts,
``dist_sync`` semantics are an all-reduce over the JAX distributed runtime
(ICI/DCN collectives) — the server vanishes, rank = ``jax.process_index()``.
``dist_async`` has no collective analog and is documented unsupported
(SURVEY.md §7 hard parts); creating it raises with that explanation.

Note the actual data-parallel hot path in this framework does NOT round-trip
gradients through KVStore handles: Module binds ONE sharded executor and XLA
inserts the psum (see module/executor_group.py). KVStore remains for API
parity, for the update_on_kvstore path, and for multi-host grad sync.
"""
from __future__ import annotations

import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray, zeros
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _ctype_key_value(key, vals):
    """Normalize to (list_of_keys, list_of_list_of_NDArray)."""
    if isinstance(key, (int, str)):
        key = [key]
        vals = [vals]
    out_vals = []
    for v in vals:
        if isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return list(key), out_vals


class KVStore:
    """Single-process store ('local'/'device'). reference:
    src/kvstore/kvstore_local.h:40-130."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None

    # ---------------------------------------------------------------- meta
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # ---------------------------------------------------------------- core
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError(f"key {k!r} already initialized")
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Reduce values; run updater or assign (reference semantics:
        kvstore_local.h Push -> Comm::Reduce -> updater/assign)."""
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            if len(vlist) == 1:
                merged = vlist[0].copy()
            else:
                acc = vlist[0].asjax()
                for v in vlist[1:]:
                    acc = acc + v.asjax()
                merged = NDArray(acc, ctx=vlist[0].context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k]._set(merged.asjax())

    def pull(self, key, out=None, priority=0):
        """Broadcast stored value into out arrays."""
        assert out is not None
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            src = self._store[k]
            for o in olist:
                # land the value in the destination's existing placement
                # (keeps mesh-sharded arrays sharded)
                o._set(jax.device_put(src.asjax(), o.asjax().sharding))

    # ------------------------------------------------------------ optimizer
    def set_optimizer(self, optimizer):
        """reference: kvstore.py:226 — local mode installs the updater
        closure; dist mode ships the (pickled) optimizer to the server.
        Here there is no server: always install locally."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        pass

    # --------------------------------------------------------- persistence
    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        states = {k: v.asnumpy() if isinstance(v, NDArray) else v
                  for k, v in getattr(self._updater, "states", {}).items()}
        with open(fname, "wb") as fout:
            pickle.dump(states, fout)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as fin:
            states = pickle.load(fin)
        self._updater.states.update(states)


class KVStoreDistSync(KVStore):
    """dist_sync over the JAX distributed runtime.

    reference semantics: kvstore_dist.h ZPush/ZPull + server merge-all-then-
    update (kvstore_dist_server.h:164-198). Realization: every process holds
    a replica; push() all-reduces the gradient across processes (psum over
    DCN/ICI), then the updater runs identically on every replica — the
    arithmetic invariant of dist_sync (nightly test formula) holds because
    sum-then-update on N replicas == server-side update.
    """

    def __init__(self, kind):
        super().__init__(kind)
        self._nproc = jax.process_count()

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return self._nproc

    def push(self, key, value, priority=0):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            acc = vlist[0].asjax()
            for v in vlist[1:]:
                acc = acc + v.asjax()
            if self._nproc > 1:
                from jax.experimental import multihost_utils
                acc = multihost_utils.process_allgather(acc).sum(axis=0)
            merged = NDArray(acc, ctx=vlist[0].context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k]._set(merged.asjax())

    def _barrier(self):
        if self._nproc > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")


def create(name="local"):
    """Factory. reference: src/kvstore/kvstore.cc:17-45 (substring match)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist_async" in name:
        raise MXNetError(
            "dist_async has no TPU-native equivalent: asynchronous "
            "parameter-server updates do not map onto XLA collectives "
            "(SURVEY.md §7). Use dist_sync (all-reduce) instead.")
    if "dist" in name:
        return KVStoreDistSync(name)
    if "device" in name or "local" in name:
        return KVStore(name)
    raise MXNetError(f"unknown kvstore type {name!r}")
