"""KVStore: parameter synchronization.

Reference architecture (reference: src/kvstore/): ``local``/``device`` reduce
gradients across local GPUs through the engine (comm.h), ``dist_*`` go
through a ZMQ parameter server (ps-lite, kvstore_dist.h). The *API* —
init/push/pull/set_optimizer/rank/num_workers/barrier — is the compatibility
surface (SURVEY.md §5.8).

TPU-native design: there is no parameter server. Within a host, "reduce"
is a jnp sum (one fused XLA op across device copies); across hosts,
``dist_sync`` semantics are an all-reduce over the JAX distributed runtime
(ICI/DCN collectives) — the server vanishes, rank = ``jax.process_index()``.
``dist_async`` has no collective analog and is documented unsupported
(SURVEY.md §7 hard parts); creating it raises with that explanation.

Note the actual data-parallel hot path in this framework does NOT round-trip
gradients through KVStore handles: Module binds ONE sharded executor and XLA
inserts the psum (see module/executor_group.py). KVStore remains for API
parity, for the update_on_kvstore path, and for multi-host grad sync.
"""
from __future__ import annotations

import logging
import os
import pickle
import threading

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray, zeros
from . import faults as _faults
from . import optimizer as opt
from . import telemetry as _telemetry
from .kvstore_sched import BucketScheduler

__all__ = ["KVStore", "create", "init_distributed"]


def _payload_bytes(vals):
    """Total bytes across a normalized list-of-list-of-NDArray payload."""
    n = 0
    for vlist in vals:
        for v in vlist:
            n += int(v.size) * np.dtype(v.dtype).itemsize
    return n


def _dist_initialized():
    """jax.distributed.is_initialized(), version-portable: older jax has
    no such predicate — the coordination client's existence is the
    equivalent signal there."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return fn()
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except ImportError:
        return False


def init_distributed():
    """Connect this process to the training job's coordination service.

    The reference bootstraps its PS cluster from DMLC_* env vars set by
    tools/launch.py (reference: launch.py:33-75, MXInitPSEnv c_api.h:1196).
    The same env contract drives the TPU-native runtime: there are no
    server processes — DMLC_PS_ROOT_URI/PORT name the jax.distributed
    coordinator (hosted by worker 0) and every worker is a peer in the
    collective. Idempotent; a single-process run is a no-op.
    """
    n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if n <= 1:
        return
    if _dist_initialized():
        return                               # already connected
    rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
    uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
    if jax.config.jax_platforms == "cpu" or \
            os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # multi-process CPU collectives need the gloo transport; must be
        # configured before the backend initializes
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # ps-lite reads PS_HEARTBEAT_TIMEOUT (seconds) for its failure
    # detector (reference: ps-lite/src/van.cc heartbeat handling); honor
    # the same knob for the coordination service's liveness tracking.
    heartbeat = int(os.environ.get("PS_HEARTBEAT_TIMEOUT", "100"))
    # Failure-handling mode. Default (fail-fast): JAX's error-polling
    # thread terminates every survivor the moment a peer misses its
    # heartbeat — the NCCL-abort analog, right for fit-and-restart jobs.
    # MXNET_KVSTORE_RECOVERABLE=1 selects ps-lite semantics instead: a
    # peer death is *reported* (get_num_dead_node, reference
    # kvstore_dist.h GetDeadNodes) and survivors keep running so they can
    # checkpoint/re-form; without the flag the fatal propagation would
    # make get_num_dead_node unobservable.
    if os.environ.get("MXNET_KVSTORE_RECOVERABLE", "0") == "1" and \
            hasattr(jax.config, "jax_enable_recoverability"):
        jax.config.update("jax_enable_recoverability", True)
    # older jax doesn't expose the heartbeat knob — pass it only where
    # the installed initialize() accepts it
    import inspect
    kwargs = {"coordinator_address": f"{uri}:{port}",
              "num_processes": n, "process_id": rank}
    try:
        if "heartbeat_timeout_seconds" in \
                inspect.signature(jax.distributed.initialize).parameters:
            kwargs["heartbeat_timeout_seconds"] = heartbeat
    except (TypeError, ValueError):
        pass
    jax.distributed.initialize(**kwargs)
    if jax.process_count() != n:
        raise MXNetError(
            f"distributed init came up with {jax.process_count()} "
            f"processes, expected {n}: the backend was initialized before "
            "init_distributed() — create the dist kvstore before touching "
            "any device")


def _coordination_client():
    """Handle to the coordination-service client, or None.

    JAX exposes no public liveness query, so this is the one sanctioned
    private touchpoint (everything else uses the public
    ``jax.distributed`` API). Guarded so a JAX upgrade that moves the
    internals degrades to a loud error rather than a silent wrong answer.
    Liveness itself has two spellings: newer jax clients expose
    ``get_live_nodes`` directly; older ones get ps-lite-style heartbeats
    over the coordination KV store (see KVStoreDistSync._start_heartbeats).
    """
    if not _dist_initialized():
        return None
    try:
        from jax._src import distributed as _dist
        client = getattr(_dist.global_state, "client", None)
    except ImportError:
        client = None
    if client is None:
        raise MXNetError(
            "jax.distributed is initialized but the coordination-service "
            "client is not reachable at jax._src.distributed.global_state."
            "client (JAX internals moved?); liveness queries unavailable")
    return client


def _ctype_key_value(key, vals):
    """Normalize to (list_of_keys, list_of_list_of_NDArray)."""
    if isinstance(key, (int, str)):
        key = [key]
        vals = [vals]
    out_vals = []
    for v in vals:
        if isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return list(key), out_vals


class KVStore:
    """Single-process store ('local'/'device'). reference:
    src/kvstore/kvstore_local.h:40-130."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._closed = False

    # ---------------------------------------------------------------- meta
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    # ---------------------------------------------------------------- core
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError(f"key {k!r} already initialized")
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Reduce values; run updater or assign (reference semantics:
        kvstore_local.h Push -> Comm::Reduce -> updater/assign)."""
        keys, vals = _ctype_key_value(key, value)
        if _telemetry.enabled():
            nbytes = _payload_bytes(vals)
            _telemetry.counter("kvstore.push.bytes").inc(nbytes)
            push_span = _telemetry.span(
                "kvstore.push", _hist="kvstore.push.seconds",
                keys=len(keys), bytes=nbytes)
        else:
            push_span = _telemetry.null_span
            _telemetry.flightrec.note("kvstore.push", keys=len(keys))
        try:
            with push_span:
                for k, vlist in zip(keys, vals):
                    if k not in self._store:
                        raise MXNetError(f"key {k!r} not initialized")
                    if len(vlist) == 1:
                        acc = vlist[0].asjax()
                    else:
                        acc = vlist[0].asjax()
                        for v in vlist[1:]:
                            acc = acc + v.asjax()
                    # colocate the merged value with the store replica:
                    # a mesh-replicated gradient pushed into a single-
                    # device store (multi-device Module + device store)
                    # would otherwise hand the updater incompatible
                    # placements
                    store_sharding = self._store[k].asjax().sharding
                    if acc.sharding != store_sharding:
                        acc = jax.device_put(acc, store_sharding)
                    elif len(vlist) == 1:
                        acc = jnp.array(acc, copy=True)
                    merged = NDArray(acc, ctx=vlist[0].context)
                    if self._updater is not None:
                        self._updater(k, merged, self._store[k])
                    else:
                        self._store[k]._set(merged.asjax())
        except Exception as exc:
            _telemetry.flightrec.on_crash(exc, where="kvstore.push")
            raise

    def pull(self, key, out=None, priority=0):
        """Broadcast stored values into out arrays.

        All destinations of the call are placed through ONE batched
        ``jax.device_put`` (a pytree of sources against a pytree of
        shardings) instead of one transfer per key — through a
        remote-chip tunnel each ``device_put`` is its own RPC, so a
        100-param pull was 100 round trips."""
        assert out is not None
        self._flush_pending()
        keys, outs = _ctype_key_value(key, out)
        if _telemetry.enabled():
            nbytes = _payload_bytes(outs)
            _telemetry.counter("kvstore.pull.bytes").inc(nbytes)
            pull_span = _telemetry.span(
                "kvstore.pull", _hist="kvstore.pull.seconds",
                keys=len(keys), bytes=nbytes)
        else:
            pull_span = _telemetry.null_span
            _telemetry.flightrec.note("kvstore.pull", keys=len(keys))
        try:
            with pull_span:
                srcs, shardings, targets = [], [], []
                for k, olist in zip(keys, outs):
                    if k not in self._store:
                        raise MXNetError(f"key {k!r} not initialized")
                    src = self._store[k]
                    for o in olist:
                        # land the value in the destination's existing
                        # placement (keeps mesh-sharded arrays sharded)
                        srcs.append(src.asjax())
                        shardings.append(o.asjax().sharding)
                        targets.append(o)
                if srcs:
                    placed = jax.device_put(srcs, shardings)
                    for o, val in zip(targets, placed):
                        o._set(val)
        except Exception as exc:
            _telemetry.flightrec.on_crash(exc, where="kvstore.pull")
            raise

    def _flush_pending(self):
        """Apply deferred pushes (dist bucket scheduler); no-op here."""

    def close(self, abort=False):
        """Release background resources (dist heartbeats). Idempotent on
        every store kind; ``abort=True`` (dist) additionally drops any
        staged-but-undispatched gradients instead of flushing them —
        the right teardown when a peer is dead and a flush would fail
        against the broken collective."""
        self._closed = True

    # ------------------------------------------------------ failure surface
    def get_dead_nodes(self, timeout_ms=2000):
        """Ranks currently considered dead (single-process: none)."""
        return []

    def on_dead_node(self, callback, period=None):
        """Register a dead-worker callback. The dist store arms a
        watcher thread that fires ``callback(dead_ranks)`` once on the
        first detection; a single-process store has no peers to lose,
        so this is a documented no-op returning False."""
        return False

    # ------------------------------------------------------------ optimizer
    def set_optimizer(self, optimizer):
        """reference: kvstore.py:226 — local mode installs the updater
        closure; dist mode ships the (pickled) optimizer to the server.
        Here there is no server: always install locally."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        pass

    # --------------------------------------------------------- persistence
    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        self._flush_pending()       # states must reflect every push
        states = {k: v.asnumpy() if isinstance(v, NDArray) else v
                  for k, v in getattr(self._updater, "states", {}).items()}
        with open(fname, "wb") as fout:
            pickle.dump(states, fout)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as fin:
            states = pickle.load(fin)
        self._updater.states.update(states)


class KVStoreDistSync(KVStore):
    """dist_sync over the JAX distributed runtime.

    reference semantics: kvstore_dist.h ZPush/ZPull + server merge-all-then-
    update (kvstore_dist_server.h:164-198). Realization: every process holds
    a replica; push() all-reduces the gradients across processes (one XLA
    collective over DCN/ICI per bucket), then the updater runs identically
    on every replica — the arithmetic invariant of dist_sync (nightly test
    formula) holds because sum-then-update on N replicas == server-side
    update.

    Unlike the reference's per-key ZPush, a multi-key push() batches every
    key of the call into large flat buckets (cap: MXNET_KVSTORE_BUCKET_BYTES,
    default 64 MiB) and all-reduces each bucket as ONE jitted XLA program —
    the analog of the reference batching gradients into its pinned merge
    buffers (comm.h InitMergeBuffer).

    Buckets run through a ready-order scheduler (kvstore_sched.py):
    ``push`` only *stages* gradients — in priority order, reverse
    execution order for Module's grads — and each bucket's collective
    dispatches asynchronously the moment the bucket fills, pipelining
    behind backward compute and each other. The host blocks (and the
    updater runs) only at ``pull``/barrier/state reads. Set
    ``MXNET_KVSTORE_OVERLAP=0`` to apply every push synchronously (the
    pre-overlap serial behavior).
    """

    _HB_PREFIX = "mxnet_kvstore_heartbeat/"

    def __init__(self, kind):
        super().__init__(kind)
        init_distributed()
        self._nproc = jax.process_count()
        self._mesh = None
        self._sum_jit = None
        self._sum_jit_shapes = set()     # (dtype, padded-len) size classes
        self._hb_stop = None
        self._hb_thread = None
        self._watch_stop = None          # dead-node watcher (on_dead_node)
        self._watch_thread = None
        self._closed = False
        # fleet identity: ring records / trace spans / ops endpoint now
        # resolve their rank from this live store (weakref'd — a closed
        # store stops answering)
        _telemetry.fleet.register_kvstore(self)
        self._sched = BucketScheduler(
            self._allreduce_flat, self._apply_reduced,
            lambda: int(os.environ.get("MXNET_KVSTORE_BUCKET_BYTES",
                                       64 << 20)))
        if self._nproc > 1:
            client = _coordination_client()
            if client is not None and not hasattr(client,
                                                  "get_live_nodes"):
                self._start_heartbeats(client)

    def _start_heartbeats(self, client):
        """ps-lite-style heartbeats for jax builds whose coordination
        client has no ``get_live_nodes``: each rank periodically writes
        its wall clock under a well-known key in the coordination KV
        store (reference: ps-lite van.cc Heartbeat), and
        ``get_num_dead_node`` counts ranks whose last beat went stale.
        The first beat lands synchronously so a freshly constructed
        store is immediately visible to its peers."""
        import threading
        import time as _time
        horizon = int(os.environ.get("PS_HEARTBEAT_TIMEOUT", "100"))
        period = max(1.0, horizon / 3.0)
        key = f"{self._HB_PREFIX}{self.rank}"

        def beat():
            try:
                client.key_value_set(key, repr(_time.time()),
                                     allow_overwrite=True)
            except Exception:
                pass        # a dying coordinator must not kill training

        beat()
        stop = threading.Event()

        def loop():
            while not stop.wait(period):
                beat()

        thread = threading.Thread(target=loop, daemon=True,
                                  name="mxnet-kvstore-heartbeat")
        thread.start()
        self._hb_stop = stop
        self._hb_thread = thread

    def close(self, abort=False):
        """Flush pending pushes and stop/join the heartbeat and
        dead-node watcher threads so a discarded store can't leak
        threads across a test suite (or keep beating for a rank that
        logically left the job). Idempotent: a second close is a no-op,
        so teardown paths (fit cleanup, recovery, __del__, user code)
        can all call it without coordination. ``abort=True`` drops any
        staged-but-undispatched gradients instead of flushing — the
        recovery teardown, where a flush would re-enter the collective
        a dead peer already broke."""
        if self._closed:
            return
        self._closed = True
        if abort:
            self._sched.drop_pending()
        else:
            self._flush_pending()
        for stop, thread in ((self._watch_stop, self._watch_thread),
                             (self._hb_stop, self._hb_thread)):
            if stop is not None:
                stop.set()
                if thread is not None and \
                        thread is not threading.current_thread():
                    thread.join(timeout=5)
        self._watch_stop = self._watch_thread = None
        self._hb_stop = self._hb_thread = None

    def __del__(self):
        try:
            for stop in (self._hb_stop, self._watch_stop):
                if stop is not None:
                    stop.set()
        except Exception:
            pass        # interpreter teardown

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return self._nproc

    # ------------------------------------------------------- collective core
    def _ensure_mesh(self):
        if self._mesh is not None:
            return
        from jax.sharding import Mesh, PartitionSpec, NamedSharding
        # (process x local-device) mesh: every chip on every host joins
        # the reduction — the analog of the reference's dist_device_sync
        # (local GPU reduce + PS across nodes, comm.h:289-361). The
        # buffer is split over the local axis, so each local device
        # reduces (and moves over DCN) only its slice, multiplying
        # cross-host bandwidth by the local device count.
        by_proc = {}
        for d in sorted(jax.devices(), key=lambda d: (d.process_index,
                                                      d.id)):
            by_proc.setdefault(d.process_index, []).append(d)
        counts = {len(v) for v in by_proc.values()}
        if len(counts) != 1:
            raise MXNetError(
                f"uneven local device counts across processes: "
                f"{sorted(counts)}")
        self._local = counts.pop()
        devs = np.array([by_proc[p] for p in range(self._nproc)])
        self._mesh = Mesh(devs, ("proc", "dev"))
        self._pspec = PartitionSpec
        self._sum_jit = jax.jit(
            lambda x: jnp.sum(x, axis=0),
            out_shardings=NamedSharding(self._mesh,
                                        PartitionSpec("dev")))

    def _size_class(self, n):
        """Padded length for a flat buffer: the local device count L
        times the next power of two of ceil(n/L). Tiny/odd gradient
        lengths then share O(log max-size) padded shapes instead of
        minting a fresh ``_sum_jit`` trace per unique length."""
        chunk = max(1, -(-n // self._local))
        chunk = 1 << (chunk - 1).bit_length()
        return chunk * self._local

    def _allreduce_flat(self, flat):
        """All-reduce one 1-D buffer, retrying transient failures.

        The dispatch is wrapped in the shared retry policy
        (``MXNET_RETRY_COLLECTIVE``, docs/faults.md): a TRANSIENT
        collective error (flaky DCN link, coordination-service blip, an
        injected ``kvstore.collective`` fault) retries with backoff and
        is invisible to the caller; a failure with an actually-dead
        peer converts to :class:`checkpoint.DeadWorkerError` IMMEDIATELY
        (the liveness layer decides — burning the backoff budget
        against a peer that will never answer just delays recovery); a
        persistent failure with every peer alive re-raises the original
        error after the policy gives up (a real bug, not a death).
        Retry is safe here because a failed dispatch applied nothing:
        every worker that failed re-enters the same collective in the
        same order (policies must match across workers — env-configured,
        docs/faults.md). Failures surfacing later, at the flush-side
        ``block_until_ready``, go through ``Module.fit``'s existing
        dead-worker conversion instead.
        """
        def give_up(exc):
            from .checkpoint.recovery import DeadWorkerError
            if isinstance(exc, DeadWorkerError):
                return exc
            try:
                dead = self.get_dead_nodes()
            except Exception:
                dead = []
            if dead:
                _telemetry.flightrec.note("recovery.dead_worker",
                                          ranks=list(dead), clean=False,
                                          where="kvstore.collective")
                return DeadWorkerError(dead, clean=False)
            return None

        return _faults.retry_call(
            lambda: self._allreduce_flat_once(flat),
            _faults.RetryPolicy.from_env("COLLECTIVE", attempts=3,
                                         base_s=0.02, max_s=0.5),
            site="kvstore.collective", give_up=give_up,
            logger=logging.getLogger(__name__))

    def _allreduce_flat_once(self, flat):
        """One all-reduce attempt across all devices of all processes.

        Layout: pad to the power-of-two size class (multiple of the
        local device count L), view as (1, L, chunk) sharded
        (proc, dev), sum over proc with the result sharded over dev;
        every process then reassembles the full reduced buffer from its
        own local shards (replicated-across-proc output). Single-process
        stores run the same program over the (1, L) mesh — the
        local-device reduction path is identical, only the proc axis is
        trivial.
        """
        _faults.point("kvstore.collective")
        from jax.experimental import multihost_utils
        self._ensure_mesh()
        if _telemetry.enabled():
            nbytes = int(flat.size) * flat.dtype.itemsize
            ar_span = _telemetry.span(
                "kvstore.allreduce", _hist="kvstore.allreduce.seconds",
                bytes=nbytes)
        else:
            ar_span = _telemetry.null_span
        with ar_span:
            n = flat.shape[0]
            padded = self._size_class(n)
            if padded != n:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((padded - n,), flat.dtype)])
            self._sum_jit_shapes.add((str(flat.dtype), padded))
            _telemetry.gauge("kvstore.allreduce.size_classes").set(
                len(self._sum_jit_shapes))
            from jax.sharding import NamedSharding
            if self._nproc == 1:
                # single process owns every mesh device: plain resharding
                # device_puts replace the multihost host-local<->global
                # conversions, keeping the whole reduction async (no host
                # sync at dispatch — the overlap window of the bucket
                # scheduler)
                x = flat.reshape(1, self._local, -1)
                glob = jax.device_put(
                    x, NamedSharding(self._mesh,
                                     self._pspec("proc", "dev")))
                return jnp.ravel(self._sum_jit(glob))[:n]
            # a gradient pushed from a multi-device (mesh-replicated)
            # executor arrives with >1 local shard; the host-local
            # conversion below needs ONE process-local array
            if getattr(flat, "sharding", None) is not None and \
                    len(flat.sharding.device_set) > 1:
                flat = jax.device_put(
                    flat, flat.addressable_shards[0].device)
            x = flat.reshape(1, self._local, -1)
            glob = multihost_utils.host_local_array_to_global_array(
                x, self._mesh, self._pspec("proc", "dev"))
            red = self._sum_jit(glob)
            loc = multihost_utils.global_array_to_host_local_array(
                red, self._mesh, self._pspec("dev"))
            out = jnp.ravel(loc)
            return out[:n] if padded != n else out

    def _allreduce(self, arrs):
        """Unbucketed reference path: one collective per array. The hot
        path is the bucket scheduler (push/_sched); this remains as the
        equivalence oracle the bucketed path is tested against."""
        return [self._allreduce_flat(jnp.ravel(jnp.asarray(a.asjax()
                if isinstance(a, NDArray) else a))).reshape(a.shape)
                for a in arrs]

    # ----------------------------------------------------------------- push
    def push(self, key, value, priority=0):
        """Stage gradients into the ready-order bucket scheduler.

        ``priority`` may be a scalar (the reference API) or one value
        per key; higher priorities dispatch earlier. Collectives for
        full buckets go on the wire inside this call — asynchronously —
        and the updater runs at the next ``pull``/barrier/state read
        (immediately under ``MXNET_KVSTORE_OVERLAP=0``)."""
        keys, vals = _ctype_key_value(key, value)
        prios = list(priority) if isinstance(priority, (list, tuple)) \
            else [priority] * len(keys)
        if len(prios) != len(keys):
            raise MXNetError(
                f"got {len(prios)} priorities for {len(keys)} keys")
        if _telemetry.enabled():
            nbytes = _payload_bytes(vals)
            _telemetry.counter("kvstore.push.bytes").inc(nbytes)
            push_span = _telemetry.span(
                "kvstore.push", _hist="kvstore.push.seconds",
                keys=len(keys), bytes=nbytes, dist=True)
        else:
            push_span = _telemetry.null_span
            _telemetry.flightrec.note("kvstore.push", keys=len(keys),
                                      dist=True)
        try:
            with push_span:
                # one arrival epoch per caller-level push: the static
                # collective-order checker (analysis rule CO301) treats
                # equal-priority keys from different epochs as
                # ready-order — i.e. nondeterministic across workers
                self._sched.note_push_call()
                for k, vlist, prio in zip(keys, vals, prios):
                    if k not in self._store:
                        raise MXNetError(f"key {k!r} not initialized")
                    acc = vlist[0].asjax()
                    for v in vlist[1:]:
                        acc = acc + v.asjax()
                    self._sched.stage(k, vlist[0].context, acc, prio)
                if os.environ.get("MXNET_KVSTORE_OVERLAP", "1") == "0":
                    self._sched.flush()
        except Exception as exc:
            _telemetry.flightrec.on_crash(exc, where="kvstore.push")
            raise

    def _apply_reduced(self, k, ctx, red):
        """Scheduler callback: one key's bucket segment, reduced."""
        # The bucketed all-reduce hands back each value sharded over the
        # local `dev` mesh axis (bandwidth layout). The store replica and
        # its optimizer state live wherever the user placed the weight —
        # re-place the reduced gradient there so the updater's inputs are
        # colocated (the analog of the reference copying the merged
        # buffer back to each GPU, comm.h Broadcast).
        store_sharding = self._store[k].asjax().sharding
        if red.sharding != store_sharding:
            red = jax.device_put(red, store_sharding)
        nd_val = NDArray(red, ctx=ctx)
        if self._updater is not None:
            self._updater(k, nd_val, self._store[k])
        else:
            self._store[k]._set(nd_val.asjax())

    def _flush_pending(self):
        try:
            self._sched.flush()
        except Exception as exc:
            _telemetry.flightrec.on_crash(exc, where="kvstore.push")
            raise

    def _barrier(self):
        self._flush_pending()
        if self._nproc > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    # ------------------------------------------------------ failure surface
    def get_dead_nodes(self, timeout_ms=2000):
        """Ranks currently considered dead (reference:
        kvstore_dist.h:159-168 GetDeadNodes over ps-lite heartbeats).
        One-sided: queries the coordination service's liveness tracking
        (``get_live_nodes`` where the client has it, else this store's
        own heartbeat keys) — any single rank can call this at any
        time, no peer cooperation needed. ``timeout_ms`` bounds the
        per-rank key wait in the heartbeat fallback; the native path
        applies the service's own heartbeat timeout. Returns a sorted
        rank list, the input the elastic-recovery rank remapping
        (checkpoint/recovery.survivor_env) needs — a bare count can't
        say WHO to exclude from the re-formed job."""
        if self._nproc <= 1:
            return []
        client = _coordination_client()
        if client is None:
            return []
        me = self.rank
        if hasattr(client, "get_live_nodes"):
            live = set(client.get_live_nodes(list(range(self._nproc))))
            return sorted(r for r in range(self._nproc)
                          if r not in live and r != me)
        # heartbeat fallback: a rank whose beat is missing or older than
        # PS_HEARTBEAT_TIMEOUT counts as dead (its last value stays in
        # the KV store, so a crashed peer reads back instantly as stale)
        import time as _time
        horizon = float(os.environ.get("PS_HEARTBEAT_TIMEOUT", "100"))
        wait_ms = max(100, int(timeout_ms) // self._nproc)
        dead = []
        for r in range(self._nproc):
            if r == me:
                continue    # a running rank can never observe itself dead
            try:
                ts = float(client.blocking_key_value_get(
                    f"{self._HB_PREFIX}{r}", wait_ms))
                if _time.time() - ts > horizon:
                    dead.append(r)
            except Exception:
                dead.append(r)      # never wrote a beat: not alive yet
        return dead

    def get_num_dead_node(self, node_id=0, timeout_ms=2000):
        """Count of dead workers (the reference-shaped polling API;
        ``get_dead_nodes`` adds the rank identities)."""
        return len(self.get_dead_nodes(timeout_ms=timeout_ms))

    def on_dead_node(self, callback, period=None):
        """Arm a watcher thread that calls ``callback(dead_ranks)`` ONCE
        when the liveness layer first reports a dead peer — the push
        seam the elastic-recovery path hangs off (polling
        ``get_num_dead_node`` from the training loop would either lag
        detection by a batch or tax every batch with a liveness RPC).

        The callback runs on the watcher thread: implementations should
        only record the event (set a flag, bump a counter) and let the
        training thread act at its next safe boundary. The watcher
        exits after firing (re-arm by calling again); ``close()`` stops
        an unfired watcher. Returns True when armed, False when there
        is nothing to watch (single process)."""
        if self._nproc <= 1 or self._closed:
            return False
        if self._watch_stop is not None:
            self._watch_stop.set()          # replace a previous watcher
        horizon = float(os.environ.get("PS_HEARTBEAT_TIMEOUT", "100"))
        period = max(0.2, horizon / 5.0) if period is None else \
            float(period)
        stop = threading.Event()

        def watch():
            while not stop.wait(period):
                try:
                    dead = self.get_dead_nodes()
                except Exception:
                    continue        # a flaky liveness query isn't a death
                if dead:
                    _telemetry.counter("recovery.events").inc()
                    _telemetry.flightrec.note("recovery.dead_node",
                                              ranks=list(dead))
                    if _telemetry.enabled():
                        _telemetry.record_event("dead_node",
                                                ranks=list(dead))
                    try:
                        callback(list(dead))
                    except Exception:
                        logging.getLogger(__name__).exception(
                            "on_dead_node callback failed")
                    return
        thread = threading.Thread(target=watch, daemon=True,
                                  name="mxnet-kvstore-deadwatch")
        thread.start()
        self._watch_stop = stop
        self._watch_thread = thread
        return True


def create(name="local"):
    """Factory. reference: src/kvstore/kvstore.cc:17-45 (substring match)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist_async" in name:
        raise MXNetError(
            "dist_async has no TPU-native equivalent: asynchronous "
            "parameter-server updates do not map onto XLA collectives "
            "(SURVEY.md §7). Use dist_sync (all-reduce) instead.")
    if "dist" in name:
        return KVStoreDistSync(name)
    if "device" in name or "local" in name:
        return KVStore(name)
    raise MXNetError(f"unknown kvstore type {name!r}")
