"""Executor: binds a Symbol to a device and runs it.

Reference pipeline (reference: src/executor/graph_executor.cc:333-446):
``Bind`` runs Gradient/PlaceDevice/InferShape/PlanMemory passes, allocates a
memory pool, wraps nodes in cached engine ops, and ``Forward``/``Backward``
push them to the dependency engine.

TPU-native pipeline: ``bind`` topologically closes the Symbol into ONE pure
JAX function and hands it to ``jax.jit`` — XLA performs memory planning,
fusion, scheduling and (on request) ``jax.vjp`` performs the Gradient pass.
Three compiled programs are built lazily per executor:

  * ``fwd_infer``  — forward, is_train=False (prediction path);
  * ``fwd_train``  — forward, is_train=True (dropout on, BN batch stats);
  * ``fwd_bwd``    — forward + cotangent propagation in a single XLA
    program — the analog of the reference's bulk-exec segment covering the
    whole fwd+bwd graph (graph_executor.cc:678-756), and the hot path of
    ``Module.fit``.

Laziness contract: ``forward(is_train=True)`` only *records* inputs; the
computation happens on first access of ``outputs`` (fwd program) or at
``backward()`` (fused program) — so a ``forward_backward`` pair costs exactly
one XLA execution, like the reference's single engine pass, while
``forward``-then-read still behaves eagerly from the caller's view.

Mutation contract: ``backward()`` applies ``grad_req`` (write/add) by
swapping new buffers into the bound grad NDArrays; aux states (BN moving
stats) are swapped after every training forward — Python aliases stay
coherent because NDArray is a mutable cell (see ndarray.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import current_context
from .ndarray import NDArray, zeros as nd_zeros
from .ops.registry import get_op
from . import kernel_tier as _kernel_tier
from . import program_cache as _progcache
from . import random as _random
from . import telemetry as _telemetry

__all__ = ["Executor", "naive_engine_active"]


def naive_engine_active():
    """True when ``MXNET_ENGINE_TYPE=NaiveEngine`` — the one-switch
    deterministic debug mode (reference: env_var.md:33-40, engine
    selection src/engine/engine.cc:13-40). Executor programs then run
    un-jitted, op by op, each op forced to completion before the next —
    serial replay for debugging, exactly what the reference's error
    message recommends (threaded_engine.h:330-338). Read at use time so
    tests (and users mid-session) can flip it."""
    import os
    return os.environ.get("MXNET_ENGINE_TYPE") == "NaiveEngine"


class _LazyOutputs:
    """List-like view of an executor's outputs that defers execution.

    ``forward(is_train=True)`` must not force the forward program: the
    hot path is ``backward()``'s single fused fwd+bwd XLA execution, and
    materializing here would run the forward twice per training step.
    Any actual access (len/index/iter) materializes via the ``outputs``
    property.
    """

    __slots__ = ("_exe",)

    def __init__(self, exe):
        self._exe = exe

    def _mat(self):
        return self._exe.outputs

    def __len__(self):
        return len(self._mat())

    def __getitem__(self, i):
        return self._mat()[i]

    def __iter__(self):
        return iter(self._mat())

    def __repr__(self):
        return repr(self._mat())


def _build_graph_runner(symbol, shape_overrides=None, tap=None, mp_plan=None,
                        compute_dtype=None, remat_segments=0,
                        spmd_plan=None):
    """Close the symbol graph into run(arg_vals, aux_vals, is_train, rng).

    Returns (runner, arg_names, aux_names, loss_mask). The runner is pure:
    dict-of-arrays in, (outputs, new_aux_dict) out — directly jittable.

    ``shape_overrides`` maps id(node) -> concrete shape for init-style ops
    whose declared shape had unknown (0) dims — e.g. RNN begin_state
    ``sym.zeros(shape=(0, H))`` resolved to the bound batch size (the
    reference resolves these in PlanMemory; here at runner-build time).

    ``tap(node, outputs)`` — optional per-op observation hook called after
    every non-variable node (the analog of the reference's per-op monitor
    callback, graph_executor.cc:758-778). Only meaningful when the runner
    executes un-jitted (eager per-op dispatch).

    ``mp_plan`` — optional ModelParallelPlan (parallel/placement.py): its
    boundary constraints are applied to cross-ctx_group edges, lowering
    the reference's PlaceDevice/_CrossDeviceCopy onto sharding
    constraints that XLA turns into collectives.

    ``compute_dtype`` — mixed precision: float variables are cast to this
    dtype (normally bfloat16 -> MXU-native matmuls/convs) at graph entry
    while the bound arrays (master params) stay float32; the cast's vjp
    upcasts gradients back automatically. Labels feeding a loss head are
    exempt (class indices above 256 don't survive a bfloat16 roundtrip).

    ``remat_segments`` — gradient mirroring (reference:
    MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:210-223): when > 1, the
    compute nodes are split into that many contiguous segments and each is
    wrapped in ``jax.checkpoint``, so backward stores only segment-boundary
    activations and recomputes the interior — sqrt(N)-checkpointing bounds
    activation memory for deep unrolled graphs.

    Every op executes under ``jax.named_scope(node.name)``, so compiled
    HLO instructions carry Symbol node names into xplane/profiler traces —
    the analog of the reference's PROFILER_MESSAGE per-op naming
    (threaded_engine.h:296-307).
    """
    nodes = symbol._topo_nodes()
    node_index = {id(n): i for i, n in enumerate(nodes)}
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    shape_overrides = shape_overrides or {}

    # NHWC layout pass (ops/layout.py): on for the compiled hot path;
    # debug runners (monitor tap, NaiveEngine) and model-parallel plans
    # stay reference-layout so per-op observations match the reference
    from .ops import layout as _layout
    layout_opt = (tap is None and mp_plan is None
                  and _layout.layout_opt_enabled())
    entry_tags = {}     # (node_idx, out_idx) -> True when value is NHWC
    loss_mask = []
    for node, _ in symbol._outputs:
        loss_mask.append(bool(not node.is_variable and
                              node.opdef().is_loss))

    # variables fed straight into a loss head's label slot keep their
    # dtype under mixed precision (class ids must stay exact)
    label_names = set()
    if compute_dtype is not None:
        compute_dtype = np.dtype(compute_dtype)
        for node in nodes:
            if not node.is_variable and node.opdef().is_loss:
                for inp, _ in node.inputs[1:]:
                    if inp.is_variable:
                        label_names.add(inp.name)

    def _load_var(val, name):
        if (compute_dtype is not None and name not in label_names
                and jnp.issubdtype(val.dtype, jnp.floating)):
            return val.astype(compute_dtype)
        return val

    def _exec_node(i, get_in, arg_vals, aux_vals, is_train, rng, new_aux):
        """Run compute node i; inputs via get_in((producer_idx, out_idx))."""
        node = nodes[i]
        opdef = node.opdef()
        attrs = node.attrs
        if id(node) in shape_overrides:
            attrs = {**attrs, "shape": shape_overrides[id(node)]}
        aux_n = len(opdef.aux_names(attrs))
        in_entries, in_tags = [], []
        for inp, idx in node.inputs:
            if inp.is_variable:
                if inp._extra.get("__is_aux__"):
                    in_entries.append(_load_var(aux_vals[inp.name],
                                                inp.name))
                else:
                    in_entries.append(_load_var(arg_vals[inp.name],
                                                inp.name))
                in_tags.append(False)
            else:
                key = (node_index[id(inp)], idx)
                in_entries.append(get_in(key))
                in_tags.append(entry_tags.get(key, False))
        regular = in_entries[:len(in_entries) - aux_n] if aux_n \
            else in_entries
        aux = in_entries[len(in_entries) - aux_n:] if aux_n else []
        krng = jax.random.fold_in(rng, i) if opdef.need_rng else None
        # per-op attribution: dispatch counts per registered op plus a
        # span per node execution. Under jax.jit this fires at trace time
        # (once per compile — the spans nest under executor.compile);
        # under the NaiveEngine/tapped runners it fires per step with
        # real per-op wall time, the reference's per-op profile records.
        if _telemetry.enabled():
            _telemetry.counter("executor.op_dispatch", op=node.op).inc()
            # cost attribution rides the same trace-time hook: per-op
            # FLOPs/bytes totals for one program execution accumulate
            # under the op label (telemetry/mfu.py reads them back)
            op_cost = opdef.cost(attrs, [tuple(v.shape) for v in regular])
            if op_cost is not None:
                _telemetry.counter("executor.op_flops",
                                   op=node.op).inc(op_cost[0])
                _telemetry.counter("executor.op_bytes",
                                   op=node.op).inc(op_cost[1])
            op_span = _telemetry.span("op." + node.op, node=node.name)
        else:
            op_span = _telemetry.null_span
        with op_span, jax.named_scope(node.name):
            out_tags = None
            if layout_opt:
                res = _layout.nhwc_exec(opdef, attrs, regular, aux,
                                        in_tags[:len(regular)],
                                        is_train, krng)
                if res is not None:
                    outs, aux_out, out_tags = res
            if out_tags is None:
                regular = [_layout.to_nchw(x) if t else x
                           for x, t in zip(regular, in_tags)]
                outs, aux_out = _kernel_tier.dispatch(
                    opdef, attrs, regular, aux, is_train, krng,
                    spmd_plan=spmd_plan)
                out_tags = [False] * len(outs)
        for j, t in enumerate(out_tags):
            entry_tags[(i, j)] = t
        if mp_plan is not None:
            outs = mp_plan.constrain(id(node), outs)
        if tap is not None:
            tap(node, outs)
        # training aux (BatchNorm moving stats) updates only under
        # is_train; a stateful_infer op (KV-cache decode) reads AND
        # writes its aux on inference forwards too — the cache advance
        # IS the inference step's side effect
        if aux_n and (is_train or opdef.stateful_infer):
            for (inp, _), new_val in zip(
                    node.inputs[len(node.inputs) - aux_n:], aux_out):
                new_aux[inp.name] = new_val
        return outs

    out_entries = []
    for n, i in symbol._outputs:
        if n.is_variable:
            out_entries.append(("var", n.name,
                                bool(n._extra.get("__is_aux__"))))
        else:
            out_entries.append(("node", node_index[id(n)], i))

    def _emit_outputs(get_entry, arg_vals, aux_vals):
        outs = []
        for ent in out_entries:
            if ent[0] == "var":
                src = aux_vals if ent[2] else arg_vals
                outs.append(_load_var(src[ent[1]], ent[1]))
            else:
                o = get_entry((ent[1], ent[2]))
                # user-visible outputs are always reference-layout NCHW
                if entry_tags.get((ent[1], ent[2]), False):
                    o = _layout.to_nchw(o)
                outs.append(o)
        return outs

    compute_idx = [i for i, n in enumerate(nodes) if not n.is_variable]

    def run(arg_vals, aux_vals, is_train, rng):
        vals = {}       # (node_idx, out_idx) -> array
        new_aux = {}
        for i in compute_idx:
            outs = _exec_node(i, vals.__getitem__, arg_vals, aux_vals,
                              is_train, rng, new_aux)
            for j, o in enumerate(outs):
                vals[(i, j)] = o
        outputs = _emit_outputs(vals.__getitem__, arg_vals, aux_vals)
        return outputs, new_aux

    if remat_segments and remat_segments > 1 and len(compute_idx) > 2:
        run = _segmented_runner(
            nodes, node_index, compute_idx, out_entries, _exec_node,
            _emit_outputs, min(int(remat_segments), len(compute_idx)))

    return run, arg_names, aux_names, loss_mask


def _segmented_runner(nodes, node_index, compute_idx, out_entries,
                      exec_node, emit_outputs, n_seg):
    """sqrt(N)-style remat: contiguous node segments under jax.checkpoint.

    Only segment-boundary entries (values consumed by a later segment or
    emitted as outputs) thread through the carry; everything interior to a
    segment is recomputed during backward instead of stored. The carry is
    a dict keyed "i:j" (producer node index : output index) so it stays a
    plain jittable pytree.
    """
    seg_size = -(-len(compute_idx) // n_seg)
    segments = [compute_idx[k:k + seg_size]
                for k in range(0, len(compute_idx), seg_size)]
    seg_of = {}
    for s, seg in enumerate(segments):
        for i in seg:
            seg_of[i] = s

    # liveness: last segment that still reads each escaping entry
    # (outputs live to the very end); dead entries drop out of the carry
    # at each boundary so the stored set stays minimal
    last_use = {}
    for i in compute_idx:
        for inp, idx in nodes[i].inputs:
            if not inp.is_variable:
                p = node_index[id(inp)]
                if seg_of[p] != seg_of[i]:
                    key = (p, idx)
                    last_use[key] = max(last_use.get(key, -1), seg_of[i])
    for ent in out_entries:
        if ent[0] == "node":
            last_use[(ent[1], ent[2])] = len(segments)

    def run(arg_vals, aux_vals, is_train, rng):
        def make_seg(s, seg_nodes):
            def seg_fn(carry, rng_in):
                local = {}
                new_aux_loc = {}

                def get_in(key):
                    if key in local:
                        return local[key]
                    return carry[f"{key[0]}:{key[1]}"]

                for i in seg_nodes:
                    outs = exec_node(i, get_in, arg_vals, aux_vals,
                                     is_train, rng_in, new_aux_loc)
                    for j, o in enumerate(outs):
                        local[(i, j)] = o
                out = {}
                for k, v in carry.items():
                    if k.startswith("aux:") or \
                            last_use[tuple(map(int, k.split(":")))] > s:
                        out[k] = v
                for key, lu in last_use.items():
                    if key in local and lu > s:
                        out[f"{key[0]}:{key[1]}"] = local[key]
                for nm, v in new_aux_loc.items():
                    out[f"aux:{nm}"] = v
                return out
            return seg_fn

        carry = {}
        for s, seg_nodes in enumerate(segments):
            carry = jax.checkpoint(make_seg(s, seg_nodes))(carry, rng)
        new_aux = {k[4:]: v for k, v in carry.items()
                   if k.startswith("aux:")}
        outputs = emit_outputs(
            lambda key: carry[f"{key[0]}:{key[1]}"], arg_vals, aux_vals)
        return outputs, new_aux

    return run


class Executor:
    """reference: include/mxnet/executor.h + python/mxnet/executor.py."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None,
                 compute_dtype=None, mirror=None, validate=None,
                 mesh_token=None, spmd_plan=None):
        self._symbol = symbol
        self._ctx = ctx
        # the binding's SpmdPlan (spmd exec groups): threaded into the
        # kernel tier so plan-dependent lowerings (the attention op's
        # sequence-sharded ring variant) can be selected at trace time
        self._spmd_plan = spmd_plan
        # device-topology token for the program-cache key: compiled
        # programs bake in their mesh's collective structure (psum /
        # reduce-scatter shard counts), so a binding over a different
        # mesh or device must never reuse them. Exec groups pass their
        # mesh/plan token; direct bindings key on the single device.
        self._mesh_token = mesh_token if mesh_token is not None else \
            ("dev", ctx.device_type, int(getattr(ctx, "device_id", 0)))
        self._group2ctx = group2ctx or {}
        self._compute_dtype = compute_dtype
        self._monitor_callback = None
        self.output_names = symbol.list_outputs()

        # gradient mirroring (reference: MXNET_BACKWARD_DO_MIRROR,
        # graph_executor.cc:210-223): True -> sqrt(N) segments under
        # jax.checkpoint; an int picks the segment count explicitly
        if mirror is None:
            import os as _os
            mirror = _os.environ.get("MXNET_BACKWARD_DO_MIRROR",
                                     "0").lower() in ("1", "true")
        if mirror is True:
            n_compute = sum(1 for n in symbol._topo_nodes()
                            if not n.is_variable)
            self._remat_segments = max(2, int(np.ceil(np.sqrt(n_compute))))
        elif mirror:
            self._remat_segments = int(mirror)
        else:
            self._remat_segments = 0

        # ---- normalize arg arrays -------------------------------------
        arg_names_all = symbol.list_arguments()
        self.arg_arrays = self._normalize_args(args, arg_names_all, "args")

        # resolve init-op nodes declared with unknown (0) dims — e.g. RNN
        # begin_state zeros(shape=(0, H)) — against the bound arg shapes
        shape_overrides = {}
        try:
            known = {nm: tuple(a.shape)
                     for nm, a in zip(arg_names_all, self.arg_arrays)
                     if a is not None}
            needs = [n for n in symbol._topo_nodes()
                     if not n.is_variable and not n.inputs
                     and isinstance(n.attrs.get("shape"), tuple)
                     and 0 in n.attrs["shape"]]
            if needs:
                entry_shapes = symbol._infer_entry_shapes(known)
                for n in needs:
                    s = entry_shapes[id(n)][0]
                    if s is not None and 0 not in s:
                        shape_overrides[id(n)] = tuple(s)
        except MXNetError:
            pass

        # model parallelism: ctx_group tags + group2ctx -> mesh shardings
        # (reference AssignContext/PlaceDevice, graph_executor.cc:242-331)
        self._mp_plan = None
        if self._group2ctx:
            from .parallel.placement import build_plan
            shapes_by_name = {nm: tuple(a.shape)
                              for nm, a in zip(arg_names_all, self.arg_arrays)
                              if a is not None}
            self._mp_plan = build_plan(symbol, self._group2ctx,
                                       shapes_by_name)

        self._shape_overrides = shape_overrides
        with _telemetry.span("executor.bind",
                             _hist="executor.bind.seconds",
                             outputs=len(self.output_names)):
            self._runner, self.arg_names, self.aux_names, self._loss_mask = \
                _build_graph_runner(symbol, shape_overrides,
                                    mp_plan=self._mp_plan,
                                    compute_dtype=compute_dtype,
                                    remat_segments=self._remat_segments,
                                    spmd_plan=spmd_plan)
        self.aux_arrays = self._normalize_args(aux_states, self.aux_names,
                                               "aux_states", allow_none=True)
        self.grad_req = self._normalize_req(grad_req)
        self.grad_arrays = self._normalize_grads(args_grad)

        if self._mp_plan is not None:
            # re-place every bound array per the plan (params sharded over
            # the model axis, the rest replicated across the mesh)
            for nm, arr in zip(self.arg_names, self.arg_arrays):
                if arr is not None:
                    arr._set(self._mp_plan.place(nm, arr.asjax()))
            for nm, arr in zip(self.arg_names, self.grad_arrays):
                if arr is not None:
                    arr._set(self._mp_plan.place(nm, arr.asjax()))
            for arr in self.aux_arrays:
                if arr is not None:
                    arr._set(jax.device_put(arr.asjax(),
                                            self._mp_plan.replicated))

        # compiled program cache, two levels: the per-instance dict is
        # the fast path, and cacheable bindings (no model-parallel plan)
        # also consult the process-wide program_cache so rebinds
        # (train→eval, force_rebind, bucketing over a shared_group)
        # reuse traces instead of recompiling per instance
        self._jit_cache = {}
        self._prog_cache_base = None
        if self._mp_plan is None:
            from .ops import layout as _layout_mod
            try:
                self._prog_cache_base = (
                    _progcache.symbol_signature(symbol),
                    tuple((nm, tuple(a.shape), str(a.dtype))
                          for nm, a in zip(self.arg_names, self.arg_arrays)
                          if a is not None),
                    tuple((nm, tuple(a.shape), str(a.dtype))
                          for nm, a in zip(self.aux_names, self.aux_arrays)
                          if a is not None),
                    ctx.device_type,
                    self._mesh_token,
                    bool(_layout_mod.layout_opt_enabled()),
                    str(compute_dtype) if compute_dtype is not None else None,
                    self._remat_segments,
                )
            except Exception:
                pass           # uncacheable binding: per-instance only
        self._tapped_runner = None   # eager monitored runner (per callback)
        self._naive_runner = None    # NaiveEngine serial replay runner
        self._pending = None      # recorded inputs awaiting execution
        self._outputs = None      # computed output NDArrays
        self._sentinel = None     # optional NaN/Inf tripwire (telemetry)
        # param/grad/aux/output footprint -> registry gauges + flight ring
        self.memory_footprint = _telemetry.memory.record_executor_bind(self)

        # bind-time static analysis (the NNVM InferShape/InferType
        # discipline, analysis/): validate="warn"|"raise" per call, or
        # process-wide via MXNET_GRAPH_VALIDATE. The span keeps the
        # overhead visible (gated <2% of bind by
        # benchmarks/lint_overhead.py).
        from . import analysis as _analysis
        vmode = _analysis.resolve_mode(validate)
        if vmode is not None:
            with _telemetry.span("executor.validate"):
                _analysis.validate_executor(self, vmode)

    # ------------------------------------------------------------ normalize
    def _normalize_args(self, args, names, what, allow_none=False):
        if args is None:
            if allow_none or not names:
                return [None] * len(names)
            raise MXNetError(f"bind requires {what}")
        if isinstance(args, dict):
            out = []
            for nm in names:
                if nm not in args:
                    if allow_none:
                        out.append(None)
                        continue
                    raise MXNetError(f"missing {what} entry {nm!r}")
                out.append(args[nm])
            return out
        args = list(args)
        if len(args) != len(names):
            raise MXNetError(
                f"{what} length {len(args)} != expected {len(names)}")
        return args

    def _normalize_req(self, grad_req):
        if isinstance(grad_req, str):
            return {nm: grad_req for nm in self.arg_names}
        if isinstance(grad_req, (list, tuple)):
            return dict(zip(self.arg_names, grad_req))
        if isinstance(grad_req, dict):
            return {nm: grad_req.get(nm, "null") for nm in self.arg_names}
        raise MXNetError("invalid grad_req")

    def _normalize_grads(self, args_grad):
        if args_grad is None:
            return [None] * len(self.arg_names)
        if isinstance(args_grad, dict):
            return [args_grad.get(nm) for nm in self.arg_names]
        args_grad = list(args_grad)
        if len(args_grad) != len(self.arg_names):
            raise MXNetError("args_grad length mismatch")
        return args_grad

    # ------------------------------------------------------------ dict views
    @property
    def arg_dict(self):
        return dict(zip(self.arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self.arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self.aux_names, self.aux_arrays))

    @property
    def output_dict(self):
        return dict(zip(self.output_names, self.outputs))

    # ------------------------------------------------------------- programs
    def _watched(self):
        return [nm for nm in self.arg_names
                if self.grad_req.get(nm, "null") != "null"]

    def _naive_runner_fn(self):
        """Serial deterministic replay runner for the NaiveEngine debug
        mode: every op executes eagerly (no jit, no XLA fusion) and is
        forced to completion before the next one dispatches — the analog
        of the reference's ``MXNET_ENGINE_TYPE=NaiveEngine`` synchronous
        engine (src/engine/naive_engine.cc; the debugging procedure in
        threaded_engine.h:330-338)."""
        if self._naive_runner is None:
            def tap(node, outs):
                for o in outs:
                    # under jax.vjp the forward replays with tracers;
                    # only concrete arrays can (and need to) block
                    if isinstance(o, jax.Array) and \
                            not isinstance(o, jax.core.Tracer):
                        o.block_until_ready()

            self._naive_runner, *_ = _build_graph_runner(
                self._symbol, self._shape_overrides, tap=tap,
                mp_plan=self._mp_plan,
                compute_dtype=self._compute_dtype)
        return self._naive_runner

    def program_cache_key(self, kind, *extras):
        """Process-wide cache key for one of this binding's programs, or
        None when the binding isn't cacheable (model-parallel plan).
        ``extras`` carries what only this program kind depends on (the
        watched-param set for gradient programs, the optimizer token for
        the fused/scan train steps)."""
        if self._prog_cache_base is None:
            return None
        # the kernel tier is read at trace time (kernel_tier.resolve()
        # inside every op dispatch), so programs traced under different
        # tiers differ even for an identical graph — it must ride every
        # key or a flipped MXNET_KERNEL_TIER reuses stale programs
        return self._prog_cache_base + \
            (("ktier", _kernel_tier.mode()),) + (kind,) + extras

    def _get_program(self, kind):
        from . import remat as _remat
        naive = naive_engine_active()
        # the staged gradient program honors the remat policy too (the
        # fused step applies it in executor_group); the policy rides
        # both cache keys so flipping it mid-process re-traces
        remat_policy = _remat.active() if kind == "fwd_bwd" else "none"
        cache_key = (kind, naive, remat_policy)
        fn = self._jit_cache.get(cache_key)
        if fn is not None:
            if _telemetry.enabled():
                _telemetry.counter("executor.jit_cache.hit").inc()
            return fn
        gkey = None
        if not naive:
            extras = (tuple(self._watched()),
                      ("remat", remat_policy)) if kind == "fwd_bwd" \
                else ()
            gkey = self.program_cache_key(kind, *extras)
            if gkey is not None:
                fn = _progcache.get(gkey)
                if fn is not None:
                    # process-wide hit: another binding of the same
                    # signature already traced this program
                    if _telemetry.enabled():
                        _telemetry.counter("executor.jit_cache.hit").inc()
                    self._jit_cache[cache_key] = fn
                    return fn
        if _telemetry.enabled():
            _telemetry.counter("executor.jit_cache.miss").inc()
        runner = self._naive_runner_fn() if naive else self._runner
        if kind in ("fwd_infer", "fwd_train"):
            is_train = kind == "fwd_train"

            def prog(arg_vals, aux_vals, rng):
                return runner(arg_vals, aux_vals, is_train, rng)

            fn = _telemetry.wrap_dispatch(prog, kind, compiled=False) \
                if naive else _telemetry.wrap_dispatch(jax.jit(prog), kind)
        elif kind == "fwd_bwd":
            watched = self._watched()

            def prog(arg_vals, aux_vals, rng, head_grads):
                w = {nm: arg_vals[nm] for nm in watched}
                rest = {nm: v for nm, v in arg_vals.items()
                        if nm not in w}

                def f(wvals):
                    outs, new_aux = runner({**rest, **wvals}, aux_vals,
                                           True, rng)
                    return outs, new_aux

                f = _remat.wrap(f, remat_policy)
                outs, vjp_fn, new_aux = jax.vjp(f, w, has_aux=True)
                grads, = vjp_fn(head_grads)
                return outs, new_aux, grads

            fn = _telemetry.wrap_dispatch(prog, kind, compiled=False) \
                if naive else _telemetry.wrap_dispatch(jax.jit(prog), kind)
        else:
            raise ValueError(kind)
        if gkey is not None:
            _progcache.put(gkey, fn)
        self._jit_cache[cache_key] = fn
        return fn

    # -------------------------------------------------------------- forward
    def forward(self, is_train=False, **kwargs):
        """Set optional input kwargs and run (lazily when training).

        reference: python/mxnet/executor.py forward / MXExecutorForward.
        """
        if kwargs:
            ad = self.arg_dict
            for nm, val in kwargs.items():
                if nm not in ad:
                    raise MXNetError(f"unknown forward argument {nm!r}")
                if isinstance(val, NDArray):
                    ad[nm]._set(val.asjax().astype(ad[nm].dtype))
                else:
                    ad[nm]._set(jnp.asarray(val, dtype=ad[nm].dtype))
        rng = _random.next_key()
        self._pending = ("fwd_train" if is_train else "fwd_infer", rng)
        self._outputs = None
        if not is_train:
            self._materialize_outputs()
            return self.outputs
        # training: stay lazy so backward() costs exactly one fused
        # fwd+bwd execution; the returned view materializes on access
        return _LazyOutputs(self)

    def _arg_vals(self):
        return {nm: a.asjax() for nm, a in zip(self.arg_names,
                                               self.arg_arrays)}

    def _aux_vals(self):
        return {nm: a.asjax() for nm, a in zip(self.aux_names,
                                               self.aux_arrays)}

    def _run_tapped(self, is_train, rng):
        """Monitored execution: walk the graph eagerly (un-jitted) and
        tap every op's outputs — full parity with the reference's
        ExecuteMonCallback granularity (graph_executor.cc:758-778), at
        interpreter speed (it's a debug mode there too: bulk exec must
        be off for per-op stats, env_var.md:71)."""
        if self._tapped_runner is None:
            def tap(node, outs):
                out_names = node.output_names() if hasattr(
                    node, "output_names") else None
                for i, o in enumerate(outs):
                    nm = out_names[i] if out_names and i < len(out_names) \
                        else (f"{node.name}_output" if len(outs) == 1
                              else f"{node.name}_output{i}")
                    self._monitor_callback(nm, NDArray(o, ctx=self._ctx))

            self._tapped_runner, *_ = _build_graph_runner(
                self._symbol, self._shape_overrides, tap=tap,
                mp_plan=self._mp_plan,
                compute_dtype=self._compute_dtype)
        return self._tapped_runner(self._arg_vals(), self._aux_vals(),
                                   is_train, rng)

    def _materialize_outputs(self):
        if self._outputs is not None or self._pending is None:
            return
        kind, rng = self._pending
        try:
            if self._monitor_callback is not None:
                outs, new_aux = self._run_tapped(kind == "fwd_train", rng)
                self._finish(outs, new_aux, monitored=True)
                return
            prog = self._get_program(kind)
            outs, new_aux = prog(self._arg_vals(), self._aux_vals(), rng)
            self._finish(outs, new_aux)
        except Exception as exc:
            _telemetry.flightrec.on_crash(exc, where="executor.forward")
            raise

    def _finish(self, outs, new_aux, grads=None, monitored=False):
        self._outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if new_aux:
            aux_d = self.aux_dict
            for nm, val in new_aux.items():
                aux_d[nm]._set(val)
        if grads is not None:
            gd = dict(zip(self.arg_names, self.grad_arrays))
            for nm, g in grads.items():
                dst = gd.get(nm)
                if dst is None:
                    continue
                req = self.grad_req.get(nm, "null")
                if req == "write":
                    dst._set(g.astype(dst.dtype))
                elif req == "add":
                    dst._set(dst.asjax() + g.astype(dst.dtype))
        if self._monitor_callback is not None and not monitored:
            for nm, arr in zip(self.output_names, self._outputs):
                self._monitor_callback(nm, arr)
        if self._sentinel is not None:
            self._sentinel.check_executor(self, grads_fresh=grads is not None)

    @property
    def outputs(self):
        self._materialize_outputs()
        return self._outputs if self._outputs is not None else []

    # -------------------------------------------------------------- backward
    def backward(self, out_grads=None):
        """Propagate gradients (fused fwd+bwd XLA program).

        reference: MXExecutorBackward -> RunOps over the backward segment.
        """
        if self._pending is None:
            raise MXNetError("backward() requires a prior forward(is_train=True)")
        kind, rng = self._pending
        if kind != "fwd_train":
            raise MXNetError("backward() after forward(is_train=False)")
        # head gradients: user-provided, else ones for loss heads
        if out_grads is None:
            heads = None
        elif isinstance(out_grads, NDArray):
            heads = [out_grads]
        else:
            heads = list(out_grads)
        arg_vals = self._arg_vals()
        out_shapes = None
        if heads is None:
            # ones for loss heads (their custom_vjp ignores the value),
            # zeros for data heads -> no spurious gradient
            outs_struct = jax.eval_shape(
                lambda a, x, r: self._runner(a, x, True, r)[0],
                arg_vals, self._aux_vals(), jax.random.PRNGKey(0))
            heads = [jnp.ones(o.shape, o.dtype) if is_loss
                     else jnp.zeros(o.shape, o.dtype)
                     for o, is_loss in zip(outs_struct, self._loss_mask)]
        else:
            heads = [h.asjax() if isinstance(h, NDArray) else jnp.asarray(h)
                     for h in heads]
        monitored = self._monitor_callback is not None
        try:
            if monitored and self._outputs is None:
                # training forward is lazy and the gradient path below runs
                # as one fused XLA program, so the per-op tap would
                # otherwise never fire under fit(monitor=...) — replay the
                # forward eagerly (same rng) purely for the monitor's
                # benefit. Skipped when outputs already materialized
                # through the tapped path (a caller that read .outputs
                # after forward) — the taps fired there.
                self._run_tapped(True, rng)
            prog = self._get_program("fwd_bwd")
            outs, new_aux, grads = prog(arg_vals, self._aux_vals(), rng,
                                        heads)
            self._finish(outs, new_aux, grads, monitored=monitored)
        except Exception as exc:
            _telemetry.flightrec.on_crash(exc, where="executor.backward")
            raise
        self._pending = None

    # ------------------------------------------------------------- utilities
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference: executor.py copy_params_from."""
        ad = self.arg_dict
        for nm, arr in arg_params.items():
            if nm in ad:
                ad[nm]._set(jnp.asarray(
                    arr.asnumpy() if isinstance(arr, NDArray) else arr,
                    dtype=ad[nm].dtype))
            elif not allow_extra_params:
                raise MXNetError(f"unknown param {nm!r}")
        if aux_params:
            xd = self.aux_dict
            for nm, arr in aux_params.items():
                if nm in xd:
                    xd[nm]._set(jnp.asarray(
                        arr.asnumpy() if isinstance(arr, NDArray) else arr,
                        dtype=xd[nm].dtype))
                elif not allow_extra_params:
                    raise MXNetError(f"unknown aux param {nm!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (fresh XLA programs compile on demand)."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for nm, s, old in zip(self.arg_names, arg_shapes, self.arg_arrays):
            if tuple(old.shape) == tuple(s):
                new_args[nm] = old
            else:
                new_args[nm] = nd_zeros(s, ctx=self._ctx, dtype=old.dtype)
        new_grads = {}
        for nm, s, old in zip(self.arg_names, arg_shapes, self.grad_arrays):
            if old is None:
                continue
            new_grads[nm] = old if tuple(old.shape) == tuple(s) else \
                nd_zeros(s, ctx=self._ctx, dtype=old.dtype)
        new_aux = {}
        for nm, s, old in zip(self.aux_names, aux_shapes, self.aux_arrays):
            new_aux[nm] = old if tuple(old.shape) == tuple(s) else \
                nd_zeros(s, ctx=self._ctx, dtype=old.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self.grad_req, new_aux, self._group2ctx,
                        compute_dtype=self._compute_dtype,
                        mirror=self._remat_segments or 0)

    def cost_table(self, train=None):
        """Per-op FLOPs/bytes attribution for this binding's shapes
        (telemetry/mfu.py). ``train`` defaults to whether gradients are
        watched. Returns None when shapes can't be inferred."""
        from .telemetry import mfu as _mfu
        if train is None:
            train = bool(self._watched())
        shapes = {nm: tuple(a.shape)
                  for nm, a in zip(self.arg_names, self.arg_arrays)
                  if a is not None}
        try:
            return _mfu.cost_table(self._symbol, shapes, train=train)
        except Exception:
            return None

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback
        self._tapped_runner = None  # tap closure binds the callback

    def debug_str(self):
        lines = [f"Symbol outputs: {self.output_names}"]
        for node in self._symbol._topo_nodes():
            kind = "var" if node.is_variable else node.op
            lines.append(f"  {kind:<20} {node.name}")
        return "\n".join(lines)

    # ----------------------------------------------------------- simple_bind
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, type_dict, group2ctx, shapes,
                     mirror=None, validate=None):
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        # a variable's declared __dtype__ binds a cell of that dtype
        # (the int8 quant tier's _q weights; executor_group does the
        # same — analysis rule GV105 audits the declaration either way);
        # an explicit type_dict entry wins
        declared = {n.name: np.dtype(n._extra["__dtype__"])
                    for n in symbol._topo_nodes()
                    if n.is_variable and n._extra.get("__dtype__")}
        args = {}
        for nm, s in zip(arg_names, arg_shapes):
            args[nm] = nd_zeros(s, ctx=ctx,
                                dtype=type_dict.get(
                                    nm, declared.get(nm, np.float32)))
        req = grad_req if isinstance(grad_req, dict) else \
            {nm: grad_req for nm in arg_names}
        grads = {nm: nd_zeros(s, ctx=ctx, dtype=type_dict.get(nm, np.float32))
                 for nm, s in zip(arg_names, arg_shapes)
                 if req.get(nm, "null") != "null"}
        # aux cells honor a declared dtype too (attention_decode's int32
        # cache cursor) — same GV105 discipline as the arg cells
        aux = {nm: nd_zeros(s, ctx=ctx,
                            dtype=declared.get(nm, np.float32))
               for nm, s in zip(aux_names, aux_shapes)}
        return Executor(symbol, ctx, args, grads, grad_req, aux, group2ctx,
                        mirror=mirror, validate=validate)
