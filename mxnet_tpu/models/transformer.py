"""Decoder-only transformer LM + KV-cache incremental decoder.

The model-zoo keystone (ROADMAP 1): a pre-LN, tied-embedding language
model composed entirely from the framework's fused ops — ``Embedding``
(fused-gather tier), ``LayerNorm`` (fused row-pass tier), ``attention``
(three gated lowerings: xla composition / Pallas flash / sequence-
sharded ring over the mesh's ``seq`` axis), ``FusedBiasGeLU`` (fused
dense epilogue) — so every hot op rides the kernel tier's numerics-gated
autotune, and ``Module.fit(spmd=True)`` on a (data x seq) mesh trains it
data+sequence-parallel with activations sharded ``P('data', 'seq')``.

Two graphs, one parameter set:

* ``get_symbol`` — the training/full-sequence forward: data ``(B, T)``
  token ids, label ``(B*T,)`` next-token ids (flat so the loss head's
  label slot is fed directly by the variable — exact class ids under
  mixed precision), softmax-CE loss over the tied embedding.
* ``get_decode_symbol`` — the inference decoder: ``(B, S)`` new tokens
  per step (S=1 for autoregressive generation), attention replaced by
  ``attention_decode`` whose fixed-capacity K/V cache rides executor
  AUX state (read+written on inference forwards), so N incremental
  steps reproduce the length-N full forward.

``KVCacheDecoder`` drives a bound decode module: host-side position
tracking (capacity overflow raises before the program clamps), learned-
position id feeding, cache reset. ``SyntheticLMIter`` is the synthetic
next-token data source bench.py and the tests train against.
"""
from __future__ import annotations

import os

import numpy as np

from .. import symbol as sym
from ..base import MXNetError

__all__ = ["get_symbol", "get_decode_symbol", "SyntheticLMIter",
           "KVCacheDecoder", "BatchedKVCacheDecoder",
           "default_cache_capacity", "default_cache_dtype"]


def default_cache_capacity():
    """Decode cache capacity default: ``MXNET_LM_CACHE_CAPACITY``
    (docs/env_var.md), else 256 positions."""
    try:
        return int(os.environ.get("MXNET_LM_CACHE_CAPACITY", "256"))
    except ValueError:
        return 256


def default_cache_dtype():
    """Decode KV-cache storage dtype default: ``MXNET_LM_CACHE_DTYPE``
    (docs/env_var.md — ``fp8`` stores cache rows as float8_e4m3fn,
    quantized on write and dequantized on read), else None for
    compute-width cells."""
    return os.environ.get("MXNET_LM_CACHE_DTYPE") or None


def _proj(x, num_hidden, name, no_bias=False):
    """FullyConnected over the flattened (B*T, D) token axis: the
    reference FC contracts all non-batch dims, so sequence models fold
    (B, T) into rows first and unfold after."""
    flat = sym.Reshape(x, shape=(-3, 0), name=f"{name}_fold")
    return sym.FullyConnected(flat, num_hidden=num_hidden, name=name,
                              no_bias=no_bias)


def _block(x, *, i, seq_len, d_model, n_head, dropout, pos_embed,
           rope_base, name, decode=False, capacity=None,
           per_slot=False, cache_dtype=None):
    """One pre-LN transformer block; ``decode=True`` swaps the full
    ``attention`` for the KV-cache ``attention_decode`` path (same
    parameter names either way, so one trained parameter set serves
    both graphs). ``per_slot=True`` selects the slot-pooled decode
    lowering: a (B, 1) cursor vector so every batch row decodes its own
    sequence at its own position."""
    pfx = f"{name}_l{i}"
    dh = d_model // n_head
    T = seq_len

    ln1 = sym.LayerNorm(x, name=f"{pfx}_ln1")
    qkv = _proj(ln1, 3 * d_model, f"{pfx}_qkv")          # (B*T, 3D)
    qkv = sym.Reshape(qkv, shape=(-1, T, 3 * n_head, dh),
                      name=f"{pfx}_qkv_split")
    qkv = sym.transpose(qkv, axes=(0, 2, 1, 3),
                        name=f"{pfx}_qkv_t")             # (B, 3H, T, dh)
    q = sym.slice_axis(qkv, axis=1, begin=0, end=n_head,
                       name=f"{pfx}_q")
    k = sym.slice_axis(qkv, axis=1, begin=n_head, end=2 * n_head,
                       name=f"{pfx}_k")
    v = sym.slice_axis(qkv, axis=1, begin=2 * n_head, end=3 * n_head,
                       name=f"{pfx}_v")
    if decode:
        att = sym.attention_decode(
            q, k, v, capacity=capacity, rope=(pos_embed == "rotary"),
            rope_base=rope_base, per_slot=per_slot,
            cache_dtype=cache_dtype or "",
            name=f"{pfx}_attn")
    else:
        if pos_embed == "rotary":
            q = sym.RoPE(q, base=rope_base, name=f"{pfx}_rope_q")
            k = sym.RoPE(k, base=rope_base, name=f"{pfx}_rope_k")
        att = sym.attention(q, k, v, causal=True, name=f"{pfx}_attn")
    att = sym.transpose(att, axes=(0, 2, 1, 3),
                        name=f"{pfx}_attn_t")            # (B, T, H, dh)
    att = sym.Reshape(att, shape=(-3, -3), name=f"{pfx}_attn_merge")
    proj = sym.FullyConnected(att, num_hidden=d_model,
                              name=f"{pfx}_proj")        # (B*T, D)
    proj = sym.Reshape(proj, shape=(-1, T, d_model),
                       name=f"{pfx}_proj_unfold")
    if dropout:
        proj = sym.Dropout(proj, p=dropout, name=f"{pfx}_drop1")
    x = x + proj

    ln2 = sym.LayerNorm(x, name=f"{pfx}_ln2")
    # dense -> GeLU as the fused epilogue pair: the matmul emits raw
    # rows (no_bias) and FusedBiasGeLU folds bias+erf-GeLU in one pass
    h = _proj(ln2, 4 * d_model, f"{pfx}_ffn1", no_bias=True)
    h = sym.FusedBiasGeLU(h, name=f"{pfx}_ffn_gelu")
    h = sym.FullyConnected(h, num_hidden=d_model, name=f"{pfx}_ffn2")
    h = sym.Reshape(h, shape=(-1, T, d_model), name=f"{pfx}_ffn_unfold")
    if dropout:
        h = sym.Dropout(h, p=dropout, name=f"{pfx}_drop2")
    return x + h


def _validate(vocab_size, d_model, n_head, pos_embed):
    if d_model % n_head:
        raise MXNetError(f"d_model {d_model} must divide n_head {n_head}")
    if (d_model // n_head) % 2:
        raise MXNetError("head dim must be even (RoPE rotates pairs)")
    if pos_embed not in ("rotary", "learned"):
        raise MXNetError(f"pos_embed {pos_embed!r}: 'rotary' or 'learned'")


def _embed(data, tok_w, *, seq_len, vocab_size, d_model, pos_embed,
           max_seq_len, name, pos_ids=None, per_slot=False):
    """Token embedding (scaled by sqrt(D), transformer convention) plus
    the learned position table when ``pos_embed='learned'``. Per-slot
    decode feeds ``pos_ids`` shaped (B, S) — every slot at its own
    absolute position — so the looked-up table rows already align with
    ``x`` and add elementwise."""
    x = sym.Embedding(data=data, weight=tok_w, input_dim=vocab_size,
                      output_dim=d_model,
                      scale=float(np.sqrt(d_model)),
                      name=f"{name}_tok_embed")          # (B, T, D)
    if pos_embed == "learned":
        if pos_ids is None:
            pos_ids = sym._arange(start=0, stop=float(seq_len),
                                  name=f"{name}_pos_ids")
        pos_w = sym.var(f"{name}_pos_embed_weight")
        pos = sym.Embedding(data=pos_ids, weight=pos_w,
                            input_dim=max_seq_len, output_dim=d_model,
                            name=f"{name}_pos_embed")    # (T, D) /
        if per_slot:                                     # (B, S, D)
            x = x + pos
        else:
            pos = sym.expand_dims(pos, axis=0, name=f"{name}_pos_b")
            x = sym.broadcast_add(x, pos, name=f"{name}_add_pos")
    return x


def get_symbol(vocab_size=256, d_model=64, n_layer=2, n_head=4,
               seq_len=32, pos_embed="rotary", rope_base=10000.0,
               dropout=0.0, include_loss=True, normalization="batch",
               max_seq_len=None, name="lm"):
    """Training/full-sequence graph.

    data: ``(B, seq_len)`` token ids (bind the data iter with an int32
    ``DataDesc`` for vocabularies past bf16's exact-integer range);
    label: ``(B*seq_len,)`` next-token ids fed straight into the loss
    head (flat on purpose — the label variable keeps its exact dtype
    under mixed precision only when it feeds the loss slot directly).

    ``include_loss=False`` returns logits ``(B, seq_len, vocab)`` — the
    decode-parity reference the KV-cache gates compare against.
    """
    _validate(vocab_size, d_model, n_head, pos_embed)
    max_seq_len = max_seq_len or seq_len
    T = seq_len

    data = sym.var("data")
    tok_w = sym.var(f"{name}_tok_embed_weight")
    x = _embed(data, tok_w, seq_len=T, vocab_size=vocab_size,
               d_model=d_model, pos_embed=pos_embed,
               max_seq_len=max_seq_len, name=name)
    for i in range(n_layer):
        x = _block(x, i=i, seq_len=T, d_model=d_model, n_head=n_head,
                   dropout=dropout, pos_embed=pos_embed,
                   rope_base=rope_base, name=name)
    x = sym.LayerNorm(x, name=f"{name}_ln_f")
    flat = sym.Reshape(x, shape=(-3, 0), name=f"{name}_head_fold")
    # tied-embedding softmax head: logits = x @ E^T over the SAME
    # variable the token embedding reads (one weight, two gradients)
    logits = sym.dot(flat, tok_w, transpose_b=True,
                     name=f"{name}_logits")              # (B*T, V)
    if not include_loss:
        return sym.Reshape(logits, shape=(-1, T, vocab_size),
                           name=f"{name}_logits_btv")
    return sym.SoftmaxOutput(logits, name="softmax",
                             normalization=normalization)


def get_decode_symbol(vocab_size=256, d_model=64, n_layer=2, n_head=4,
                      pos_embed="rotary", rope_base=10000.0,
                      capacity=None, step_len=1, max_seq_len=None,
                      per_slot=False, cache_dtype=None, name="lm"):
    """Incremental KV-cache decoder: ``(B, step_len)`` new token ids in,
    logits ``(B, step_len, vocab)`` out, per-layer K/V caches of
    ``capacity`` positions riding executor aux state. Parameter names
    match ``get_symbol``'s exactly, so a trained parameter set loads
    unchanged. ``pos_embed='learned'`` adds a ``pos_ids`` input
    (``(step_len,)`` absolute positions — ``KVCacheDecoder`` feeds it).

    ``per_slot=True`` builds the slot-pooled continuous-batching graph:
    every batch row is an independent decode slot with its own (B, 1)
    cache cursor, so one pinned program advances B sequences at B
    different positions per dispatch — ``BatchedKVCacheDecoder`` drives
    it, ``serve.decode`` schedules it. ``step_len`` > 1 builds the
    S-token *window* variant of the same graph (chunked prefill and
    speculative verify): each slot consumes S tokens starting at its own
    cursor, with within-window causal masking, and the logits row ``s``
    predicts the token after stream position ``cursor + s``. With
    learned positions the ``pos_ids`` input becomes ``(B, step_len)``
    per-slot absolute positions.

    ``cache_dtype='fp8'`` (or ``MXNET_LM_CACHE_DTYPE=fp8``) declares
    the per-layer K/V cache cells as ``float8_e4m3fn`` storage: rows
    quantize on write and dequantize on read inside the pinned decode
    program, quartering cache HBM traffic and footprint. The cursor
    stays int32 and the default (None) keeps compute-width cells.
    """
    _validate(vocab_size, d_model, n_head, pos_embed)
    capacity = capacity or default_cache_capacity()
    cache_dtype = cache_dtype or default_cache_dtype()
    max_seq_len = max_seq_len or capacity
    S = step_len

    data = sym.var("data")
    tok_w = sym.var(f"{name}_tok_embed_weight")
    pos_ids = sym.var("pos_ids") if pos_embed == "learned" else None
    x = _embed(data, tok_w, seq_len=S, vocab_size=vocab_size,
               d_model=d_model, pos_embed=pos_embed,
               max_seq_len=max_seq_len, name=name, pos_ids=pos_ids,
               per_slot=per_slot)
    for i in range(n_layer):
        x = _block(x, i=i, seq_len=S, d_model=d_model, n_head=n_head,
                   dropout=0.0, pos_embed=pos_embed, rope_base=rope_base,
                   name=name, decode=True, capacity=capacity,
                   per_slot=per_slot, cache_dtype=cache_dtype)
    x = sym.LayerNorm(x, name=f"{name}_ln_f")
    flat = sym.Reshape(x, shape=(-3, 0), name=f"{name}_head_fold")
    logits = sym.dot(flat, tok_w, transpose_b=True,
                     name=f"{name}_logits")
    return sym.Reshape(logits, shape=(-1, S, vocab_size),
                       name=f"{name}_logits_bsv")


class SyntheticLMIter:
    """Synthetic next-token LM batches: data ``(B, T)`` int32 ids,
    label ``(B*T,)`` float ids (the shifted-by-one stream), matching
    ``get_symbol``'s flat-label loss contract."""

    def __init__(self, vocab_size, batch_size, seq_len, n_batches,
                 seed=0):
        from ..io import DataDesc
        from .. import ndarray as nd
        rs = np.random.RandomState(seed)
        stream = rs.randint(
            0, vocab_size,
            (n_batches * batch_size, seq_len + 1)).astype(np.int32)
        self._data = [nd.array(stream[i * batch_size:(i + 1) * batch_size,
                                      :seq_len])
                      for i in range(n_batches)]
        self._label = [nd.array(
            stream[i * batch_size:(i + 1) * batch_size, 1:]
            .reshape(-1).astype(np.float32)) for i in range(n_batches)]
        self.provide_data = [DataDesc("data", (batch_size, seq_len),
                                      np.int32)]
        self.provide_label = [DataDesc("softmax_label",
                                       (batch_size * seq_len,))]
        self.batch_size = batch_size
        self._i = 0

    def reset(self):
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        from ..io import DataBatch
        if self._i >= len(self._data):
            raise StopIteration
        b = DataBatch(data=[self._data[self._i]],
                      label=[self._label[self._i]],
                      provide_data=self.provide_data,
                      provide_label=self.provide_label)
        self._i += 1
        return b

    next = __next__


class KVCacheDecoder:
    """Host-side driver for a bound decode module.

    Owns what the jitted program cannot check: the absolute position
    cursor (capacity overflow raises HERE, before dynamic_update_slice
    would clamp the write), the ``pos_ids`` feed for learned positions,
    and cache reset between sequences. The module must be bound
    ``for_training=False`` over ``get_decode_symbol``'s graph.
    """

    def __init__(self, module, capacity, pos_embed="rotary"):
        self._mod = module
        self.capacity = int(capacity)
        self.pos_embed = pos_embed
        self.pos = 0
        self._new_session_trace()

    def _new_session_trace(self):
        """One trace per decode session (telemetry.trace): every step
        records a child span under the session root, so an N-token
        decode reconstructs to a single parented span tree keyed by
        ``self.trace.trace_id``."""
        from ..telemetry import trace as _trace
        self.trace = _trace.new_trace(session=True)
        self.trace.root = _trace.next_span_id()

    def reset(self):
        """Zero every decode cache (aux cells), rewind the cursor and
        rotate the session trace (a new sequence = a new trace)."""
        import jax.numpy as jnp
        exe = self._mod._exec_group.executor
        for nm, cell in exe.aux_dict.items():
            cell._set(jnp.zeros(cell.shape, cell.asjax().dtype))
        self.pos = 0
        self._new_session_trace()

    def step(self, tokens):
        """Decode one window: tokens ``(B, S)`` -> logits ``(B, S, V)``
        NDArray. Advances the device-side caches and the host cursor."""
        import time
        from .. import ndarray as nd
        from ..io import DataBatch
        from ..telemetry import trace as _trace
        tokens = np.asarray(tokens)
        if tokens.ndim == 1:
            tokens = tokens[:, None]
        S = tokens.shape[1]
        if self.pos + S > self.capacity:
            raise MXNetError(
                f"KV cache overflow: position {self.pos} + {S} new "
                f"tokens exceeds capacity {self.capacity}; reset() or "
                "re-bind with a larger capacity")
        data = [nd.array(tokens.astype(np.int32))]
        if self.pos_embed == "learned":
            data.append(nd.array(
                np.arange(self.pos, self.pos + S, dtype=np.float32)))
        t0 = time.perf_counter()
        if self.trace.start_s is None:
            self.trace.start_s = t0
        self._mod.forward(DataBatch(data=data, label=[]), is_train=False)
        self.pos += S
        t1 = time.perf_counter()
        _trace.record(self.trace, "lm.decode.step", t0, t1,
                      parent=self.trace.root, pos=self.pos - S, tokens=S)
        # the session root grows with every step: same span id, longer
        # duration — consumers dedupe keeping the last record
        _trace.record(self.trace, "lm.decode.session",
                      self.trace.start_s, t1, span_id=self.trace.root,
                      capacity=self.capacity, pos=self.pos)
        return self._mod.get_outputs()[0]


class BatchedKVCacheDecoder:
    """Host-side driver for a bound SLOT-POOLED decode module.

    The module must be bound ``for_training=False`` over
    ``get_decode_symbol(per_slot=True)``'s graph at a fixed slot count
    (the batch dim). Each slot is an independent sequence: ``join``
    claims a slot (resets its device cursor), ``leave`` releases it
    host-side only (the program keeps advancing the retired row
    harmlessly — its writes stay inside its own slot and nothing
    attends them), and ``step`` advances EVERY slot by one token in one
    dispatch. Like ``KVCacheDecoder``, the driver owns what the pinned
    program cannot check: per-slot cursors (capacity overflow raises
    HERE, naming the offending slots, before the masked write would
    no-op) and the per-slot ``pos_ids`` feed for learned positions.

    Besides the steady-state S=1 program, a driver can carry *window*
    modules (``add_window``): same parameters, same shared aux cells,
    ``step_len=S`` graphs that advance every slot by S positions per
    dispatch — chunked prefill and speculative verify ride these.
    ``step`` dispatches on ``tokens.shape[1]``. ``rewind`` pokes a
    slot's device cursor to an arbitrary position (the join-style aux
    update, never a compile) — the seam for padded final prefill
    chunks, prefix-cache joins at cursor C, and speculative rollback.

    ``serve.decode.DecodeScheduler`` builds the continuous-batching
    front end (admission, retirement, streaming, rung ladder) on top of
    one of these per slot rung.
    """

    def __init__(self, module, capacity, slots=None, pos_embed="rotary"):
        self._mod = module
        self.capacity = int(capacity)
        self.pos_embed = pos_embed
        if slots is None:
            slots = module.data_shapes[0].shape[0]
        self.slots = int(slots)
        self.pos = np.zeros(self.slots, np.int64)    # device-cursor mirror
        self.active = np.zeros(self.slots, bool)
        self._windows = {}                           # step_len -> module

    def add_window(self, step_len, module):
        """Register an S-token window module. It MUST have been bound
        with ``shared_module=`` this driver's S=1 module (or a module
        sharing its cells) so both programs advance the SAME device
        cache/cursor cells — the executor-group aux-sharing rule makes
        that automatic when slot count and capacity agree."""
        self._windows[int(step_len)] = module

    @property
    def window_lens(self):
        return sorted(self._windows)

    def _cursor_cells(self):
        exe = self._mod._exec_group.executor
        return [cell for nm, cell in exe.aux_dict.items()
                if nm.endswith("cache_pos")]

    def _kv_cells(self):
        """(name, cell) for every layer's K and V cache, in graph
        order — the prefix store snapshots/restores these rows."""
        exe = self._mod._exec_group.executor
        return [(nm, cell) for nm, cell in exe.aux_dict.items()
                if nm.endswith("k_cache") or nm.endswith("v_cache")]

    def free_slots(self):
        """Slot indices with no active sequence."""
        return [i for i in range(self.slots) if not self.active[i]]

    def join(self, slot):
        """Claim ``slot`` for a new sequence: rewind its device cursor
        to 0 across every layer (one tiny in-place aux update per layer
        — never a program-cache compile) and mark it active. The cache
        rows are NOT zeroed: every position a fresh sequence attends is
        rewritten by it first, and masked positions carry exactly zero
        softmax weight, so reuse is bit-clean."""
        import jax.numpy as jnp
        slot = int(slot)
        if self.active[slot]:
            raise MXNetError(f"slot {slot} already holds an active "
                             "sequence (leave() it first)")
        for cell in self._cursor_cells():
            cell._set(cell.asjax().at[slot, 0].set(jnp.int32(0)))
        self.pos[slot] = 0
        self.active[slot] = True
        return slot

    def leave(self, slot):
        """Release ``slot`` host-side. No device work: the retired row
        keeps advancing as a masked no-op until the next join."""
        self.active[int(slot)] = False

    def rewind(self, slot, pos):
        """Poke ``slot``'s device cursor to ``pos`` across every layer
        (the same tiny in-place aux update as ``join`` — never a
        compile). Used to discard the tail of a window after dispatch:
        padded final prefill chunks, rejected speculative proposals, and
        decoding slots riding a chunk dispatch all rewind to the stream
        position they actually reached. Cache rows past ``pos`` become
        garbage nobody attends (exp(-inf)-masked) and are rewritten
        before first read — the same bit-clean contract as ``join``."""
        self.rewind_many([slot], [pos])

    def rewind_many(self, slots, positions):
        """Batched ``rewind``: ONE aux update per layer for any number
        of slots (the chunk-dispatch epilogue touches most of a rung)."""
        import jax.numpy as jnp
        if not len(slots):
            return
        idx = np.asarray(slots, np.int32)
        val = np.asarray(positions, np.int32)
        for cell in self._cursor_cells():
            cell._set(cell.asjax().at[idx, 0].set(jnp.asarray(val)))
        self.pos[idx] = val.astype(np.int64)

    def capture_rows(self, slot, length):
        """Snapshot ``slot``'s first ``length`` cache positions across
        every layer: ``{cell_name: (length, ...) np.ndarray}``. The
        prefix store keeps these host-side under its byte budget."""
        slot = int(slot)
        return {nm: np.asarray(cell.asjax()[slot, :, :int(length)])
                for nm, cell in self._kv_cells()}

    def restore_rows(self, slot, rows):
        """Write captured rows back into ``slot`` (prefix-cache join):
        one in-place aux update per layer cache, bitwise the values
        ``capture_rows`` saw. The caller rewinds/sets the cursor."""
        slot = int(slot)
        for nm, cell in self._kv_cells():
            row = rows[nm]
            arr = cell.asjax()
            cell._set(arr.at[slot, :, :row.shape[1]].set(
                np.asarray(row, dtype=str(arr.dtype))))

    def overflowing(self, window=1):
        """Active slots whose next ``window``-token dispatch would pass
        capacity — the scheduler retires these (alone) before dispatch."""
        return [i for i in range(self.slots)
                if self.active[i] and self.pos[i] + window > self.capacity]

    def step(self, tokens):
        """Advance every slot by one S-token window: ``tokens``
        (slots,) or (slots, S) int ids (retired slots ride any valid
        id, 0 by convention) -> logits (slots, S, V) NDArray. S=1 runs
        the steady-state decode program; S>1 dispatches the matching
        window module registered via ``add_window``. Raises per slot
        BEFORE dispatch when an active slot would overflow its cache —
        batchmates are untouched (nothing was dispatched)."""
        from .. import ndarray as nd
        from ..io import DataBatch
        tokens = np.asarray(tokens)
        if tokens.ndim == 1:
            tokens = tokens[:, None]
        S = tokens.shape[1]
        if tokens.shape != (self.slots, S) or S < 1:
            raise MXNetError(f"step() wants ({self.slots}, S) tokens, "
                             f"got {tokens.shape}")
        if S == 1:
            mod = self._mod
        else:
            mod = self._windows.get(S)
            if mod is None:
                raise MXNetError(
                    f"no window module for step_len={S} (have "
                    f"{self.window_lens}); add_window() it at engine "
                    "warmup — steady-state dispatch never compiles")
        over = self.overflowing(S)
        if over:
            raise MXNetError(
                f"KV cache overflow in slot(s) {over}: position "
                f"{[int(self.pos[i]) for i in over]} + {S} exceeds "
                f"capacity {self.capacity}; retire the sequence(s) or "
                "re-bind with a larger capacity")
        data = [nd.array(tokens.astype(np.int32))]
        if self.pos_embed == "learned":
            pos = self.pos[:, None] + np.arange(S)[None, :]
            data.append(nd.array(
                np.minimum(pos, self.capacity - 1).astype(np.float32)))
        mod.forward(DataBatch(data=data, label=[]), is_train=False)
        self.pos += S            # the program advances EVERY slot
        return mod.get_outputs()[0]
