"""AlexNet symbol.

Architecture per Krizhevsky et al. 2012 as configured in the reference's
example zoo (example/image-classification/symbols/alexnet.py): five conv
layers (the first two followed by local response normalization), three
max-pools, and three fully-connected layers with dropout. The layer
hyperparameters (kernel/stride/pad/filter counts) are the fixed AlexNet
spec; the graph construction below is ours.
"""
from .. import symbol as sym

# (num_filter, kernel, stride, pad, lrn?, pool?) for the conv trunk
_TRUNK = [
    (96, (11, 11), (4, 4), (0, 0), True, True),
    (256, (5, 5), (1, 1), (2, 2), True, True),
    (384, (3, 3), (1, 1), (1, 1), False, False),
    (384, (3, 3), (1, 1), (1, 1), False, False),
    (256, (3, 3), (1, 1), (1, 1), False, True),
]


def get_symbol(num_classes=1000, **kwargs):
    net = sym.var("data")
    for i, (nf, kernel, stride, pad, lrn, pool) in enumerate(_TRUNK, 1):
        net = sym.Convolution(data=net, num_filter=nf, kernel=kernel,
                              stride=stride, pad=pad, name=f"conv{i}")
        net = sym.Activation(data=net, act_type="relu")
        if lrn:
            net = sym.LRN(data=net, alpha=0.0001, beta=0.75, knorm=2,
                          nsize=5)
        if pool:
            net = sym.Pooling(data=net, pool_type="max", kernel=(3, 3),
                              stride=(2, 2))
    net = sym.Flatten(data=net)
    for i, width in enumerate([4096, 4096], 1):
        net = sym.FullyConnected(data=net, num_hidden=width, name=f"fc{i}")
        net = sym.Activation(data=net, act_type="relu")
        net = sym.Dropout(data=net, p=0.5)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc3")
    return sym.SoftmaxOutput(data=net, name="softmax")
