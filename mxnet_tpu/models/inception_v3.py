"""Inception-v3 symbol (299x299 input).

Architecture per Szegedy et al., "Rethinking the Inception Architecture
for Computer Vision" (2015), as configured in the reference's example
zoo (reference: example/image-classification/symbols/inception-v3.py:1
— BASELINE's "ResNet-50 / Inception-v3 on ImageNet" config). Layer
names follow the reference's checkpoint naming so `.params` files line
up. The builders below are table-driven: every tower is a conv chain
spec run by `_chain`, the five mixed-block shapes (A grid, B reduce,
C factorized-7, D reduce, E expanded-3) differ only in their tower
tables.
"""
from .. import symbol as sym

# conv spec: (num_filter, kernel, stride, pad)
_1x1 = lambda nf: (nf, (1, 1), (1, 1), (0, 0))


def _conv(data, nf, kernel=(1, 1), stride=(1, 1), pad=(0, 0), name=None,
          suffix=""):
    """conv -> BN(fix_gamma) -> relu, the v3 building block."""
    net = sym.Convolution(data=data, num_filter=nf, kernel=kernel,
                          stride=stride, pad=pad, no_bias=True,
                          name=f"{name}{suffix}_conv2d")
    net = sym.BatchNorm(data=net, fix_gamma=True,
                        name=f"{name}{suffix}_batchnorm")
    return sym.Activation(data=net, act_type="relu",
                          name=f"{name}{suffix}_relu")


def _chain(data, specs, name):
    """Run one tower: consecutive convs with reference suffix numbering
    (_conv, _conv_1, _conv_2, ...)."""
    out = data
    for i, (nf, kernel, stride, pad) in enumerate(specs):
        out = _conv(out, nf, kernel, stride, pad, name=name,
                    suffix="_conv" if i == 0 else f"_conv_{i}")
    return out


def _pool(data, pool_type, name):
    """Grid-preserving 3x3 stride-1 pool feeding a projection conv."""
    return sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1),
                       pad=(1, 1), pool_type=pool_type,
                       name=f"{pool_type}_pool_{name}_pool")


def _block_a(data, n1, r3, n3a, n3b, r5, n5, pool, proj, name):
    """Grid-size-preserving block: 1x1 / 5x5 / double-3x3 / pool-proj."""
    t1 = _conv(data, n1, name=f"{name}_conv")
    t5 = _chain(data, [_1x1(r5), (n5, (5, 5), (1, 1), (2, 2))],
                f"{name}_tower")
    t3 = _chain(data, [_1x1(r3), (n3a, (3, 3), (1, 1), (1, 1)),
                       (n3b, (3, 3), (1, 1), (1, 1))], f"{name}_tower_1")
    p = _pool(data, pool, name)
    cp = _conv(p, proj, name=f"{name}_tower_2", suffix="_conv")
    return sym.Concat(t1, t5, t3, cp, name=f"ch_concat_{name}_chconcat")


def _block_b(data, n3, r, d1, d2, name):
    """First grid reduction: strided 3x3 / strided double-3x3 / max-pool."""
    t3 = _conv(data, n3, kernel=(3, 3), stride=(2, 2), name=f"{name}_conv")
    td = _chain(data, [_1x1(r), (d1, (3, 3), (1, 1), (1, 1)),
                       (d2, (3, 3), (2, 2), (0, 0))], f"{name}_tower")
    p = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2), pad=(0, 0),
                    pool_type="max", name=f"max_pool_{name}_pool")
    return sym.Concat(t3, td, p, name=f"ch_concat_{name}_chconcat")


def _block_c(data, n1, rd, d1, d2, rq, q1, q2, q3, q4, pool, proj, name):
    """Factorized-7x7 block: 1x1 / 1x7-7x1 / 7x1-1x7-7x1-1x7 / pool-proj."""
    t1 = _conv(data, n1, name=f"{name}_conv")
    td = _chain(data, [_1x1(rd), (d1, (1, 7), (1, 1), (0, 3)),
                       (d2, (7, 1), (1, 1), (3, 0))], f"{name}_tower")
    tq = _chain(data, [_1x1(rq), (q1, (7, 1), (1, 1), (3, 0)),
                       (q2, (1, 7), (1, 1), (0, 3)),
                       (q3, (7, 1), (1, 1), (3, 0)),
                       (q4, (1, 7), (1, 1), (0, 3))], f"{name}_tower_1")
    p = _pool(data, pool, name)
    cp = _conv(p, proj, name=f"{name}_tower_2", suffix="_conv")
    return sym.Concat(t1, td, tq, cp, name=f"ch_concat_{name}_chconcat")


def _block_d(data, r3, n3, rd, d1, d2, d3, pool, name):
    """Second grid reduction: 1x1-3x3s2 / 1x1-1x7-7x1-3x3s2 / pool."""
    t3 = _chain(data, [_1x1(r3), (n3, (3, 3), (2, 2), (0, 0))],
                f"{name}_tower")
    td = _chain(data, [_1x1(rd), (d1, (1, 7), (1, 1), (0, 3)),
                       (d2, (7, 1), (1, 1), (3, 0)),
                       (d3, (3, 3), (2, 2), (0, 0))], f"{name}_tower_1")
    p = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2), pad=(0, 0),
                    pool_type=pool, name=f"{pool}_pool_{name}_pool")
    return sym.Concat(t3, td, p, name=f"ch_concat_{name}_chconcat")


def _block_e(data, n1, rd3, d3a, d3b, r33, n33, d33a, d33b, pool, proj,
             name):
    """Expanded-filter-bank block: the 3x3s split into parallel 1x3/3x1
    outputs that concat (coarsest-grid stage)."""
    t1 = _conv(data, n1, name=f"{name}_conv")
    stem = _conv(data, rd3, name=f"{name}_tower", suffix="_conv")
    ta = _conv(stem, d3a, kernel=(1, 3), pad=(0, 1), name=f"{name}_tower",
               suffix="_mixed_conv")
    tb = _conv(stem, d3b, kernel=(3, 1), pad=(1, 0), name=f"{name}_tower",
               suffix="_mixed_conv_1")
    stem2 = _chain(data, [_1x1(r33), (n33, (3, 3), (1, 1), (1, 1))],
                   f"{name}_tower_1")
    t2a = _conv(stem2, d33a, kernel=(1, 3), pad=(0, 1),
                name=f"{name}_tower_1", suffix="_mixed_conv")
    t2b = _conv(stem2, d33b, kernel=(3, 1), pad=(1, 0),
                name=f"{name}_tower_1", suffix="_mixed_conv_1")
    p = _pool(data, pool, name)
    cp = _conv(p, proj, name=f"{name}_tower_2", suffix="_conv")
    return sym.Concat(t1, ta, tb, t2a, t2b, cp,
                      name=f"ch_concat_{name}_chconcat")


# stage tables: per-block tower widths (the published v3 configuration)
_STAGE_A = [(64, 64, 96, 96, 48, 64, "avg", 32, "mixed"),
            (64, 64, 96, 96, 48, 64, "avg", 64, "mixed_1"),
            (64, 64, 96, 96, 48, 64, "avg", 64, "mixed_2")]
_STAGE_C = [(192, 128, 128, 192, 128, 128, 128, 128, 192, "avg", 192,
             "mixed_4"),
            (192, 160, 160, 192, 160, 160, 160, 160, 192, "avg", 192,
             "mixed_5"),
            (192, 160, 160, 192, 160, 160, 160, 160, 192, "avg", 192,
             "mixed_6"),
            (192, 192, 192, 192, 192, 192, 192, 192, 192, "avg", 192,
             "mixed_7")]
_STAGE_E = [(320, 384, 384, 384, 448, 384, 384, 384, "avg", 192,
             "mixed_9"),
            (320, 384, 384, 384, 448, 384, 384, 384, "max", 192,
             "mixed_10")]


def get_symbol(num_classes=1000, **kwargs):
    data = sym.var("data")
    # stem: 299 -> 35 spatial
    net = _conv(data, 32, kernel=(3, 3), stride=(2, 2), name="conv")
    net = _conv(net, 32, kernel=(3, 3), name="conv_1")
    net = _conv(net, 64, kernel=(3, 3), pad=(1, 1), name="conv_2")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max", name="pool")
    net = _conv(net, 80, kernel=(1, 1), name="conv_3")
    net = _conv(net, 192, kernel=(3, 3), name="conv_4")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max", name="pool1")
    for cfg in _STAGE_A:
        net = _block_a(net, *cfg)
    net = _block_b(net, 384, 64, 96, 96, "mixed_3")
    for cfg in _STAGE_C:
        net = _block_c(net, *cfg)
    net = _block_d(net, 192, 320, 192, 192, 192, 192, "max", "mixed_8")
    for cfg in _STAGE_E:
        net = _block_e(net, *cfg)
    net = sym.Pooling(data=net, kernel=(8, 8), stride=(1, 1),
                      pool_type="avg", name="global_pool")
    net = sym.Flatten(data=net, name="flatten")
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=net, name="softmax")
