"""Model zoo: symbol builders for the reference's example configs
(reference: example/image-classification/symbols/, example/rnn/)."""
from . import mlp
from . import lenet
from . import resnet
from . import alexnet
from . import vgg
from . import inception_bn
from . import inception_v3
from . import transformer
