"""Imperative autograd (reference: python/mxnet/contrib/autograd.py +
src/ndarray/autograd.cc).

The reference records executed imperative ops on a tape and replays a
GraphExecutor backward (autograd.cc:132-188). TPU-native: the tape IS
``jax.vjp`` — ``grad_and_loss`` traces the python function with jax arrays
and differentiates it, no graph rebuild. ``mark_variables`` +
``train_section`` + ``backward`` reproduce the contrib API.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["grad_and_loss", "grad", "mark_variables", "backward",
           "train_section", "test_section", "set_is_training",
           "is_training"]

_STATE = {"train": False, "marked": []}


def set_is_training(is_train):
    prev = _STATE["train"]
    _STATE["train"] = bool(is_train)
    return prev


def is_training():
    return _STATE["train"]


@contextmanager
def train_section():
    """reference: contrib/autograd.py train_section."""
    prev = set_is_training(True)
    try:
        yield
    finally:
        set_is_training(prev)


@contextmanager
def test_section():
    prev = set_is_training(False)
    try:
        yield
    finally:
        set_is_training(prev)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Associate grad buffers with variables.
    reference: autograd.cc MarkVariables."""
    if isinstance(variables, NDArray):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    _STATE["marked"] = list(zip(variables, gradients, grad_reqs))


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient and loss.
    reference: contrib/autograd.py grad_and_loss."""
    @functools.wraps(func)
    def wrapped(*args):
        nd_args = [a for a in args]
        jax_args = [a.asjax() if isinstance(a, NDArray) else jnp.asarray(a)
                    for a in nd_args]
        argnums = argnum if argnum is not None else tuple(range(len(args)))
        if isinstance(argnums, int):
            argnums = (argnums,)

        def f(*xs):
            wrapped_args = [NDArray(x) for x in xs]
            out = func(*wrapped_args)
            if isinstance(out, (list, tuple)):
                return [o.asjax() if isinstance(o, NDArray) else o
                        for o in out]
            return out.asjax() if isinstance(out, NDArray) else out

        outputs, vjp_fn = jax.vjp(f, *jax_args)
        if isinstance(outputs, (list, tuple)):
            head = [jnp.ones_like(o) for o in outputs]
        else:
            head = jnp.ones_like(outputs)
        all_grads = vjp_fn(head)
        grads = [NDArray(all_grads[i]) for i in argnums]
        outs = [NDArray(o) for o in outputs] \
            if isinstance(outputs, (list, tuple)) else NDArray(outputs)
        return grads, outs
    return wrapped


def grad(func, argnum=None):
    """reference: contrib/autograd.py grad."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped


def backward(outputs, out_grads=None, retain_graph=False):
    """Compute gradients of marked variables w.r.t. outputs produced by
    ``compute``-style closures. In this framework the recommended API is
    grad_and_loss; this shim supports simple marked-variable use where the
    forward is re-traced."""
    raise MXNetError(
        "imperative backward() requires the taped-execution mode; use "
        "autograd.grad_and_loss(func)(args) which differentiates the "
        "function directly via jax.vjp")
