"""Imperative autograd (reference: python/mxnet/contrib/autograd.py +
src/ndarray/autograd.cc).

The reference records executed imperative ops on a tape and replays a
GraphExecutor backward (autograd.cc:132-188). TPU-native: the tape IS
``jax.vjp`` — ``grad_and_loss`` traces the python function with jax arrays
and differentiates it, no graph rebuild. ``mark_variables`` +
``train_section`` + ``backward`` reproduce the contrib API.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["grad_and_loss", "grad", "mark_variables", "backward",
           "train_section", "test_section", "set_is_training",
           "is_training"]

_STATE = {"train": False}
_MARKED = {}      # id(var cell) -> (var, grad_cell, req)
_TAPE = []        # recorded _TapeEntry, in execution order


class _TapeEntry:
    """One recorded imperative op (reference: AGNode, autograd.h).

    Inputs are stored as (cell id, captured value): if the id resolves to
    a marked variable or an earlier entry's output at replay time the
    value flows through the graph, otherwise the captured constant is
    used. Output cells are recorded by id so later entries (and
    ``backward(outputs)``) can refer to them. ``replay`` is a pure
    function list-of-arrays -> list-of-arrays.

    The entry keeps strong references to the input and output handles:
    ids are only unique while the object is alive, so without the refs a
    temporary freed mid-section could have its id reused by a new
    unrelated array and the tape would silently wire the wrong value.
    The refs (and the entries) are dropped when the tape is cleared.
    """

    __slots__ = ("replay", "in_ids", "in_consts", "out_ids",
                 "_in_handles", "_out_handles")

    def __init__(self, replay, in_handles, in_consts, out_handles):
        self.replay = replay
        self._in_handles = list(in_handles)
        self._out_handles = list(out_handles)
        self.in_ids = [id(h) for h in self._in_handles]
        self.in_consts = in_consts
        self.out_ids = [id(h) for h in self._out_handles]


def _record_fn(replay, input_handles, input_vals, output_handles):
    """Generic tape hook (NDArray operators record through this)."""
    if not _STATE["train"]:
        return
    _TAPE.append(_TapeEntry(replay, input_handles, list(input_vals),
                            output_handles))


def _record(opdef, attrs, input_handles, input_vals, output_handles, rng):
    """Called by imperative_invoke for every registry op while training."""
    if not _STATE["train"] or opdef.mutate_inputs:
        return
    n_aux = len(opdef.aux_names(attrs))

    def replay(vals):
        split = len(vals) - n_aux if n_aux else len(vals)
        outs, _ = opdef.forward(attrs, vals[:split], vals[split:],
                                True, rng)
        return outs

    _record_fn(replay, input_handles, input_vals, output_handles)


def set_is_training(is_train):
    prev = _STATE["train"]
    _STATE["train"] = bool(is_train)
    return prev


def is_training():
    return _STATE["train"]


@contextmanager
def train_section():
    """reference: contrib/autograd.py train_section."""
    prev = set_is_training(True)
    try:
        yield
    finally:
        set_is_training(prev)


@contextmanager
def test_section():
    prev = set_is_training(False)
    try:
        yield
    finally:
        set_is_training(prev)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Associate grad buffers with variables.
    reference: autograd.cc MarkVariables."""
    if isinstance(variables, NDArray):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad_cell, req in zip(variables, gradients, grad_reqs):
        _MARKED[id(var)] = (var, grad_cell, req)


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient and loss.
    reference: contrib/autograd.py grad_and_loss."""
    @functools.wraps(func)
    def wrapped(*args):
        nd_args = [a for a in args]
        jax_args = [a.asjax() if isinstance(a, NDArray) else jnp.asarray(a)
                    for a in nd_args]
        argnums = argnum if argnum is not None else tuple(range(len(args)))
        if isinstance(argnums, int):
            argnums = (argnums,)

        def f(*xs):
            wrapped_args = [NDArray(x) for x in xs]
            out = func(*wrapped_args)
            if isinstance(out, (list, tuple)):
                return [o.asjax() if isinstance(o, NDArray) else o
                        for o in out]
            return out.asjax() if isinstance(out, NDArray) else out

        outputs, vjp_fn = jax.vjp(f, *jax_args)
        if isinstance(outputs, (list, tuple)):
            head = [jnp.ones_like(o) for o in outputs]
        else:
            head = jnp.ones_like(outputs)
        all_grads = vjp_fn(head)
        grads = [NDArray(all_grads[i]) for i in argnums]
        outs = [NDArray(o) for o in outputs] \
            if isinstance(outputs, (list, tuple)) else NDArray(outputs)
        return grads, outs
    return wrapped


def grad(func, argnum=None):
    """reference: contrib/autograd.py grad."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped


def backward(outputs, out_grads=None, retain_graph=False):
    """Differentiate taped imperative work back to the marked variables.

    reference: contrib/autograd.py backward -> AutogradRuntime::
    ComputeGradient (autograd.cc:132-188), which rebuilds a graph from
    the tape and runs a GraphExecutor backward. Here the tape replays as
    a pure jax function of the marked leaves and ``jax.vjp`` produces the
    gradients, which land in the buffers given to ``mark_variables``
    honoring each req (write/add/null).
    """
    if isinstance(outputs, NDArray):
        outputs = [outputs]
    if out_grads is not None and isinstance(out_grads, NDArray):
        out_grads = [out_grads]
    if not _TAPE:
        raise MXNetError(
            "no imperative ops were recorded — run the computation inside "
            "a train_section() with variables marked first")

    out_ids_req = [id(o) for o in outputs]
    # prune the tape to the sub-graph feeding the requested outputs:
    # entries on unrelated branches are neither replayed nor
    # differentiated (the reference builds its backward graph only from
    # the requested heads, autograd.cc:132-188)
    needed = set(out_ids_req)
    kept = []
    for e in reversed(_TAPE):
        if any(o in needed for o in e.out_ids):
            kept.append(e)
            needed.update(e.in_ids)
    tape = list(reversed(kept))
    if not tape:
        raise MXNetError(
            "backward() got outputs that were not produced by recorded "
            "ops in this train_section")
    # Leaves are only the marked variables this (pruned) tape actually
    # consumed — computing grads for every variable ever marked would
    # clobber the grad buffers of unrelated models with zeros (the
    # reference scopes its tape per recording session, autograd.cc:54-68).
    used = set()
    for e in tape:
        used.update(e.in_ids)
    leaves = {vid: var.asjax() for vid, (var, _, _) in _MARKED.items()
              if vid in used}
    leaf_ids = list(leaves)
    out_ids = out_ids_req

    def replay(leaf_vals):
        env = dict(zip(leaf_ids, leaf_vals))
        for e in tape:
            vals = [env.get(i, c) for i, c in zip(e.in_ids, e.in_consts)]
            outs = e.replay(vals)
            for oid, val in zip(e.out_ids, outs):
                env[oid] = val
        missing = [i for i in out_ids if i not in env]
        if missing:
            raise MXNetError(
                "backward() got outputs that were not produced by recorded "
                "ops in this train_section")
        return [env[i] for i in out_ids]

    out_vals, vjp_fn = jax.vjp(replay, list(leaves.values()))
    if out_grads is None:
        heads = [jnp.ones_like(o) for o in out_vals]
    else:
        heads = [g.asjax() if isinstance(g, NDArray) else jnp.asarray(g)
                 for g in out_grads]
    (leaf_grads,) = vjp_fn(heads)

    for vid, g in zip(leaf_ids, leaf_grads):
        _, grad_cell, req = _MARKED[vid]
        if req == "null" or grad_cell is None:
            continue
        if req == "add":
            grad_cell._set(grad_cell.asjax() + g.astype(grad_cell.dtype))
        else:
            grad_cell._set(g.astype(grad_cell.dtype))
    if not retain_graph:
        _TAPE.clear()
