"""Determinism/replay audit (DT4xx).

The serve plane's replay contract (record a trace, replay it
bit-identically), the program cache (same key => same program), and the
health plane's divergence triage all assume program construction and
scheduler decisions are deterministic functions of their declared
inputs. Three nondeterminism sources keep sneaking in:

* **DT401** — wall-clock reads (``time.time`` / ``time.monotonic`` /
  ``time.perf_counter``) off the injectable-clock seam.  Everything in
  ``serve/`` must route timing through ``serve.clock`` so replay can
  substitute the recorded clock; a direct read makes latency-dependent
  decisions unreplayable.
* **DT402** — unseeded global-RNG draws (``random.random()``,
  ``np.random.rand()`` …) inside graph build or scheduler decisions.
  Sampling must flow through an explicitly seeded generator
  (``np.random.Generator(PCG64(seed))``, ``jax.random`` keys);
  module-global draws make two builds of the same symbol differ.
* **DT403** — iteration over an unordered ``set`` feeding program
  structure or key order.  ``for x in {...}`` (or ``tuple(set(...))``)
  hashes differently across processes (PYTHONHASHSEED), so op order —
  and therefore the traced program and its cache key — changes between
  runs.  ``sorted(...)`` over the set is the fix and is exempt.

Scope: the replayable serve path (``serve/*.py``, minus ``clock.py``
which *is* the seam) plus program construction (``executor.py``,
``module/executor_group.py``, ``program_cache.py``,
``kernel_tier.py``).  A ``# mxlint: allow(DT40x)`` comment on the line
suppresses a finding with intent recorded (e.g. a log-only timestamp).

CLI: ``python tools/mxlint.py --determinism-audit`` (and inside
``--check``). Test/CLI-time only — no bind-time cost.
"""
from __future__ import annotations

import ast
import os
import re

__all__ = ["audit", "scan_source", "SCAN_FILES"]

#: scanned files, relative to mxnet_tpu/. serve/ is globbed; clock.py
#: is the injectable seam itself and is exempt from DT401.
SCAN_FILES = ("executor.py", os.path.join("module", "executor_group.py"),
              "program_cache.py", "kernel_tier.py")

_ALLOW_RE = re.compile(r"#\s*mxlint:\s*allow\(\s*(DT4\d\d)\s*\)")

#: wall-clock entry points (DT401). time.sleep is not a clock *read*.
_CLOCK_FNS = {"time", "monotonic", "perf_counter", "monotonic_ns",
              "perf_counter_ns", "time_ns"}

#: module-global draw functions of random / numpy.random (DT402).
_DRAW_FNS = {"random", "randint", "randrange", "uniform", "choice",
             "choices", "shuffle", "sample", "gauss", "normal",
             "rand", "randn", "permutation", "standard_normal",
             "exponential", "poisson", "binomial", "beta", "gamma"}

#: receivers whose draws are module-global state (seeded generator
#: objects and jax.random are fine and keyed explicitly)
_GLOBAL_RNG = {"random", "np.random", "numpy.random"}


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_set_expr(node):
    """Expression that evaluates to an unordered set."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        # set algebra: s | t, s & t, s - t, s ^ t on set displays
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel_path, allow, dt401_exempt):
        self.rel = rel_path
        self.allow = allow          # line -> set of allowed rules
        self.dt401_exempt = dt401_exempt
        self.findings = []
        self.allowed = []           # suppressed-with-intent records
        self._sorted_depth = 0

    def _emit(self, rule, node, message, hint):
        line = getattr(node, "lineno", 0)
        if rule in self.allow.get(line, ()):
            self.allowed.append({"file": self.rel, "rule": rule,
                                 "line": line})
            return
        self.findings.append({"target": self.rel, "rule": rule,
                              "severity": "error", "node": None,
                              "line": line, "message": message,
                              "hint": None or hint})

    def visit_Call(self, node):
        d = _dotted(node.func)
        if d:
            root, _, leaf = d.rpartition(".")
            if not self.dt401_exempt and root.split(".")[-1] == "time" \
                    and leaf in _CLOCK_FNS:
                self._emit(
                    "DT401", node,
                    f"{self.rel}:{node.lineno} reads the wall clock "
                    f"({d}()) off the injectable-clock seam — "
                    "replay cannot substitute the recorded time",
                    "route through serve.clock (now()/monotonic()) or "
                    "annotate the line `# mxlint: allow(DT401)` for "
                    "log-only timestamps")
            if leaf in _DRAW_FNS and root in _GLOBAL_RNG:
                self._emit(
                    "DT402", node,
                    f"{self.rel}:{node.lineno} draws from the "
                    f"module-global RNG ({d}()) inside graph build or "
                    "scheduler code — two builds of the same inputs "
                    "diverge",
                    "draw from an explicitly seeded "
                    "np.random.Generator(PCG64(seed)) / jax.random "
                    "key, or annotate `# mxlint: allow(DT402)`")
        if isinstance(node.func, ast.Name) and \
                node.func.id == "sorted":
            self._sorted_depth += 1
            self.generic_visit(node)
            self._sorted_depth -= 1
            return
        if isinstance(node.func, ast.Name) and \
                node.func.id in ("tuple", "list") and node.args and \
                _is_set_expr(node.args[0]) and not self._sorted_depth:
            self._emit(
                "DT403", node,
                f"{self.rel}:{node.lineno} materializes a set in "
                "arbitrary iteration order "
                f"({node.func.id}(set-expr)) — order varies with "
                "PYTHONHASHSEED and can reach program structure or "
                "key order",
                "wrap in sorted(...) so the order is a pure function "
                "of the contents, or annotate "
                "`# mxlint: allow(DT403)`")
        self.generic_visit(node)

    def visit_For(self, node):
        if _is_set_expr(node.iter):
            self._emit(
                "DT403", node,
                f"{self.rel}:{node.lineno} iterates a set in "
                "arbitrary order — order varies with PYTHONHASHSEED "
                "and can reach program structure or key order",
                "iterate sorted(...) of the set, or annotate "
                "`# mxlint: allow(DT403)`")
        self.generic_visit(node)


def scan_source(source, rel_path, dt401_exempt=False):
    """Scan one file's source; returns (findings, allowed)."""
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as e:
        return ([{"target": rel_path, "rule": "XX001",
                  "severity": "info", "node": None,
                  "line": getattr(e, "lineno", 0) or 0,
                  "message": f"determinism audit could not parse: {e}",
                  "hint": None}], [])
    allow = {}
    for i, text in enumerate(source.splitlines(), 1):
        m = _ALLOW_RE.search(text)
        if m:
            allow.setdefault(i, set()).add(m.group(1))
    v = _Visitor(rel_path, allow, dt401_exempt)
    v.visit(tree)
    return v.findings, v.allowed


def _corpus(repo_root):
    pkg = os.path.join(repo_root, "mxnet_tpu")
    out = []
    serve = os.path.join(pkg, "serve")
    if os.path.isdir(serve):
        for fn in sorted(os.listdir(serve)):
            if fn.endswith(".py"):
                out.append((os.path.join("serve", fn),
                            fn == "clock.py"))
    for rel in SCAN_FILES:
        out.append((rel, False))
    return out


def audit(repo_root=None, sources=None):
    """Run the determinism audit; returns a result dict.

    ``sources`` maps rel_path -> source text for the seeded fixtures
    (clock.py basenames stay DT401-exempt, matching the real seam).
    """
    findings, allowed = [], []
    files = 0
    if sources is not None:
        items = [(rel, os.path.basename(rel) == "clock.py")
                 for rel in sorted(sources)]
        read = lambda rel: sources[rel]
    else:
        items = _corpus(repo_root)
        pkg = os.path.join(repo_root, "mxnet_tpu")

        def read(rel):
            with open(os.path.join(pkg, rel)) as f:
                return f.read()
    for rel, exempt in items:
        try:
            src = read(rel)
        except OSError:
            continue
        files += 1
        f, a = scan_source(src, rel.replace(os.sep, "/"),
                           dt401_exempt=exempt)
        findings.extend(f)
        allowed.extend(a)
    return {"findings": findings, "allowed": allowed,
            "files_scanned": files,
            "ok": not [f for f in findings
                       if f["severity"] == "error"]}
