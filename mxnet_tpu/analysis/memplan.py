"""Static memory planner (ME8xx): peak HBM per binding, before compile.

The only memory evidence this framework had was *runtime*: the NDArray
ledger (telemetry/memory.py) after arrays exist, and the fused step's
``remat.residual_bytes`` — an ``eval_shape`` trace that needs a bound
module and an armed optimizer. This module predicts the same bill from
the Symbol graph alone: a liveness/residual analysis over the executor's
topo order, layout-aware for everything that now decides the footprint —
per-``MXNET_REMAT_POLICY`` residual sets (mirroring the measured
``remat.residual_bytes`` semantics op by op, see below), dtype-aware
param bytes (int8/fp8 quant weights and fp8 KV-cache cells count
1 B/elem), ZeRO's 1/N flat state
shards, SPMD param specs, donation credits, and the batch buffers —
divided across the mesh. Zero compiles, zero traces, no jax import.

Residual model (validated against ``jax.vjp`` + ``eval_shape`` on the
bundled models; the tier-1 agreement gate pins resnet20 within 5% for
all three policies):

* ``none`` — the saved set is the union of per-op saves, deduplicated
  at the *entry* (node-output) level exactly as partial-eval residuals
  are: conv/dense save their data input (grad_w needs it), BatchNorm
  saves its input plus the normalized copy (when gradient actually
  flows), activations save their input, elementwise adds / pooling /
  movement save nothing, loss heads save their output (the custom-vjp
  ``(prob, label)`` pair) — plus every backward-reachable param;
* ``dots`` — ``remat.DOT_SAVEABLE_OPS`` outputs + program inputs
  (params + batch): the static mirror of
  ``jax.checkpoint_policies.dots_saveable``;
* ``all`` — program inputs only (params + batch).

Surfaces: ``mxlint --memory-plan <model> --policy dots --batch 256``,
``DataParallelExecutorGroup.static_memory_plan()`` (the batch-bucket
headroom gate's static fast path, cross-checked against the eval_shape
number in tests), the ``memory_planner`` analysis pass (ME801
predicted-OOM, ME802 headroom-admits-larger-bucket) and a "memory plan"
section in ``tools/diagnose.py`` via the ``memplan.*`` gauges.
"""
from __future__ import annotations

import numpy as np

from .diagnostics import Diagnostic
from .precision import entry_dtypes, dtype_name, _label_names

__all__ = ["OPTIMIZER_STATE_MULT", "state_multiplier", "plan_symbol",
           "plan_findings", "record_plan", "format_plan"]

#: optimizer -> param-shaped f32 state arrays the fused plan carries
OPTIMIZER_STATE_MULT = {
    "sgd": 1.0,            # momentum buffer (mom=0 still allocates it)
    "sgd_mom": 1.0, "nag": 1.0, "ccsgd": 1.0, "sgld": 0.0,
    "adam": 2.0, "rmsprop": 1.0, "rmspropalex": 2.0,
    "adagrad": 1.0, "adadelta": 2.0, "ftrl": 2.0,
}

#: per-op residual behavior under policy "none" (see module docstring)
_SAVE_INPUT0_FOR_GRAD_W = frozenset({
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "QuantizedFullyConnected", "QuantizedConvolution", "RNN",
    "FusedConvBNReLU", "attention",
})
_SAVE_INPUT0_IF_GRAD = frozenset({
    "Activation", "LeakyReLU", "softmax", "log_softmax",
    "SoftmaxActivation", "sigmoid", "tanh", "relu", "clip", "square",
    "sqrt", "rsqrt", "exp", "log", "FusedBiasGeLU", "L2Normalization",
    "InstanceNorm", "LRN",
})
_NORM_OPS = frozenset({"BatchNorm", "LayerNorm"})
_SAVE_ALL_INPUTS_IF_GRAD = frozenset({
    "_mul", "elemwise_mul", "broadcast_mul", "_div", "elemwise_div",
    "broadcast_div", "_power", "broadcast_power", "_hypot",
    "broadcast_hypot", "_maximum", "broadcast_maximum", "_minimum",
    "broadcast_minimum",
})
_SAVE_NOTHING = frozenset({
    "_plus", "elemwise_add", "broadcast_add", "_minus", "elemwise_sub",
    "broadcast_sub", "Flatten", "flatten", "Reshape",
    "reshape", "transpose", "Cast", "cast", "_copy", "identity",
    "BlockGrad", "stop_gradient", "Concat", "concat", "SliceChannel",
    "split", "slice", "slice_axis", "expand_dims", "Embedding",
    "one_hot", "_zeros", "_ones", "_arange", "add_n",
    # RoPE is linear in x (fixed-angle rotation): its vjp is the inverse
    # rotation, no activation saved beyond the (T, D/2) trig tables;
    # attention_decode is inference-only (never differentiated)
    "RoPE", "attention_decode",
})


def state_multiplier(optimizer):
    """f32 param-shaped state arrays for one optimizer (by name or
    instance); unknown optimizers estimate 1."""
    name = optimizer if isinstance(optimizer, str) else \
        type(optimizer).__name__
    return OPTIMIZER_STATE_MULT.get(str(name).lower(), 1.0)


def _opdef_of(node):
    try:
        return node.opdef()
    except Exception:
        return None


def _nelems(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _itemsize(name):
    # fp8 storage (quant weights, KV cache cells) is 1 B/elem; resolve
    # it by name so the no-jax contract holds even when ml_dtypes has
    # not registered the dtype with numpy
    if str(name).startswith("float8"):
        return 1
    try:
        return np.dtype(name).itemsize
    except TypeError:
        return 4


def _default_prefix_cache_bytes():
    """The serve-plane prefix store's byte budget, charged only when
    the operator armed it (``MXNET_SERVE_PREFIX_CACHE_MB`` set in the
    environment): plans for non-serving bindings stay byte-identical."""
    import os
    raw = os.environ.get("MXNET_SERVE_PREFIX_CACHE_MB")
    if raw is None:
        return 0
    try:
        return int(max(0.0, float(raw)) * (1 << 20))
    except ValueError:
        return 0


def plan_symbol(symbol, shapes, policy="none", for_training=True,
                optimizer="sgd_mom", compute_dtype=None, n_data=1,
                spmd_plan=None, zero=False, donation=True,
                fixed_params=(), state_bytes=None, batch_axis=0,
                prefix_cache_bytes=None):
    """Static peak-HBM plan for one (symbol, input shapes) binding.

    ``shapes`` maps data/label names to concrete shapes (the same dict
    ``infer_shape``/``simple_bind`` take) — those names classify as
    batch buffers, every other argument as a parameter. Returns a plan
    dict; raises MXNetError only when shape inference itself fails.

    ``n_data`` divides the batch-linear components (batch, activations,
    outputs) for the per-device view; ``spmd_plan`` (a
    ``parallel.spmd.SpmdPlan``) additionally shards param/state bytes
    per its PartitionSpecs; ``zero`` shards optimizer state 1/N over the
    data axis (ZeRO-1's flat layout). ``state_bytes`` overrides the
    optimizer-multiplier estimate with an exact figure (the exec group
    knows its armed state tree). ``donation=False`` adds the
    double-buffer params+state a non-donating (staged) update pays.

    ``prefix_cache_bytes`` charges the serving prefix store's byte
    budget (``serve.prefix.PrefixStore``) against slot-pooled decode
    bindings — ``None`` reads ``MXNET_SERVE_PREFIX_CACHE_MB`` when set
    (else 0), so ME801 gates HBM with the store's worst case included
    before anything compiles. The charge applies only to graphs with a
    ``per_slot`` stateful decode op (the store snapshots their rows).
    """
    shapes = dict(shapes)
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    known = dict(zip(arg_names, arg_shapes))
    known.update(zip(aux_names, aux_shapes))
    entry_shapes = symbol._infer_entry_shapes(known)
    dtypes = entry_dtypes(symbol, compute_dtype=compute_dtype)

    nodes = symbol._topo_nodes()
    by_id = {id(n): n for n in nodes}

    def entry_bytes(key):
        node = by_id.get(key[0])
        store = entry_shapes.get(key[0])
        s = store[key[1]] if store and key[1] < len(store) else None
        if node is not None and node.is_variable and s is None:
            s = known.get(node.name)
        if s is None or 0 in tuple(s):
            return 0
        return _nelems(s) * _itemsize(dtypes.get(key, "float32"))

    # labels ride the batch even when the caller seeded only the data
    # shape (inference fills them in): never classify them as params
    batch_names = set(shapes) | _label_names(symbol)
    param_nodes = [n for n in nodes if n.is_variable
                   and not n._extra.get("__is_aux__")
                   and n.name not in batch_names]
    watched = [n for n in param_nodes if n.name not in set(fixed_params)
               and dtypes.get((id(n), 0)) not in ("int8",
                                                  "float8_e4m3fn",
                                                  "float8_e5m2")]

    def shard_fraction(name, shape):
        if spmd_plan is None:
            return 1.0
        try:
            frac = spmd_plan.param_shard_fraction(name, shape)
        except Exception:
            frac = 1.0
        return frac

    param_bytes = sum(
        int(entry_bytes((id(n), 0)) * shard_fraction(
            n.name, known.get(n.name) or ()))
        for n in param_nodes)
    watched_f32 = sum(_nelems(known[n.name]) * 4 for n in watched
                      if known.get(n.name))
    batch_bytes = sum(entry_bytes((id(n), 0)) for n in nodes
                      if n.is_variable and n.name in batch_names)
    aux_bytes = sum(_nelems(s) * 4 for s in aux_shapes if s is not None)
    # KV-cache accounting: a stateful-inference op's aux states (the
    # fixed-capacity K/V cache + cursor) are the decode path's dominant
    # resident bytes — charge them into the per-op table so the plan
    # names WHERE the HBM goes, not just that aux is big
    kv_charges = []
    for n in nodes:
        if n.is_variable:
            continue
        try:
            opdef = n.opdef()
        except Exception:
            continue
        if not getattr(opdef, "stateful_infer", False):
            continue
        aux_n = len(opdef.aux_names(n.attrs))
        if not aux_n:
            continue
        nb = 0
        for inp, idx in n.inputs[len(n.inputs) - aux_n:]:
            store = known.get(inp.name)
            if store is not None and 0 not in tuple(store):
                nb += _nelems(store) * _itemsize(
                    dtypes.get((id(inp), idx), "float32"))
        kv_charges.append((n.op, nb))
    kv_cache_bytes = sum(nb for _, nb in kv_charges)
    # prefix-store accounting: the serving plane's prefix cache holds
    # snapshots of these same rows under its own byte budget — a
    # slot-pooled decode binding pays the full budget up front so ME801
    # trips BEFORE the store could grow into an OOM
    from ..base import parse_bool as _parse_bool
    per_slot_decode = any(
        not n.is_variable and _parse_bool(n.attrs.get("per_slot", False))
        and getattr(_opdef_of(n), "stateful_infer", False)
        for n in nodes)
    prefix_store_bytes = 0
    if per_slot_decode and kv_charges:
        prefix_store_bytes = int(prefix_cache_bytes
                                 if prefix_cache_bytes is not None
                                 else _default_prefix_cache_bytes())
    output_bytes = sum(_nelems(s) * 4 for s in out_shapes
                       if s is not None)

    per_op_bytes = {}

    def charge(op, nbytes):
        if nbytes:
            per_op_bytes[op] = per_op_bytes.get(op, 0) + int(nbytes)

    for _op, _nb in kv_charges:
        charge(_op, _nb)
    charge("prefix_store", prefix_store_bytes)

    residual = 0
    if for_training:
        residual = _residual_bytes(
            nodes, entry_bytes, policy,
            watched={n.name for n in watched},
            batch_names=batch_names,
            param_bytes=sum(entry_bytes((id(n), 0))
                            for n in param_nodes),
            batch_bytes=batch_bytes, charge=charge)

    grad_bytes = watched_f32 if for_training else 0
    if state_bytes is None:
        state_bytes = (state_multiplier(optimizer) * watched_f32
                       if for_training else 0)
    state_bytes = int(state_bytes)
    n_state_shards = max(1, int(n_data)) if zero else 1
    state_dev = state_bytes // n_state_shards
    nd = max(1, int(n_data))

    fixed_dev = param_bytes + state_dev + aux_bytes + prefix_store_bytes
    linear_dev = (batch_bytes + residual + output_bytes) // nd
    peak_dev = fixed_dev + grad_bytes + linear_dev
    if for_training and not donation:
        peak_dev += param_bytes + state_dev     # staged double-buffer

    batch_size = None
    for name in shapes:
        s = shapes[name]
        if s and len(s) > batch_axis:
            batch_size = int(s[batch_axis])
            break
    per_sample = ((residual + batch_bytes) / batch_size
                  if batch_size else None)

    return {
        "policy": policy,
        "for_training": bool(for_training),
        "batch_size": batch_size,
        "n_data": nd,
        "zero": bool(zero),
        "param_bytes": int(param_bytes),
        "grad_bytes": int(grad_bytes),
        "state_bytes": int(state_bytes),
        "state_bytes_per_device": int(state_dev),
        "aux_bytes": int(aux_bytes),
        "kv_cache_bytes": int(kv_cache_bytes),
        "prefix_store_bytes": int(prefix_store_bytes),
        "batch_bytes": int(batch_bytes),
        "residual_bytes": int(residual),
        "output_bytes": int(output_bytes),
        "fixed_bytes": int(fixed_dev),
        "per_sample_bytes": per_sample,
        "peak_bytes_per_device": int(peak_dev),
        "per_op_bytes": per_op_bytes,
    }


def _residual_bytes(nodes, entry_bytes, policy, watched, batch_names,
                    param_bytes, batch_bytes, charge):
    """Policy-conditional residual set (see module docstring)."""
    by_id = {id(n): n for n in nodes}
    from .. import remat as _remat
    if policy == "all":
        return param_bytes + batch_bytes
    if policy == "dots":
        total = param_bytes + batch_bytes
        for n in nodes:
            if n.is_variable or n.op not in _remat.DOT_SAVEABLE_OPS:
                continue
            nb = entry_bytes((id(n), 0))
            charge(n.op, nb)
            total += nb
        return total

    # policy "none": entry-level saved-set walk with dedup
    needs_grad = {}
    for n in nodes:
        if n.is_variable:
            needs_grad[id(n)] = n.name in watched
        else:
            needs_grad[id(n)] = any(needs_grad.get(id(inp), False)
                                    for inp, _ in n.inputs)

    saved = {}          # entry key -> charged op (dedup)
    synthetic = 0

    def mark(key, op):
        if key not in saved:
            saved[key] = op

    for n in nodes:
        if n.is_variable:
            continue
        try:
            opdef = n.opdef()
            aux_n = len(opdef.aux_names(n.attrs))
            is_loss = opdef.is_loss
        except Exception:
            aux_n, is_loss = 0, False
        ins = n.inputs[:len(n.inputs) - aux_n] if aux_n else n.inputs
        in0 = ins[0] if ins else None
        op = n.op
        if is_loss:
            nb = entry_bytes((id(n), 0))
            synthetic += nb
            charge(op, nb)
            if len(ins) > 1:
                mark((id(ins[1][0]), ins[1][1]), op)
            continue
        if op in _SAVE_NOTHING:
            continue
        if op in _SAVE_INPUT0_FOR_GRAD_W:
            # grad_w needs the data input whenever the weight trains
            trains = any(inp.is_variable and inp.name in watched
                         for inp, _ in ins[1:]) or \
                (in0 is not None and needs_grad.get(id(in0[0]), False))
            if trains and in0 is not None:
                mark((id(in0[0]), in0[1]), op)
            continue
        if op in _NORM_OPS:
            if in0 is None:
                continue
            x_key = (id(in0[0]), in0[1])
            gamma_trains = any(
                inp.is_variable and inp.name in watched
                for inp, _ in ins[1:])
            from ..base import parse_bool
            fix_gamma = parse_bool(n.attrs.get("fix_gamma", False))
            if needs_grad.get(id(in0[0]), False):
                # grad_x path: x plus the normalized copy stay saved
                mark(x_key, op)
                nb = entry_bytes(x_key)
                synthetic += nb
                charge(op, nb)
            elif gamma_trains and not fix_gamma:
                nb = entry_bytes(x_key)     # x-hat only (grad_gamma)
                synthetic += nb
                charge(op, nb)
            continue
        if op == "Pooling":
            # max pooling re-derives its argmax from the saved input
            # during backward; avg/sum pool gradients are input-free
            if str(n.attrs.get("pool_type", "max")) == "max" and \
                    in0 is not None and \
                    needs_grad.get(id(in0[0]), False):
                mark((id(in0[0]), in0[1]), op)
            continue
        if op in _SAVE_INPUT0_IF_GRAD:
            if in0 is not None and needs_grad.get(id(in0[0]), False):
                mark((id(in0[0]), in0[1]), op)
            continue
        if op in _SAVE_ALL_INPUTS_IF_GRAD:
            if needs_grad.get(id(n), False):
                for inp, idx in ins:
                    mark((id(inp), idx), op)
            continue
        if op == "Dropout":
            nb = entry_bytes((id(n), 0))    # the kept-mask
            synthetic += nb
            charge(op, nb)
            continue
        # unknown op: conservative — save its data input when gradient
        # flows through it (the dominant vjp pattern)
        if in0 is not None and needs_grad.get(id(in0[0]), False):
            mark((id(in0[0]), in0[1]), op)

    total = synthetic
    for key, op in saved.items():
        src = by_id.get(key[0])
        # params are counted once via the param_bytes term below
        if src is not None and src.is_variable and \
                src.name not in batch_names:
            continue
        nb = entry_bytes(key)
        charge(op, nb)
        total += nb
    # every backward-reachable param is a residual leaf too (weights
    # feed grad_x, gamma feeds the BN backward)
    total += param_bytes
    return total


def plan_findings(plan, capacity_bytes=None, buckets=None, where=""):
    """ME8xx diagnostics for one plan against a device capacity."""
    found = []
    if not capacity_bytes:
        return found
    peak = plan["peak_bytes_per_device"]
    tag = f" ({where})" if where else ""
    if peak > capacity_bytes:
        found.append(Diagnostic(
            "ME801", f"predicted peak {peak / 1e9:.2f} GB exceeds the "
            f"device capacity {capacity_bytes / 1e9:.2f} GB at batch "
            f"{plan['batch_size']} under policy "
            f"{plan['policy']!r}{tag}",
            hint="shrink the batch bucket, pick a stronger remat "
                 "policy (dots/all), enable ZeRO, or shard params "
                 "(mxlint --memory-plan compares policies statically)"))
        return found
    if buckets and plan.get("per_sample_bytes"):
        from ..telemetry.memory import batch_headroom
        fixed = plan["fixed_bytes"] + plan["grad_bytes"]
        admitted = batch_headroom(capacity_bytes, fixed,
                                  plan["per_sample_bytes"], buckets)
        if admitted and plan["batch_size"] and \
                admitted > plan["batch_size"]:
            found.append(Diagnostic(
                "ME802", f"headroom admits batch {admitted} (now "
                f"{plan['batch_size']}) under policy "
                f"{plan['policy']!r}: "
                f"{(capacity_bytes - peak) / 1e9:.2f} GB spare{tag}",
                hint="raise the batch bucket to claim the remat/ZeRO-"
                     "freed HBM (docs/performance.md)"))
    return found


def record_plan(plan, model=""):
    """Mirror a plan into telemetry (memplan.* gauges + a flight-ring
    note) so tools/diagnose.py renders a 'memory plan' section."""
    try:
        from .. import telemetry as _telemetry
        labels = {"policy": plan["policy"]}
        if model:
            labels["model"] = model
        for key in ("peak_bytes_per_device", "residual_bytes",
                    "param_bytes", "state_bytes", "batch_bytes"):
            _telemetry.gauge(f"memplan.{key}", **labels).set(plan[key])
        _telemetry.flightrec.note(
            "memplan.plan", model=model, policy=plan["policy"],
            batch=plan["batch_size"] or 0,
            peak_bytes=plan["peak_bytes_per_device"],
            residual_bytes=plan["residual_bytes"])
    except Exception:   # telemetry must never break planning
        pass
    return plan


def format_plan(plan, model="", capacity_bytes=None):
    """Human-readable plan section (mxlint/diagnose rendering)."""
    mb = 1.0 / (1 << 20)

    def f(k):
        return f"{plan[k] * mb:10.2f} MiB"

    head = f"memory plan{f' for {model}' if model else ''}: " \
           f"policy={plan['policy']} batch={plan['batch_size']} " \
           f"devices={plan['n_data']}" \
           f"{' zero' if plan['zero'] else ''}"
    lines = [head,
             f"  params        {f('param_bytes')}",
             f"  grads         {f('grad_bytes')}",
             f"  opt state     {f('state_bytes_per_device')}"
             f"{' (1/%d shard)' % plan['n_data'] if plan['zero'] else ''}",
             f"  batch         {f('batch_bytes')}",
             f"  residuals     {f('residual_bytes')}",
             f"  outputs+aux   "
             f"{(plan['output_bytes'] + plan['aux_bytes']) * mb:10.2f}"
             " MiB",
             f"  peak/device   {f('peak_bytes_per_device')}"]
    if capacity_bytes:
        frac = plan["peak_bytes_per_device"] / capacity_bytes
        lines.append(f"  capacity      {capacity_bytes * mb:10.2f} MiB "
                     f"({frac:.0%} used)")
    return "\n".join(lines)
