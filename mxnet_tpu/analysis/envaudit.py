"""Env-var doc-sync lint: ``MXNET_*`` reads vs ``docs/env_var.md``.

The configuration surface grows one env knob per PR and the doc rots
silently — a knob nobody can discover is a knob that ships
half-supported. This audit keeps the two in lockstep, ast-based so it
survives formatting:

* **code scan** — every ``*.py`` under ``mxnet_tpu/`` (plus the repo's
  ``bench.py``, which reads its own knobs) is parsed and
  every string constant that IS an ``MXNET_*`` name is collected: the
  codebase's convention is that such a literal is always an environ
  key — ``os.environ.get/[...]``, ``os.getenv``, the ``_env_int``-style
  wrappers, and the env dicts recovery re-exec writes. Mentions inside
  docstrings or longer messages are not full-token literals and do not
  count as reads. f-string keys (``f"MXNET_RETRY_{site}"``) contribute
  their literal *prefix*, matched against doc rows by prefix;
* **doc scan** — every ``MXNET_*`` token in ``docs/env_var.md``;
* **drift** — code vars missing a doc row fail the audit, and so do
  dead doc rows naming vars no code touches.

CLI: ``python tools/mxlint.py --env-audit`` (nonzero exit on drift —
the CI gate); the test suite runs the same audit in-process.
"""
from __future__ import annotations

import ast
import os
import re

__all__ = ["scan_code", "scan_docs", "audit"]

_NAME_RE = re.compile(r"MXNET_[A-Z0-9_]+")


def _collect_keys(expr, exact):
    """Record a literal env-key expression as an exact name."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
            and expr.value.startswith("MXNET_"):
        m = _NAME_RE.match(expr.value)
        if m and m.group(0) == expr.value:
            exact.add(expr.value)


def _collect_prefix(expr, prefixes):
    """A ``f"MXNET_FOO_{x}"`` anywhere declares a constructed env-key
    family; its leading MXNET_* literal becomes a prefix."""
    if isinstance(expr, ast.JoinedStr) and expr.values:
        first = expr.values[0]
        if isinstance(first, ast.Constant) and \
                isinstance(first.value, str):
            m = _NAME_RE.match(first.value)
            if m:
                prefixes.add(m.group(0))


def _scan_file(path, exact, prefixes):
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant):
            _collect_keys(node, exact)
        elif isinstance(node, ast.JoinedStr):
            _collect_prefix(node, prefixes)


def scan_code(root, extra_files=()):
    """(exact_names, prefixes) of MXNET_* environ keys under ``root``
    plus any ``extra_files`` (bench.py reads knobs too — e.g. the
    ``MXNET_SERVE_SPEC_DRAFT`` draft preset — and those must stay
    documented like everything else)."""
    exact, prefixes = set(), set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            _scan_file(os.path.join(dirpath, fname), exact, prefixes)
    for path in extra_files:
        _scan_file(path, exact, prefixes)
    return exact, prefixes


def scan_docs(doc_path):
    """All MXNET_* tokens appearing in the doc."""
    with open(doc_path) as f:
        return set(_NAME_RE.findall(f.read()))


def audit(repo_root):
    """Run the doc-sync audit; returns a result dict.

    ``undocumented``: env vars the code reads with no doc row (a
    prefix-read like MXNET_RETRY_* is covered when at least one doc row
    starts with the prefix). ``dead``: doc rows naming vars no code
    touches (exactly or via a prefix read). Empty both ways = in sync.
    """
    code_root = os.path.join(repo_root, "mxnet_tpu")
    doc_path = os.path.join(repo_root, "docs", "env_var.md")
    exact, prefixes = scan_code(
        code_root,
        extra_files=(os.path.join(repo_root, "bench.py"),))
    doc = scan_docs(doc_path)

    def doc_covers(name):
        if name in doc:
            return True
        # a code var constructed from a documented-prefix family row
        return any(name.startswith(p) and any(
            d.startswith(p) for d in doc) for p in prefixes)

    def code_covers(name):
        if name in exact:
            return True
        return any(name.startswith(p) for p in prefixes)

    undocumented = sorted(n for n in exact if not doc_covers(n))
    dead = sorted(n for n in doc if not code_covers(n))
    return {"undocumented": undocumented, "dead": dead,
            "code_vars": sorted(exact), "code_prefixes": sorted(prefixes),
            "doc_vars": sorted(doc),
            "ok": not undocumented and not dead}
