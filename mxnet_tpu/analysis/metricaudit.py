"""Metric-name doc-sync lint: recorded metrics vs docs/telemetry.md.

The observability surface grows a few metric families per PR and the
catalog rots silently — a metric nobody can discover is a dashboard
nobody builds. The PR-12 env audit solved exactly this shape of drift
for env vars; this is its mirror for the metrics registry, ast-based so
it survives formatting:

* **code scan** — every ``*.py`` under ``mxnet_tpu/`` is parsed and
  every ``counter(...)``/``gauge(...)``/``histogram(...)`` call site
  contributes its metric name. Names are resolved best-effort within
  the enclosing function scope: plain literals, ``name + ".seconds"``
  concatenations, and ``a if cond else b`` literal ternaries all
  resolve to exact names; f-string names (``f"serve.decode.{key}"``)
  contribute their literal *prefix*. ``hist=``/``_hist=`` keyword
  literals (the span-to-histogram feed) count as exact histogram
  names, and ``metric_prefix=`` keywords (and defaults) declare a
  ``<prefix>.`` family (the circuit breaker's ``.state``/
  ``.transitions`` gauges). Docstring mentions are not calls and never
  count;
* **doc scan** — the "Metric catalog" section of docs/telemetry.md:
  every backticked token in the section is a catalog row; rows with a
  ``<placeholder>`` segment (``step.phase.<phase>.seconds``) document
  a prefix family;
* **drift** — code metrics missing a catalog row fail the audit, and
  so do dead catalog rows naming metrics no code records. A code
  f-string family with no catalog row for its prefix fails too
  (reported as ``prefix*``).

CLI: ``python tools/mxlint.py --metric-audit`` (nonzero exit on drift —
the CI gate); the test suite runs the same audit in-process next to
``--env-audit``.
"""
from __future__ import annotations

import ast
import os
import re

__all__ = ["scan_code", "scan_docs", "audit", "CATALOG_HEADING"]

CATALOG_HEADING = "## Metric catalog"

_METRIC_FNS = {"counter", "gauge", "histogram"}
_HIST_KWARGS = {"hist", "_hist"}

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
_PREFIX_RE = re.compile(r"^[a-z][a-z0-9_.]*\.$")
_DOC_TOKEN_RE = re.compile(r"`([^`\s]+)`")


# ------------------------------------------------------------- code scan
def _resolve(node, env, depth=0):
    """Best-effort set of string values an expression can take within
    its function scope; None when unresolvable."""
    if depth > 6:
        return None
    if isinstance(node, ast.Constant):
        return {node.value} if isinstance(node.value, str) else None
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.IfExp):
        a = _resolve(node.body, env, depth + 1)
        b = _resolve(node.orelse, env, depth + 1)
        return (a or set()) | (b or set()) if (a or b) else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve(node.left, env, depth + 1)
        right = _resolve(node.right, env, depth + 1)
        if left and right:
            return {a + b for a in left for b in right}
        return None
    return None


def _joined_prefix(node):
    """The leading literal of an f-string, when it has one."""
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and \
                isinstance(first.value, str) and first.value:
            return first.value
    return None


def _call_fn_name(node):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _scope_nodes(scope):
    """Child nodes of a scope, not descending into nested function
    scopes (classes are transparent: methods become their own scopes
    via the outer walk, class-level assigns belong to the class body)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _scan_scope(scope, exact, prefixes):
    env = {}
    for node in _scope_nodes(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            vals = _resolve(node.value, env)
            if vals:
                name = node.targets[0].id
                env[name] = env.get(name, set()) | vals
    for node in _scope_nodes(scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a metric_prefix="..." default declares the family the
            # function records under when callers don't override
            for arg, default in zip(node.args.args[-len(node.args.defaults):]
                                    if node.args.defaults else [],
                                    node.args.defaults):
                if arg.arg == "metric_prefix" and \
                        isinstance(default, ast.Constant) and \
                        isinstance(default.value, str):
                    prefixes.add(default.value + ".")
            continue
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg in _HIST_KWARGS:
                for v in _resolve(kw.value, env) or ():
                    if _NAME_RE.match(v):
                        exact.add(v)
            elif kw.arg == "metric_prefix":
                for v in _resolve(kw.value, env) or ():
                    prefixes.add(v + ".")
        if _call_fn_name(node) not in _METRIC_FNS or not node.args:
            continue
        arg0 = node.args[0]
        resolved = _resolve(arg0, env)
        if resolved:
            for v in resolved:
                if _NAME_RE.match(v):
                    exact.add(v)
            continue
        prefix = _joined_prefix(arg0)
        if prefix is not None and _PREFIX_RE.match(prefix):
            prefixes.add(prefix)


def scan_code(root):
    """(exact_names, prefixes) of recorded metric names under ``root``."""
    exact, prefixes = set(), set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path) as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            scopes = [tree] + [n for n in ast.walk(tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
            for scope in scopes:
                _scan_scope(scope, exact, prefixes)
    return exact, prefixes


# -------------------------------------------------------------- doc scan
def scan_docs(doc_path):
    """(exact_rows, prefix_rows) from the doc's Metric catalog section.

    Only the catalog section counts — prose elsewhere may mention
    metric names without cataloguing them. A backticked token with a
    ``<placeholder>`` documents the family of names sharing its literal
    prefix."""
    with open(doc_path) as f:
        text = f.read()
    exact, prefixes = set(), set()
    in_section = False
    for line in text.splitlines():
        if line.strip() == CATALOG_HEADING:
            in_section = True
            continue
        if in_section and line.startswith("## "):
            break
        if not in_section:
            continue
        for token in _DOC_TOKEN_RE.findall(line):
            if "<" in token:
                prefix = token.split("<", 1)[0]
                if _PREFIX_RE.match(prefix):
                    prefixes.add(prefix)
            elif _NAME_RE.match(token):
                exact.add(token)
    return exact, prefixes


# ----------------------------------------------------------------- audit
def audit(repo_root):
    """Run the doc-sync audit; returns a result dict.

    ``undocumented``: metric names the code records with no catalog row
    (an f-string family is covered when a catalog row falls under its
    prefix; uncovered families report as ``prefix*``). ``dead``:
    catalog rows naming metrics no code records (exactly or via a
    family). Empty both ways = in sync.
    """
    code_root = os.path.join(repo_root, "mxnet_tpu")
    doc_path = os.path.join(repo_root, "docs", "telemetry.md")
    exact, prefixes = scan_code(code_root)
    doc_exact, doc_prefixes = scan_docs(doc_path)

    def doc_covers(name):
        if name in doc_exact:
            return True
        return any(name.startswith(p) for p in doc_prefixes)

    def doc_covers_family(prefix):
        if any(d.startswith(prefix) for d in doc_exact):
            return True
        return any(d.startswith(prefix) or prefix.startswith(d)
                   for d in doc_prefixes)

    def code_covers(name):
        if name in exact:
            return True
        return any(name.startswith(p) for p in prefixes)

    def code_covers_family(prefix):
        if any(e.startswith(prefix) for e in exact):
            return True
        return any(c.startswith(prefix) or prefix.startswith(c)
                   for c in prefixes)

    undocumented = sorted(n for n in exact if not doc_covers(n))
    undocumented += sorted(f"{p}*" for p in prefixes
                           if not doc_covers_family(p))
    dead = sorted(d for d in doc_exact if not code_covers(d))
    dead += sorted(f"{p}*" for p in doc_prefixes
                   if not code_covers_family(p))
    return {"undocumented": undocumented, "dead": dead,
            "code_names": sorted(exact),
            "code_prefixes": sorted(prefixes),
            "doc_names": sorted(doc_exact),
            "doc_prefixes": sorted(doc_prefixes),
            "ok": not undocumented and not dead}
