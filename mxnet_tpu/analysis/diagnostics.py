"""Structured diagnostics for the bind-time static-analysis passes.

Every finding a pass emits is a :class:`Diagnostic` — rule id, severity,
human message, node provenance, and a fix hint — collected into a
:class:`Report`. The rule catalog below is the single source of truth:
``tools/mxlint.py --rules`` prints it, docs/analysis.md documents it,
and tests assert against the ids, so a rule exists exactly when it has
a row here.

Rule id scheme (the NNVM-pass analog of compiler warning numbers):

* ``GV1xx`` — graph verifier (shapes, dtypes, structure)
* ``DA2xx`` — donation / aliasing hazards
* ``CO3xx`` — collective dispatch order
* ``RC2xx`` — host-concurrency race lint (shared state across threads)
* ``RC4xx`` — retrace / program-cache churn
* ``HS5xx`` — host synchronization in the fit hot path
* ``MF6xx`` — MFU/cost-metadata coverage
* ``QT7xx`` — precision flow (mixed precision + the int8 quant rewrite)
* ``ME8xx`` — static memory planner (predicted-OOM before compile)
* ``PK9xx`` — Pallas kernel registration (VMEM/tiling/dtype feasibility)
* ``CK3xx`` — program-cache-key completeness (knob registry vs. key)
* ``DT4xx`` — determinism/replay audit (clock, RNG, set order)
* ``XX0xx`` — analysis-infrastructure notices

Severities: ``error`` (the program is wrong or will crash/deadlock),
``warning`` (probably a bug or a large avoidable cost), ``info``
(intentional-but-costly arrangements worth surfacing). ``raise`` mode
raises on errors only; ``mxlint`` exits nonzero on errors (``--strict``
promotes warnings).
"""
from __future__ import annotations

__all__ = ["Diagnostic", "Report", "RULES", "SEVERITIES"]

SEVERITIES = ("info", "warning", "error")

#: rule id -> (default severity, one-line title)
RULES = {
    # ---- graph verifier -------------------------------------------------
    "GV101": ("error", "shape/type inference failed over the graph"),
    "GV102": ("warning", "shape inference left argument/output shapes "
                         "unknown"),
    "GV103": ("error", "two distinct variables share one name"),
    "GV104": ("warning", "two distinct op nodes share one name"),
    "GV105": ("warning", "declared variable dtype conflicts with the "
                         "bound array"),
    "GV106": ("error", "dangling node input (bad index or forward "
                       "reference) in the JSON graph"),
    "GV107": ("warning", "inference stalled at an op registered without "
                         "infer_shape or a shape_passthrough flag"),
    "GV108": ("warning", "dead node unreachable from any graph head"),
    # ---- donation / aliasing -------------------------------------------
    "DA201": ("error", "buffer aliased into a donated fused/scan argument "
                       "(use-after-donation)"),
    "DA202": ("warning", "fused step donates parameter cells shared with "
                         "another executor group"),
    "DA203": ("error", "donated parameter name doubles as a data/label "
                       "input"),
    "DA204": ("warning", "one buffer staged under two kvstore keys in the "
                         "same bucket window"),
    # ---- collective order ----------------------------------------------
    "CO301": ("error", "bucket all-reduce order depends on grad-ready "
                       "arrival order (cross-worker divergence)"),
    "CO302": ("error", "in-program reduce-scatter plan armed together "
                       "with a dist kvstore reduction"),
    "CO303": ("error", "in-program collective order diverges from the "
                       "parameter declaration order"),
    # ---- host-concurrency race lint -------------------------------------
    "RC201": ("error", "shared attribute written cross-thread with no "
                       "common lock on every access path"),
    "RC202": ("error", "shared attribute guarded inconsistently (two "
                       "different locks, no common guard)"),
    "RC203": ("error", "two locks acquired in opposite orders on "
                       "different paths (deadlock shape)"),
    # ---- retrace / cache churn -----------------------------------------
    "RC401": ("warning", "op attr value is not cache-key stable "
                         "(identity repr, array, or non-finite float)"),
    "RC402": ("warning", "binding is not program-cacheable; every rebind "
                         "re-traces"),
    # ---- host sync ------------------------------------------------------
    "HS501": ("warning", "NaiveEngine serializes every op through the "
                         "host in the fit hot path"),
    "HS502": ("info", "monitor tap forces eager per-op execution with "
                      "device->host transfers"),
    "HS503": ("info", "training graph re-emits a bare input variable as "
                      "an output every step"),
    "HS504": ("info", "MXNET_FUSED_KEEP_GRADS materializes every "
                      "gradient as a program output"),
    # ---- sharding / SPMD plan ------------------------------------------
    "SH601": ("error", "bound array sharding diverges from the SPMD "
                       "plan's PartitionSpec"),
    "SH602": ("warning", "ctx_group-tagged parameter degraded to full "
                         "replication on the model axis"),
    "SH603": ("error", "donated SPMD-carry entry whose sharding cannot "
                       "alias the program output (donation breaks)"),
    # ---- MFU coverage ---------------------------------------------------
    "MF601": ("info", "op has no flops/bytes cost metadata (invisible "
                      "to MFU/roofline accounting)"),
    # ---- precision flow -------------------------------------------------
    "QT701": ("warning", "silent float32 upcast inside a reduced-"
                         "precision (bf16/fp16) compute graph"),
    "QT702": ("error", "Quantized op consumes a weight that was never "
                       "rewritten to int8 + scale"),
    "QT703": ("error", "int8-quantized weight shared with a "
                       "non-quantized consumer (reads raw int8 codes)"),
    "QT704": ("warning", "dequantize->requantize round-trip (int8 -> "
                         "float -> int8 detour)"),
    "QT705": ("warning", "loss-head accumulation narrower than float32"),
    # ---- static memory planner ------------------------------------------
    "ME801": ("error", "predicted peak HBM exceeds device capacity "
                       "(OOM before anything compiles)"),
    "ME802": ("info", "device-memory headroom admits a larger batch "
                      "bucket"),
    # ---- Pallas kernel registration -------------------------------------
    "PK901": ("error", "declared kernel tile working set exceeds the "
                       "per-generation VMEM budget"),
    "PK902": ("error", "declared kernel tile violates lane/sublane "
                       "alignment (last dim % 128, dtype sublane rows)"),
    "PK903": ("error", "kernel variant declares no (or unsupported) "
                       "dtype coverage for the numerics gate"),
    # ---- program-cache-key completeness ---------------------------------
    "CK301": ("error", "shape-affecting knob read during program "
                       "construction but absent from the cache key"),
    "CK302": ("error", "tagged cache-key element that no registered "
                       "knob declares (dead or undeclared freight)"),
    "CK303": ("error", "autotune-key/program-key divergence for one "
                       "registered knob"),
    # ---- determinism / replay audit -------------------------------------
    "DT401": ("error", "wall-clock read off the injectable-clock seam "
                       "in the replayable serve path"),
    "DT402": ("error", "module-global RNG draw inside graph build or "
                       "scheduler decisions"),
    "DT403": ("error", "unordered set iteration feeding program "
                       "structure or cache-key order"),
    # ---- infrastructure -------------------------------------------------
    "XX001": ("info", "an analysis pass failed to run"),
}


class Diagnostic:
    """One finding: rule id + severity + message + node provenance."""

    __slots__ = ("rule", "severity", "message", "node", "op", "hint")

    def __init__(self, rule, message, node=None, op=None, hint=None,
                 severity=None):
        if rule not in RULES:
            raise ValueError(f"unknown lint rule id {rule!r}")
        self.rule = rule
        self.severity = severity or RULES[rule][0]
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")
        self.message = message
        self.node = node          # node name (provenance), or None
        self.op = op              # op name, or None
        self.hint = hint          # how to fix / suppress

    def format(self):
        where = ""
        if self.node:
            where = f" at node '{self.node}'"
            if self.op:
                where += f" ({self.op})"
        elif self.op:
            where = f" at op '{self.op}'"
        text = f"{self.rule} [{self.severity}]{where}: {self.message}"
        if self.hint:
            text += f"  — hint: {self.hint}"
        return text

    def as_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "message": self.message, "node": self.node, "op": self.op,
                "hint": self.hint}

    def __repr__(self):
        return f"<Diagnostic {self.format()}>"


class Report:
    """Ordered collection of diagnostics from one analysis run."""

    def __init__(self, diagnostics=None):
        self.diagnostics = list(diagnostics or [])

    def add(self, diag):
        self.diagnostics.append(diag)

    def __len__(self):
        return len(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def by_severity(self, severity):
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self):
        return self.by_severity("error")

    @property
    def warnings(self):
        return self.by_severity("warning")

    @property
    def infos(self):
        return self.by_severity("info")

    @property
    def rules(self):
        """Set of rule ids that fired."""
        return {d.rule for d in self.diagnostics}

    def format(self):
        if not self.diagnostics:
            return "no findings"
        return "\n".join(d.format() for d in self.diagnostics)

    def as_dict(self):
        return {"findings": [d.as_dict() for d in self.diagnostics],
                "errors": len(self.errors), "warnings": len(self.warnings),
                "infos": len(self.infos)}

    def __repr__(self):
        return (f"<Report {len(self.errors)} errors, "
                f"{len(self.warnings)} warnings, {len(self.infos)} infos>")
