"""Pallas kernel registration validator (PK9xx).

A VMEM-infeasible or misaligned kernel variant used to fail *silently*:
Mosaic rejects the tile at autotune time, the numerics gate or the
timer never selects it, and the kernel just never wins — the work of
writing it evaporates with no diagnostic. This module moves that
failure to **import time**: ``OpDef.add_variant(...,
kernel_spec=...)`` declares the variant's worst-case VMEM-resident
tiles and the dtype set its eligibility admits, and registration
validates the declaration against hard TPU constraints:

* ``PK901`` — the declared tiles' combined working set exceeds the
  per-generation VMEM budget (the min across
  ``telemetry.mfu.PEAKS[*]["vmem_bytes"]``: a portable kernel must fit
  the smallest core it may autotune on);
* ``PK902`` — a declared tile violates lane/sublane alignment: the
  last dim must be a multiple of 128 lanes and the second-to-last a
  multiple of the dtype's sublane rows (f32 8, bf16 16, int8/fp8 32);
* ``PK903`` — the declared dtype coverage is empty or names a dtype
  the kernel tier's numerics gate cannot compare.

``kernel_spec`` schema (plain dict, validated here)::

    {"tiles": [((rows, cols), "float32"), ...],   # worst-case blocks
               # resident in VMEM simultaneously (inputs + outputs +
               # scratch at the eligibility bounds)
     "dtypes": ("float32", "bfloat16")}           # numerics-gate set

Failures raise ``MXNetError`` naming the op, the variant, and the rule
id — the registration analog of ``bind(validate="raise")``.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["SUPPORTED_GATE_DTYPES", "SUBLANE_ROWS", "LANES",
           "validate_kernel_spec", "tile_bytes"]

#: dtypes the kernel-tier numerics gate can compare against the XLA
#: reference (kernel_tier.py gates every variant before selection)
SUPPORTED_GATE_DTYPES = frozenset({
    "float32", "bfloat16", "float16", "int8", "int32", "uint8",
    "float8_e4m3fn", "float8_e5m2",
})

#: minimum second-to-last-dim rows per dtype (TPU tiling: the last dim
#: is always 128 lanes; sublanes scale inversely with element width)
SUBLANE_ROWS = {
    "float32": 8, "int32": 8, "uint32": 8,
    "bfloat16": 16, "float16": 16,
    "int8": 32, "uint8": 32, "float8_e4m3fn": 32, "float8_e5m2": 32,
}
LANES = 128

_ITEMSIZE = {"float32": 4, "int32": 4, "uint32": 4, "bfloat16": 2,
             "float16": 2, "int8": 1, "uint8": 1,
             "float8_e4m3fn": 1, "float8_e5m2": 1}


def tile_bytes(shape, dtype):
    n = 1
    for d in shape:
        n *= int(d)
    return n * _ITEMSIZE.get(str(dtype), 4)


def _budget():
    from ..telemetry.mfu import min_vmem_budget
    return min_vmem_budget()


def validate_kernel_spec(op_name, variant, spec):
    """Validate one variant's kernel_spec; raises MXNetError with the
    failing PK9xx rule id. Returns the spec on success."""
    where = f"op {op_name!r} variant {variant!r}"
    if not isinstance(spec, dict):
        raise MXNetError(f"PK903: {where}: kernel_spec must be a dict "
                         f"with 'tiles' and 'dtypes', got "
                         f"{type(spec).__name__}")

    dtypes = tuple(str(d) for d in spec.get("dtypes", ()))
    if not dtypes:
        raise MXNetError(
            f"PK903: {where} declares no dtype coverage; the numerics "
            "gate cannot qualify a kernel with no comparable dtypes")
    bad = [d for d in dtypes if d not in SUPPORTED_GATE_DTYPES]
    if bad:
        raise MXNetError(
            f"PK903: {where} declares dtype(s) {bad} outside the "
            f"numerics gate's coverage {sorted(SUPPORTED_GATE_DTYPES)}")

    tiles = spec.get("tiles", ())
    total = 0
    for entry in tiles:
        shape, dtype = entry
        shape = tuple(int(d) for d in shape)
        dtype = str(dtype)
        if any(d <= 0 for d in shape):
            raise MXNetError(
                f"PK902: {where} tile {shape} has a non-positive dim")
        if shape[-1] % LANES != 0:
            raise MXNetError(
                f"PK902: {where} tile {shape} ({dtype}): last dim "
                f"{shape[-1]} is not a multiple of {LANES} lanes")
        sublane = SUBLANE_ROWS.get(dtype, 8)
        if len(shape) >= 2 and shape[-2] % sublane != 0:
            raise MXNetError(
                f"PK902: {where} tile {shape} ({dtype}): sublane dim "
                f"{shape[-2]} is not a multiple of {sublane} rows "
                f"({dtype} packs {sublane}-row sublanes)")
        total += tile_bytes(shape, dtype)
    budget = _budget()
    if total > budget:
        raise MXNetError(
            f"PK901: {where} declares a {total / (1 << 20):.1f} MiB "
            f"VMEM working set; the per-generation budget is "
            f"{budget / (1 << 20):.0f} MiB — shrink the block caps or "
            "tighten the eligibility bounds")
    return spec
