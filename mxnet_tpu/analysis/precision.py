"""Precision-flow dataflow pass (QT7xx): dtypes through the bound graph.

The executor already decides real dtypes at bind time — ``__dtype__``
declarations bind typed cells (the int8 quant tier), ``compute_dtype``
casts float variables at graph entry (mixed precision), and the
Quantized* ops carry int8/f32 input contracts — but until now nothing
*checked* the flow: a float weight feeding a ``QuantizedFullyConnected``
silently produced garbage, a stray f32 constant in a bf16 graph silently
widened the whole downstream chain, and an int8->float->int8 detour
(the dequant/requant round-trip a careless ``quantize_symbol`` composition
can introduce) just burned bytes. This pass re-runs the same forward
dtype propagation statically — declared ``__dtype__`` cells, bound array
dtypes, the registry's ``infer_type`` where an op registers one, and
attr-driven rules for Cast/creation/Quantized/loss ops — and audits the
result:

* ``QT701`` — a node inside a reduced-precision (bf16/fp16) graph whose
  output silently widens to f32 because one input is f32 (mixing, not an
  explicit Cast);
* ``QT702`` — a ``Quantized*`` node whose weight slot is not an int8
  entry: the weight was never rewritten to int8 + scale;
* ``QT703`` — an int8 weight feeding a Quantized weight slot that is
  *also* consumed by a non-quantized node, which would read the raw
  int8 codes as values;
* ``QT704`` — a Cast back to int8 whose source chain (through
  movement ops and casts) starts at an int8 entry: a dequant->requant
  round trip;
* ``QT705`` — a loss head whose *declared* input dtype is narrower than
  f32 (accumulating the loss in bf16/fp16). The ``compute_dtype`` mixed-
  precision path is exempt by design: master params stay f32 and the
  entry cast's vjp upcasts gradients, so accumulation is f32 there.

Pure observer over the Symbol graph — no jax import, no tracing.
"""
from __future__ import annotations

import numpy as np

from .diagnostics import Diagnostic

__all__ = ["entry_dtypes", "dtype_name", "is_reduced_float",
           "is_floating", "precision_flow"]

#: float dtypes narrower than f32 (the mixed-precision compute tier)
REDUCED_FLOATS = frozenset({"float16", "bfloat16"})
_FLOAT_WIDTH = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8}

#: ops that move data without touching values: dtype passes through
#: input 0 and a dequant->requant chain may thread through them (QT704)
_MOVEMENT_OPS = frozenset({
    "Reshape", "reshape", "Flatten", "flatten", "transpose", "_copy",
    "identity", "BlockGrad", "stop_gradient", "expand_dims", "slice",
    "slice_axis", "SliceChannel", "split", "repeat", "tile", "reverse",
    "flip", "swapaxes", "SwapAxis", "broadcast_axis", "broadcast_to",
    "Crop", "Pad", "pad",
})

_CAST_OPS = frozenset({"Cast", "cast"})

#: valid narrow storage dtypes for Quantized* weights (QT702-704):
#: the int8 PTQ tier and the fp8 serving tier (ops/quant.py)
_QUANT_STORAGE_DTYPES = frozenset({"int8", "float8_e4m3fn",
                                   "float8_e5m2"})


def dtype_name(dt):
    """Canonical dtype name; tolerates np dtypes, strings, ml_dtypes."""
    try:
        return str(np.dtype(dt))
    except TypeError:
        return str(dt)


def is_floating(name):
    return name in _FLOAT_WIDTH


def is_reduced_float(name):
    return name in REDUCED_FLOATS


def _promote(names):
    """jnp-style promotion over entry dtype names: widest float wins,
    else widest int; empty -> f32. (Deliberately NOT numpy promotion,
    which widens int32+f32 to f64 — XLA never does.)"""
    floats = [n for n in names if is_floating(n)]
    if floats:
        return max(floats, key=lambda n: _FLOAT_WIDTH[n])
    ints = [n for n in names if n.startswith(("int", "uint", "bool"))]
    if ints:
        return max(ints, key=lambda n: np.dtype(n).itemsize
                   if n != "bool" else 1)
    return names[0] if names else "float32"


def _label_names(symbol):
    """Variables feeding a loss head past slot 0 — exempt from
    compute_dtype casting (mirrors executor._build_graph_runner)."""
    labels = set()
    for node in symbol._topo_nodes():
        if node.is_variable:
            continue
        try:
            if node.opdef().is_loss:
                for inp, _ in node.inputs[1:]:
                    if inp.is_variable:
                        labels.add(inp.name)
        except Exception:  # unregistered op: no label exemption
            continue
    return labels


def entry_dtypes(symbol, compute_dtype=None, bound_dtypes=None):
    """Forward dtype propagation: {(id(node), out_idx): dtype name}.

    Entry dtypes seed from ``__dtype__`` declarations, then
    ``bound_dtypes`` (executor bindings), default f32; when
    ``compute_dtype`` is given, floating variables (except loss labels)
    take it — exactly the executor's graph-entry cast. Ops propagate via
    the registry's ``infer_type`` when registered, else attr/op-family
    rules, else promotion over the inputs.
    """
    bound_dtypes = bound_dtypes or {}
    cd = dtype_name(compute_dtype) if compute_dtype is not None else None
    labels = _label_names(symbol) if cd is not None else set()
    out = {}
    for node in symbol._topo_nodes():
        if node.is_variable:
            declared = node._extra.get("__dtype__")
            name = (dtype_name(declared) if declared
                    else dtype_name(bound_dtypes.get(node.name,
                                                     "float32")))
            if (cd is not None and is_floating(name)
                    and node.name not in labels):
                name = cd
            out[(id(node), 0)] = name
            continue
        try:
            opdef = node.opdef()
        except Exception:
            opdef = None
        in_names = [out.get((id(inp), idx), "float32")
                    for inp, idx in node.inputs]
        n_out = opdef.num_outputs(node.attrs) if opdef is not None else 1
        names = None
        if opdef is not None and opdef.infer_type is not None:
            try:
                _in, outs, _aux = opdef.infer_type(
                    node.attrs, [np.dtype(n) if n != "bfloat16" else n
                                 for n in in_names])
                names = [dtype_name(t) for t in outs]
            except Exception:
                names = None
        if names is None:
            if node.op in _CAST_OPS and node.attrs.get("dtype"):
                names = [dtype_name(node.attrs["dtype"])] * n_out
            elif "dtype" in node.attrs and not node.inputs:
                # creation ops (_zeros/_ones/_arange): dtype attr rules
                names = [dtype_name(node.attrs["dtype"])] * n_out
            elif node.op.startswith("Quantized"):
                # data in, data dtype out (dequant happens inside)
                names = [in_names[0] if in_names else "float32"] * n_out
            elif node.op == "Embedding":
                # output follows the table, not the int ids
                names = [in_names[1] if len(in_names) > 1
                         else "float32"] * n_out
            elif opdef is not None and opdef.is_loss:
                names = [in_names[0] if in_names else "float32"] * n_out
            elif node.op in _MOVEMENT_OPS:
                names = [in_names[0] if in_names else "float32"] * n_out
            else:
                aux_n = (len(opdef.aux_names(node.attrs))
                         if opdef is not None else 0)
                regular = in_names[:len(in_names) - aux_n] if aux_n \
                    else in_names
                names = [_promote(regular)] * n_out
        for i in range(n_out):
            out[(id(node), i)] = names[i] if i < len(names) else names[-1]
    return out


_F32 = np.dtype("float32")


def _bound_var_dtypes(executor):
    """Bound cells that deviate from the f32 default — the only
    entries the propagation needs seeded (absent names default f32).
    Kept to raw dtype compares: this runs on every validated bind and
    rides inside the <2% warm-bind overhead gate."""
    out = {}
    for nm, a in zip(executor.arg_names, executor.arg_arrays):
        if a is None:
            continue
        d = getattr(getattr(a, "_data", None), "dtype", None)
        if d is None:
            d = np.dtype(a.dtype)
        if d != _F32:
            out[nm] = str(d)
    return out


def _has_precision_surface(symbol):
    """Can any QT rule fire on this graph absent reduced/bound-typed
    entries? Declared dtypes and Quantized nodes are the only
    dtype-independent triggers; memoized per symbol so the all-f32
    steady state short-circuits the whole pass."""
    for n in symbol._topo_nodes():
        if n.is_variable:
            if "__dtype__" in n._extra:
                return True
        elif n.op.startswith("Quantized"):
            return True
    return False


def _regular_inputs(node):
    try:
        aux_n = len(node.opdef().aux_names(node.attrs))
    except Exception:
        aux_n = 0
    return node.inputs[:len(node.inputs) - aux_n] if aux_n \
        else node.inputs


def precision_flow(ctx, out):
    """The QT7xx pass body (registered in passes.PASSES)."""
    sym = ctx.symbol
    exe = ctx.executor
    if sym is None and exe is not None:
        sym = exe._symbol
    if sym is None:
        return
    compute_dtype = getattr(ctx, "compute_dtype", None)
    if compute_dtype is None and exe is not None:
        compute_dtype = getattr(exe, "_compute_dtype", None)
    bound = _bound_var_dtypes(exe) if exe is not None else {}

    from .passes import _symbol_memo  # lazy: avoid circular import
    if compute_dtype is None and not bound and not _symbol_memo(
            sym, "precision_surface", None,
            lambda: _has_precision_surface(sym)):
        return      # all-f32 graph, no quant surface: nothing can fire
    memo_key = (dtype_name(compute_dtype) if compute_dtype is not None
                else None, tuple(sorted(bound.items())))
    out.extend(_symbol_memo(
        sym, "precision_flow", memo_key,
        lambda: _audit(sym, compute_dtype, bound)))


def _audit(sym, compute_dtype, bound):
    found = []
    nodes = sym._topo_nodes()
    dtypes = entry_dtypes(sym, compute_dtype=compute_dtype,
                          bound_dtypes=bound)
    cd_name = dtype_name(compute_dtype) if compute_dtype is not None \
        else None
    reduced_graph = (cd_name in REDUCED_FLOATS) or any(
        is_reduced_float(dtypes[(id(n), 0)])
        for n in nodes if n.is_variable)

    # QT701: silent f32 widening inside a reduced-precision graph.
    # Explicit Casts and loss heads (upcasting *into* the loss is the
    # QT705 fix, not a hazard) are exempt.
    if reduced_graph:
        flagged_ops = set()
        for n in nodes:
            if n.is_variable or n.op in _CAST_OPS:
                continue
            try:
                if n.opdef().is_loss:
                    continue
            except Exception:
                pass
            if dtypes.get((id(n), 0)) != "float32":
                continue
            in_names = [dtypes.get((id(inp), idx), "float32")
                        for inp, idx in _regular_inputs(n)]
            if any(is_reduced_float(nm) for nm in in_names) and \
                    "float32" in in_names and n.op not in flagged_ops:
                flagged_ops.add(n.op)
                found.append(Diagnostic(
                    "QT701", f"node {n.name!r} mixes "
                    f"{[nm for nm in in_names if is_reduced_float(nm)][0]}"
                    " and float32 inputs; the output (and everything "
                    "downstream) silently widens to float32",
                    node=n.name, op=n.op,
                    hint="cast the f32 operand (or declare/create it at "
                         "the compute dtype); use an explicit Cast if "
                         "the upcast is intended"))

    # QT702/703: the quant-rewrite contract around Quantized* ops —
    # int8 and fp8 (float8_e4m3fn) storage are both valid tiers
    quant_weight_vars = set()
    for n in nodes:
        if n.is_variable or not n.op.startswith("Quantized"):
            continue
        ins = _regular_inputs(n)
        if len(ins) < 2:
            continue
        wnode, widx = ins[1]
        wdt = dtypes.get((id(wnode), widx), "float32")
        if wdt not in _QUANT_STORAGE_DTYPES:
            found.append(Diagnostic(
                "QT702", f"{n.op} node {n.name!r} consumes weight "
                f"{wnode.name!r} of dtype {wdt}; the quant rewrite "
                "never produced a narrow-storage + scale pair for it",
                node=n.name, op=n.op,
                hint="run quantize_symbol over the trained symbol (or "
                     "bind the _q/_scale params it produced)"))
        elif wnode.is_variable:
            quant_weight_vars.add(id(wnode))

    if quant_weight_vars:
        for n in nodes:
            if n.is_variable:
                continue
            for i, (inp, _idx) in enumerate(_regular_inputs(n)):
                if id(inp) not in quant_weight_vars:
                    continue
                if n.op.startswith("Quantized") and i == 1:
                    continue
                found.append(Diagnostic(
                    "QT703", f"quantized weight {inp.name!r} also feeds "
                    f"{n.op} node {n.name!r} (slot {i}), which reads "
                    "the raw storage codes as values",
                    node=n.name, op=n.op,
                    hint="keep a float copy for the non-quantized "
                         "consumer, or route it through the Quantized "
                         "op"))

    # QT704: Cast back to a narrow storage dtype (int8 or fp8) whose
    # source chain is already that dtype — a dequant->requant round
    # trip. A legitimate fp8 dequant chain (storage -> f32 compute,
    # never cast back) does not trip this.
    for n in nodes:
        if n.is_variable or n.op not in _CAST_OPS:
            continue
        target = dtype_name(n.attrs.get("dtype", ""))
        if target not in _QUANT_STORAGE_DTYPES:
            continue
        src, sidx = n.inputs[0] if n.inputs else (None, 0)
        hops = 0
        while (src is not None and not src.is_variable and hops < 64
               and (src.op in _MOVEMENT_OPS or src.op in _CAST_OPS)
               and src.inputs):
            src, sidx = src.inputs[0]
            hops += 1
        if src is not None and \
                dtypes.get((id(src), sidx)) == target and hops >= 0 \
                and (id(src), sidx) != (id(n.inputs[0][0]),
                                        n.inputs[0][1]):
            found.append(Diagnostic(
                "QT704", f"Cast node {n.name!r} requantizes to "
                f"{target} a chain that starts {target} at "
                f"{src.name!r}: a dequantize->requantize round trip",
                node=n.name, op=n.op,
                hint="drop the float detour; quantize_symbol already "
                     "produces narrow-storage weights consumed in "
                     "place"))

    # QT705: loss-head accumulation narrower than f32 BY DECLARATION
    # (a second propagation without compute_dtype: the mixed-precision
    # entry cast keeps f32 master accumulation and is exempt)
    declared = entry_dtypes(sym, compute_dtype=None, bound_dtypes=bound) \
        if compute_dtype is not None else dtypes
    for n in nodes:
        if n.is_variable:
            continue
        try:
            if not n.opdef().is_loss:
                continue
        except Exception:
            continue
        ins = _regular_inputs(n)
        if not ins:
            continue
        pnode, pidx = ins[0]
        pdt = declared.get((id(pnode), pidx), "float32")
        if is_reduced_float(pdt):
            found.append(Diagnostic(
                "QT705", f"loss head {n.name!r} accumulates in {pdt}; "
                "bf16/fp16 loss accumulation loses update signal at "
                "scale", node=n.name, op=n.op,
                hint="keep the loss head's input f32 (upcast before "
                     "the head, or use compute_dtype= mixed precision, "
                     "whose master params stay f32)"))
    return found
