"""Bind-time static-analysis passes over the bound graph.

The reference validated every graph with iterative NNVM passes
(InferShape/InferType, graph_executor.cc:425) *before* anything
executed. This module re-grows that discipline for the hazards this
framework actually has: donated fused/scan buffers, in-program
collective plans, ready-order bucket all-reduces, program-cache
keys, dtype flow through the mixed-precision/int8 tiers
(``precision_flow``/QT7xx, precision.py), and predicted-OOM memory
plans (``memory_planner``/ME8xx, memplan.py — inert unless armed). Each pass walks the Symbol node graph plus whatever execution
state is available (a bound Executor, an armed exec group's fused/scan
plan, a kvstore bucket scheduler) and emits structured diagnostics —
finding at bind time what PR 2's runtime NaN-poison and crash dumps
only catch at step 40k on a pod.

Passes are pure observers: they never mutate the graph, never dispatch
device work, and a pass that itself fails must never break a bind — a
crash inside a pass becomes an ``XX001`` info finding.

Entry points:

* ``lint_symbol(sym, shapes)`` / ``lint_executor(exe)`` /
  ``lint_module(mod)`` / ``lint_json(text)`` — build a context and run
  every applicable pass, returning a :class:`Report`;
* ``validate_executor(exe, mode)`` / ``validate_module(mod, mode)`` —
  the bind-time hooks behind ``bind(validate=...)`` and
  ``MXNET_GRAPH_VALIDATE`` (warn -> log, raise -> MXNetError on
  error-severity findings);
* findings mirror into the telemetry registry
  (``analysis.lint.findings`` counters) and the flight-recorder ring
  (``lint.finding`` records) so ``tools/diagnose.py`` reports them.

Suppression: ``MXNET_LINT_DISABLE`` takes a comma-separated list of
rule ids (``GV107,HS501``), pass names (``host_sync``), or ``all``.
"""
from __future__ import annotations

import json as _json
import logging
import os
from collections import OrderedDict

import numpy as np

from ..base import MXNetError
from ..program_cache import attr_cache_stable
from .diagnostics import Diagnostic, Report

__all__ = ["AnalysisContext", "PASSES", "run_passes", "lint_symbol",
           "lint_executor", "lint_module", "lint_json",
           "validate_executor", "validate_module", "resolve_mode",
           "attr_cache_stable"]

log = logging.getLogger(__name__)


class AnalysisContext:
    """Everything a pass may look at; absent fields disable the checks
    that need them (static analysis is best-effort by design)."""

    def __init__(self, symbol=None, known_shapes=None, executor=None,
                 exec_group=None, module=None, kvstore=None, sched=None,
                 json_graph=None, assume_multiworker=False,
                 compute_dtype=None, memplan=None):
        self.symbol = symbol
        self.known_shapes = dict(known_shapes or {})
        self.executor = executor
        self.exec_group = exec_group
        self.module = module
        self.kvstore = kvstore
        self.sched = sched            # kvstore_sched.BucketScheduler
        self.json_graph = json_graph  # raw dict of a symbol JSON
        # single-process runs can't diverge across workers; fixtures and
        # mxlint set this to audit a plan as if it ran on a multihost mesh
        self.assume_multiworker = assume_multiworker
        # precision_flow: simulate a mixed-precision binding; bound
        # executors contribute their own _compute_dtype when unset
        self.compute_dtype = compute_dtype
        # memory_planner: options dict ({"capacity_bytes":..., "policy":
        # ..., "buckets":...}); None (the default) keeps the planner
        # inert so bind-time lint stays inside the <2% overhead gate —
        # mxlint --memory-plan and MXNET_LINT_MEMPLAN_BUDGET arm it
        self.memplan = memplan


# --------------------------------------------------------------- helpers
def _symbol_memo(symbol, slot, key, compute):
    """Per-symbol memo for the O(nodes) pass portions.

    Binds repeat over the same (symbol, shapes) — train/eval pairs,
    force_rebind, every step of a bucketing cycle — and the graph walks
    (fixpoint inference, name/attr scans) are the only non-trivial
    validation cost, so warm-bind validation runs at dict-lookup prices
    (the <2% bind-time budget in benchmarks/lint_overhead.py). The memo
    assumes the de-facto immutability of built graphs; mutating a
    node's attrs after a lint serves stale findings for that symbol
    object.
    """
    memo = getattr(symbol, "_mx_lint_memo", None)
    if memo is None:
        memo = {}
        try:
            symbol._mx_lint_memo = memo
        except AttributeError:
            return compute()
    cached = memo.get(slot)
    if cached is not None and cached[0] == key:
        return cached[1]
    value = compute()
    memo[slot] = (key, value)
    return value


def _entry_shapes_cached(symbol, known):
    """Fixpoint entry shapes, memoized per (symbol, seed shapes)."""
    key = tuple(sorted(known.items()))
    return _symbol_memo(symbol, "entry_shapes", key,
                        lambda: symbol._infer_entry_shapes(known))


def _known_shapes(ctx):
    """Seed shapes: explicit ctx shapes, else every bound arg array."""
    if ctx.known_shapes:
        return dict(ctx.known_shapes)
    exe = ctx.executor
    if exe is not None:
        return {nm: tuple(a.shape)
                for nm, a in zip(exe.arg_names, exe.arg_arrays)
                if a is not None}
    return {}


# ================================================================ passes
def graph_verifier(ctx, out):
    """GV1xx: the InferShape/InferType discipline plus graph structure.

    JSON-only structural rules (GV106 dangling input, GV108 dead node)
    live in the same pass but run off ``ctx.json_graph`` because a
    loaded Symbol cannot represent either state (load_json would have
    crashed, and _topo_nodes only walks reachable nodes).
    """
    if ctx.json_graph is not None:
        _verify_json_graph(ctx.json_graph, out)
    sym = ctx.symbol
    if sym is None:
        return
    known = _known_shapes(ctx)
    shapes_key = tuple(sorted(known.items()))
    out.extend(_symbol_memo(
        sym, "graph_verifier", shapes_key,
        lambda: _verify_symbol(sym, known)))

    # GV105: declared dtype vs bound dtype (the declared-var list is
    # shape-independent — memoize it; the dtype compare is per binding)
    exe = ctx.executor
    if exe is not None:
        declared_vars = _symbol_memo(
            sym, "declared_dtypes", None,
            lambda: [(n.name, str(n._extra["__dtype__"]))
                     for n in sym._topo_nodes()
                     if n.is_variable and "__dtype__" in n._extra])
        if declared_vars:
            bound = dict(zip(exe.arg_names, exe.arg_arrays))
            for name, declared in declared_vars:
                arr = bound.get(name)
                if arr is None:
                    continue
                if str(np.dtype(arr.dtype)) != str(np.dtype(declared)):
                    out.append(Diagnostic(
                        "GV105", f"variable {name!r} declares dtype "
                        f"{declared} but is bound to "
                        f"{np.dtype(arr.dtype)}", node=name,
                        hint="bind an array of the declared dtype or "
                             "drop the declaration"))


def _verify_symbol(sym, known):
    """The symbol-level GV rules (everything derivable from the graph +
    seed shapes alone); memoized per (symbol, shapes)."""
    out = []
    nodes = sym._topo_nodes()

    # GV103/GV104: name collisions. Binding, attr_dict and the JSON wire
    # format all key by name — two distinct nodes sharing one are
    # silently merged on reload or bound to one buffer.
    seen = {}
    for n in nodes:
        other = seen.get(n.name)
        if other is None:
            seen[n.name] = n
        elif other is not n:
            if n.is_variable or other.is_variable:
                out.append(Diagnostic(
                    "GV103", f"variable name {n.name!r} is used by two "
                    "distinct nodes; binding by name is ambiguous",
                    node=n.name,
                    hint="rename one of the variables"))
            else:
                out.append(Diagnostic(
                    "GV104", f"op node name {n.name!r} is used by two "
                    "distinct nodes; attrs and JSON round-trips will "
                    "merge them", node=n.name, op=n.op,
                    hint="pass unique name= to the symbol calls"))

    # GV101/GV102/GV107: run the same fixpoint inference bind runs,
    # seeded with everything known, and audit what it could not settle.
    try:
        entry = _entry_shapes_cached(sym, known)
    except MXNetError as e:
        out.append(Diagnostic(
            "GV101", str(e),
            hint="fix the conflicting shapes (the message carries the "
                 "failing node's op, name, and input shapes)"))
        return out
    except Exception as e:  # noqa: BLE001 — a broken infer fn is a finding
        out.append(Diagnostic(
            "GV101", f"shape inference crashed: {type(e).__name__}: {e}",
            hint="fix the op's infer_shape function"))
        return out

    stalled_ops = set()
    for n in nodes:
        store = entry.get(id(n))
        if store is None:
            continue
        unknown = [s is None or 0 in s for s in store]
        if n.is_variable:
            continue
        if all(unknown) and n.op not in stalled_ops:
            in_known = any(
                (entry.get(id(inp)) or [None])[idx] is not None
                for inp, idx in n.inputs if id(inp) in entry
                and idx < len(entry[id(inp)]))
            opdef = n.opdef()
            if (in_known and opdef.infer_shape is None
                    and not getattr(opdef, "shape_passthrough", False)):
                stalled_ops.add(n.op)
                out.append(Diagnostic(
                    "GV107", f"op {n.op!r} has no infer_shape and no "
                    "shape_passthrough flag; inference stalls on "
                    "partial input shapes", node=n.name, op=n.op,
                    hint="register infer_shape (or shape_passthrough="
                         "True for identity-shaped ops)"))

    if known:
        # with seeds present, whatever stayed unknown will stay unknown
        # at run time too — the bind will allocate nothing for it
        missing = []
        for n in nodes:
            if n.is_variable:
                s = entry[id(n)][0]
                if s is None or 0 in s:
                    missing.append(n.name)
        for node, idx in sym._outputs:
            store = entry.get(id(node))
            s = store[idx] if store and idx < len(store) else None
            if s is None or 0 in s:
                missing.append(f"output {node.name}[{idx}]")
                break
        if missing:
            out.append(Diagnostic(
                "GV102", "shape inference left "
                f"{', '.join(missing[:6])} unknown"
                + (f" (+{len(missing) - 6} more)"
                   if len(missing) > 6 else ""),
                hint="provide more input shapes or register the missing "
                     "infer_shape functions"))
    return out


def _verify_json_graph(graph, out):
    """GV106/GV108 over a raw symbol-JSON dict."""
    nodes = graph.get("nodes") or []
    heads = graph.get("heads") or []
    for i, jn in enumerate(nodes):
        for ref in jn.get("inputs") or []:
            src = ref[0] if ref else -1
            if not (0 <= src < i):
                out.append(Diagnostic(
                    "GV106", f"node {jn.get('name', i)!r} input refers "
                    f"to node {src}, which is "
                    + ("out of range" if not (0 <= src < len(nodes))
                       else "not topologically earlier"),
                    node=jn.get("name"), op=jn.get("op"),
                    hint="the graph JSON is corrupt; regenerate it"))
    reach = set()
    stack = [h[0] for h in heads if h and 0 <= h[0] < len(nodes)]
    while stack:
        i = stack.pop()
        if i in reach:
            continue
        reach.add(i)
        for ref in nodes[i].get("inputs") or []:
            if ref and 0 <= ref[0] < len(nodes):
                stack.append(ref[0])
    for i, jn in enumerate(nodes):
        if i not in reach:
            out.append(Diagnostic(
                "GV108", f"node {jn.get('name', i)!r} is unreachable "
                "from every head", node=jn.get("name"), op=jn.get("op"),
                hint="dead nodes bloat checkpoints and mask wiring "
                     "mistakes; drop them or re-head the graph"))


def donation_checker(ctx, out):
    """DA2xx: buffer ownership through the donated fused/scan plans.

    The fused/scan programs donate their watched params and optimizer
    states (executor_group.py donate_argnums=(0, 4)); XLA then reuses
    those buffers for the outputs and *deletes* the inputs. Any other
    holder of the same buffer — a second arg name, an optimizer-state
    leaf, a shared group's cell — reads a deleted array on its next
    access. PR 2 poisons grads at runtime; these rules find the alias
    before the first step runs.
    """
    g = ctx.exec_group
    if g is None or getattr(g, "_fused_prog", None) is None:
        _bucket_alias_check(ctx, out)
        return
    exe = g.executor
    watched = list(getattr(g, "_fused_watched", ()) or ())
    wset = set(watched)

    # DA203: a donated param name that is also a data/label input would
    # ride in both the donated dict and the aliased `rest` dict
    for nm in watched:
        if nm in set(g.data_names) | set(g.label_names):
            out.append(Diagnostic(
                "DA203", f"parameter {nm!r} is donated by the fused "
                "step but is also a data/label input of the binding",
                node=nm,
                hint="exclude it from the trained params (fixed_param_"
                     "names) or rename the input"))

    # DA201: identity aliasing. Two views: NDArray cells bound under
    # two names, and one jax buffer behind two cells/state leaves.
    entries = []      # (name, kind, cell, buffer)
    for nm, arr in zip(exe.arg_names, exe.arg_arrays):
        if arr is not None:
            entries.append((nm, "arg", arr, arr.asjax()))
    for nm, arr in zip(exe.arg_names, exe.grad_arrays):
        if arr is not None:
            entries.append((nm, "grad", arr, arr.asjax()))
    for nm, arr in zip(exe.aux_names, exe.aux_arrays):
        if arr is not None:
            entries.append((nm, "aux", arr, arr.asjax()))
    import jax as _jax
    for nm in watched:
        st = getattr(g, "_fused_states", {}).get(nm)
        if st is not None:
            for leaf in _jax.tree.leaves(st):
                entries.append((nm, "state", None, leaf))

    donated = {(nm, kind) for nm, kind, _cell, _buf in entries
               if kind in ("arg", "state") and nm in wset}
    by_cell, by_buf = {}, {}
    for nm, kind, cell, buf in entries:
        if cell is not None:
            by_cell.setdefault(id(cell), []).append((nm, kind))
        if buf is not None:
            by_buf.setdefault(id(buf), []).append((nm, kind))
    flagged = set()
    for holders in list(by_cell.values()) + list(by_buf.values()):
        if len(holders) < 2:
            continue
        donated_holders = [h for h in holders if h in donated]
        if not donated_holders:
            continue
        key = tuple(sorted(set(holders)))
        if key in flagged:
            continue
        flagged.add(key)
        desc = ", ".join(f"{nm} ({kind})" for nm, kind in key)
        out.append(Diagnostic(
            "DA201", "one buffer is bound under multiple entries — "
            f"{desc} — and the fused step donates it; the other "
            "holder(s) would read a deleted array", node=key[0][0],
            hint="copy the array before binding (jnp.array(x, "
                 "copy=True)) or drop the extra binding"))

    # DA202: donation into cells shared with another group (bucketing /
    # shared_module): the sharer's pending programs may still hold the
    # pre-donation buffer
    if wset & set(getattr(g, "_shared_param_names", ()) or ()):
        shared = sorted(wset & set(g._shared_param_names))
        out.append(Diagnostic(
            "DA202", "fused step donates parameter cells shared with "
            f"another executor group: {', '.join(shared[:4])}"
            + (f" (+{len(shared) - 4} more)" if len(shared) > 4 else ""),
            node=shared[0],
            hint="borrow_optimizer/staged updates for shared groups, or "
                 "rebind without shared_module"))

    _bucket_alias_check(ctx, out)


def _bucket_alias_check(ctx, out):
    """DA204: one buffer staged under two keys in one flush window —
    both keys' segments of the flat bucket would scatter back into the
    same destination."""
    sched = ctx.sched
    if sched is None:
        return
    windows = {}
    for rec in getattr(sched, "stage_log", ()):
        windows.setdefault(rec.get("window"), []).append(rec)
    for recs in windows.values():
        by_buf = {}
        for r in recs:
            if r.get("buf") is not None:
                by_buf.setdefault(r["buf"], set()).add(r["key"])
        for keys in by_buf.values():
            if len(keys) > 1:
                out.append(Diagnostic(
                    "DA204", "one gradient buffer was staged under "
                    f"kvstore keys {sorted(keys)} in the same bucket "
                    "window",
                    hint="push distinct arrays per key (the reduced "
                         "segments write back to one destination)"))
                return


def collective_order(ctx, out):
    """CO3xx: every worker must dispatch the same collective sequence.

    A collective is a rendezvous: if worker A dispatches bucket(k3,k4)
    while worker B — whose backward happened to finish k4 first —
    dispatches bucket(k4,k3), the mesh deadlocks. The order must
    therefore be a *total* order derived from data every worker shares
    (key ids, declared priorities), never from grad-ready arrival time.
    """
    # CO301: audit the staged push plan recorded by the scheduler
    sched = ctx.sched
    multi = ctx.assume_multiworker
    kv = ctx.kvstore
    if kv is not None and getattr(kv, "_nproc", 1) > 1:
        multi = True
    if sched is not None and multi:
        windows = {}
        for rec in getattr(sched, "stage_log", ()):
            windows.setdefault(rec.get("window"), []).append(rec)
        for recs in windows.values():
            by_prio = {}
            for r in recs:
                by_prio.setdefault(r.get("prio", 0), set()).add(
                    r.get("push", 0))
            bad = {p: pushes for p, pushes in by_prio.items()
                   if len(pushes) > 1}
            if bad:
                prio = sorted(bad)[0]
                out.append(Diagnostic(
                    "CO301", f"{sum(len(v) for v in bad.values())} push "
                    f"calls staged gradients at equal priority "
                    f"(e.g. {prio}) in one bucket window; bucket "
                    "composition then follows per-worker grad-ready "
                    "order and the collectives diverge across workers",
                    hint="push all keys in ONE call, or give every key "
                         "a distinct priority (Module.update does both)"))
                break

    g = ctx.exec_group
    mod = ctx.module
    # CO302: two reduction plans over the same gradients
    if g is not None and getattr(g, "_zero_plan", None) is not None:
        kv = kv or (getattr(mod, "_kvstore", None) if mod else None)
        if kv is not None and "dist" in getattr(kv, "type", ""):
            plan = g._zero_plan.describe()
            out.append(Diagnostic(
                "CO302", f"ZeRO in-program plan {plan} is armed while "
                f"a {kv.type!r} kvstore also reduces gradients; the "
                "gradients would be summed twice in an undefined order",
                hint="use zero_stage only with the in-program plan "
                     "(kvstore=None/local) or disable zero_stage"))

    # CO303: the fused/scan program's collective sequence is the watched
    # list; it must match declaration order, the one order every worker
    # derives identically from the symbol
    if g is not None and getattr(g, "_fused_prog", None) is not None:
        watched = list(getattr(g, "_fused_watched", ()) or ())
        expect = [nm for nm in g.param_names
                  if g.grad_req.get(nm) == "write"]
        if watched != expect:
            out.append(Diagnostic(
                "CO303", "fused-step collective order "
                f"{watched[:4]}... diverges from parameter declaration "
                f"order {expect[:4]}...",
                hint="do not reorder _fused_watched; both lists must "
                     "derive from symbol.list_arguments()"))


def sharding_checker(ctx, out):
    """SH6xx: the SPMD plan vs what is actually bound.

    Only active on bindings carrying a ``parallel/spmd.SpmdPlan``: the
    plan's PartitionSpecs are the contract every placement and the
    fused program's donation discipline depend on — an array re-bound
    with the wrong sharding silently changes the program XLA partitions
    (wrong collective structure, broken donation aliasing), which no
    runtime check catches before the step count makes it expensive.
    """
    g = ctx.exec_group
    plan = getattr(g, "_spmd_plan", None) if g is not None else None
    if plan is None:
        return
    exe = g.executor
    from jax.sharding import NamedSharding

    def matches(arr, want):
        """Does a bound jax array's sharding realize ``want``?"""
        try:
            sh = arr.sharding
            if hasattr(sh, "is_equivalent_to"):
                return sh.is_equivalent_to(want, arr.ndim)
            return str(sh) == str(want)
        except Exception:
            return True            # unknown sharding kinds: no finding

    # SH601: bound param/aux arrays vs the plan's specs (data/label
    # arrays are re-placed per batch and are not audited here)
    ad = exe.arg_dict
    for nm in g.param_names:
        arr = ad.get(nm)
        if arr is None:
            continue
        want = plan.param_sharding(nm)
        if not matches(arr.asjax(), want):
            out.append(Diagnostic(
                "SH601", f"parameter {nm!r} is bound with sharding "
                f"{arr.asjax().sharding} but the SPMD plan places it as "
                f"{want.spec}", node=nm,
                hint="place params through the plan (set_params / "
                     "exec_group._place); do not _set raw device arrays"))

    # SH602: a ctx_group-tagged param the plan could NOT shard over the
    # model axis — it silently replicates, paying full memory on every
    # device of the axis the tag asked to split over
    for nm, reason in sorted(plan.unsharded_tagged.items()):
        out.append(Diagnostic(
            "SH602", f"parameter {nm!r} is ctx_group-tagged for the "
            f"model axis but stays fully replicated: {reason}", node=nm,
            hint="pad the dimension to a multiple of the axis size, "
                 "shrink the model axis, or drop the ctx_group tag"))

    # SH603: donation over the spmd carry — the fused program donates
    # watched params and state leaves and emits outputs constrained to
    # the plan's specs; an input whose committed sharding differs can't
    # alias its output buffer (XLA copies: double memory, or deletes a
    # still-referenced buffer under a later reshard)
    if getattr(g, "_fused_prog", None) is not None:
        watched = list(getattr(g, "_fused_watched", ()) or ())
        states = getattr(g, "_fused_states", {}) or {}
        import jax as _jax
        for nm in watched:
            arr = ad.get(nm)
            if arr is not None and not matches(arr.asjax(),
                                               plan.param_sharding(nm)):
                out.append(Diagnostic(
                    "SH603", f"donated parameter {nm!r} enters the "
                    "fused step with a sharding that differs from the "
                    "program's output spec "
                    f"{plan.param_spec(nm)}; donation cannot alias",
                    node=nm,
                    hint="re-place the param per the plan before the "
                         "next step (set_params does this)"))
                continue
            want_state = plan.state_sharding(nm)
            for leaf in _jax.tree.leaves(states.get(nm, ())):
                shaped_like_param = getattr(leaf, "shape", None) == \
                    getattr(arr, "shape", None)
                want = want_state if (plan.zero or shaped_like_param) \
                    else plan.replicated
                if not matches(leaf, want):
                    out.append(Diagnostic(
                        "SH603", f"optimizer-state leaf of {nm!r} is "
                        f"sharded {leaf.sharding} but the plan's state "
                        f"spec is {want.spec}; the donated carry "
                        "cannot alias", node=nm,
                        hint="import states through "
                             "import_fused_states/import_staged_state"))
                    break


def retrace_churn(ctx, out):
    """RC4xx: what would mint a new program_cache key per step.

    The process-wide program cache keys on (symbol sha1, shapes/dtypes,
    ...). Anything unstable inside that key — an attr whose repr embeds
    an object id, an array attr whose repr truncates (two DIFFERENT
    graphs hash equal: worse), a NaN that never compares equal in the
    lr/wd value cache — turns the cache into a per-step recompile.
    """
    sym = ctx.symbol
    if sym is not None:
        out.extend(_symbol_memo(sym, "unstable_attrs", None,
                                lambda: _unstable_attr_findings(sym)))

    exe = ctx.executor
    if exe is not None and getattr(exe, "_prog_cache_base", None) is None \
            and getattr(exe, "_mp_plan", None) is None:
        out.append(Diagnostic(
            "RC402", "this binding has no program-cache key; every "
            "rebind (train/eval pair, force_rebind, bucketing) "
            "re-traces and recompiles",
            hint="make the symbol JSON-serializable (see the RC401 "
                 "findings, if any) so its signature hashes"))


def _unstable_attr_findings(sym):
    """RC401 scan over every node's attrs; memoized per symbol."""
    out = []
    flagged = set()
    for n in sym._topo_nodes():
        for k, v in list(n.attrs.items()) + list(n._extra.items()):
            ok, why = attr_cache_stable(v)
            if ok or (n.name, k) in flagged:
                continue
            flagged.add((n.name, k))
            out.append(Diagnostic(
                "RC401", f"attr {k!r} = {type(v).__name__} on node "
                f"{n.name!r} is not cache-key stable ({why})",
                node=n.name, op=n.op,
                hint="use plain str/int/float/bool/tuple attr "
                     "values; pass arrays as graph inputs, not "
                     "attrs"))
    return out


def host_sync(ctx, out):
    """HS5xx: implicit device->host transfers in the fit hot path."""
    env = os.environ
    exe = ctx.executor
    if env.get("MXNET_ENGINE_TYPE") == "NaiveEngine":
        out.append(Diagnostic(
            "HS501", "MXNET_ENGINE_TYPE=NaiveEngine forces every op to "
            "complete on the host before the next dispatches",
            hint="debug mode only; unset it for training runs"))
    if exe is not None and getattr(exe, "_monitor_callback", None) \
            is not None:
        out.append(Diagnostic(
            "HS502", "a monitor callback is installed: every batch "
            "replays eagerly with per-op device->host transfers",
            hint="remove the monitor for production runs"))
    sym = ctx.symbol
    training = False
    if ctx.exec_group is not None:
        training = bool(getattr(ctx.exec_group, "for_training", False))
    elif exe is not None:
        training = any(r != "null"
                       for r in getattr(exe, "grad_req", {}).values())
    if sym is not None and training:
        for node, idx in sym._outputs:
            if node.is_variable:
                out.append(Diagnostic(
                    "HS503", f"training output {node.name!r} is a bare "
                    "input variable; it is re-materialized (and "
                    "typically host-read) every step", node=node.name,
                    hint="drop the passthrough head or wrap it in "
                         "BlockGrad outside the train symbol"))
                break
    if ctx.exec_group is not None \
            and getattr(ctx.exec_group, "_fused_prog", None) is not None \
            and env.get("MXNET_FUSED_KEEP_GRADS", "0") == "1":
        out.append(Diagnostic(
            "HS504", "MXNET_FUSED_KEEP_GRADS=1 emits every gradient as "
            "a fused-program output (~5% step time) and keeps it "
            "host-readable",
            hint="unset it unless something reads grad_dict mid-run"))


def mfu_coverage(ctx, out):
    """MF601: ops with nodes in this graph but no cost metadata.

    The MFU/roofline accounting (telemetry/mfu.py) folds per-op
    ``flops``/``bytes_moved`` estimators over the graph; an op without
    them silently under-counts every step it runs. One info finding per
    distinct op keeps the coverage gap visible (registry-wide audit:
    ``tools/mxlint.py --mfu-audit``).
    """
    sym = ctx.symbol
    if sym is None and ctx.executor is not None:
        sym = ctx.executor._symbol
    if sym is None:
        return

    def compute():
        missing = {}
        for node in sym._topo_nodes():
            if node.is_variable:
                continue
            if not node.opdef().has_cost():
                missing.setdefault(node.op, (node.name, 0))
                nm, n = missing[node.op]
                missing[node.op] = (nm, n + 1)
        return missing

    missing = _symbol_memo(sym, "mfu_coverage", True, compute)
    for op, (first_node, n) in sorted(missing.items()):
        out.append(Diagnostic(
            "MF601", f"op {op!r} ({n} node(s)) carries no flops/bytes "
            "cost metadata; MFU and roofline reports under-count it",
            node=first_node, op=op,
            hint="seed an estimator in ops/cost.py (or "
                 "OpDef.set_cost); list all gaps with "
                 "tools/mxlint.py --mfu-audit"))


def memory_planner(ctx, out):
    """ME8xx: the static memory planner as a lint pass.

    Inert unless armed — planning walks the graph per policy, which the
    warm-bind <2% overhead gate cannot absorb on every bind. Armed by an
    explicit ``AnalysisContext(memplan={...})`` (mxlint --memory-plan)
    or by ``MXNET_LINT_MEMPLAN_BUDGET`` (bytes, or "16G") for bindings
    that know their shapes. Options: ``capacity_bytes`` (default: the
    env budget, else ``telemetry.mfu.device_hbm_bytes()``), ``policy``
    (default: the active remat policy), ``buckets`` (ME802 ladder),
    plus anything ``memplan.plan_symbol`` takes.
    """
    opts = ctx.memplan
    if opts is None:
        raw = os.environ.get("MXNET_LINT_MEMPLAN_BUDGET", "").strip()
        if not raw:
            return
        mult = 1
        if raw[-1:].upper() == "G":
            raw, mult = raw[:-1], 1 << 30
        elif raw[-1:].upper() == "M":
            raw, mult = raw[:-1], 1 << 20
        try:
            opts = {"capacity_bytes": int(float(raw) * mult)}
        except ValueError:
            return
    opts = dict(opts)
    sym = ctx.symbol
    if sym is None and ctx.executor is not None:
        sym = ctx.executor._symbol
    if sym is None:
        return
    shapes = _known_shapes(ctx)
    g = ctx.exec_group
    if g is not None:
        shapes = {d.name: tuple(d.shape) for d in g.data_shapes}
        for l in (g.label_shapes or []):
            shapes[l.name] = tuple(l.shape)
    if not shapes:
        return
    from . import memplan as _memplan
    from ..telemetry.mfu import device_hbm_bytes
    capacity = opts.pop("capacity_bytes", None)
    if capacity is None:
        capacity = device_hbm_bytes()
    buckets = opts.pop("buckets", None)
    if "policy" not in opts:
        from .. import remat as _remat
        opts["policy"] = getattr(g, "_remat_policy", None) \
            if g is not None else None
        opts["policy"] = opts["policy"] or _remat.active()
    if g is not None:
        opts.setdefault("n_data", getattr(g, "_n_data", 1))
        opts.setdefault("for_training", bool(g.for_training))
        opts.setdefault("compute_dtype", g.compute_dtype)
    plan = _memplan.plan_symbol(sym, shapes, **opts)
    _memplan.record_plan(plan)
    out.extend(_memplan.plan_findings(plan, capacity_bytes=capacity,
                                      buckets=buckets))


from .precision import precision_flow  # noqa: E402  (pass body)

#: pass name -> callable(ctx, out_list); order is the report order
PASSES = OrderedDict([
    ("graph_verifier", graph_verifier),
    ("donation_checker", donation_checker),
    ("collective_order", collective_order),
    ("sharding_checker", sharding_checker),
    ("retrace_churn", retrace_churn),
    ("host_sync", host_sync),
    ("mfu_coverage", mfu_coverage),
    ("precision_flow", precision_flow),
    ("memory_planner", memory_planner),
])


# ========================================================== orchestration
def _disabled():
    raw = os.environ.get("MXNET_LINT_DISABLE", "")
    return {tok.strip() for tok in raw.split(",") if tok.strip()}


def run_passes(ctx, passes=None, mirror=True):
    """Run the (enabled) passes over ``ctx`` and return a Report.

    A pass that raises contributes an XX001 info finding instead of
    propagating — analysis must never break a bind.
    """
    disabled = _disabled()
    report = Report()
    if "all" in disabled:
        return report
    names = list(passes or PASSES)
    for name in names:
        if name in disabled:
            continue
        fn = PASSES[name]
        found = []
        try:
            fn(ctx, found)
        except Exception as e:  # noqa: BLE001 — observers must not throw
            log.debug("analysis pass %s failed", name, exc_info=True)
            found = [Diagnostic(
                "XX001", f"analysis pass {name!r} failed: "
                f"{type(e).__name__}: {e}",
                hint="report this; the pass was skipped")]
        for d in found:
            if d.rule not in disabled:
                report.add(d)
    if mirror and len(report):
        _mirror(report)
    return report


def _mirror(report):
    """Findings -> telemetry registry counters + flight-recorder ring."""
    try:
        from .. import telemetry as _telemetry
        for d in report:
            _telemetry.metrics.counter("analysis.lint.findings",
                                       rule=d.rule,
                                       severity=d.severity).inc()
            if _telemetry.enabled():
                # event() lands in the jsonl/chrome exporters AND the
                # flight ring; the direct note keeps the always-on ring
                # populated when the tracer is off
                _telemetry.event("lint.finding", rule=d.rule,
                                 severity=d.severity, node=d.node or "",
                                 message=d.message)
            else:
                _telemetry.flightrec.note("lint.finding", rule=d.rule,
                                          severity=d.severity,
                                          node=d.node or "",
                                          message=d.message)
    except Exception:  # noqa: BLE001 — telemetry must not break analysis
        log.debug("lint telemetry mirroring failed", exc_info=True)


# ---------------------------------------------------------- entry points
def lint_symbol(symbol, shapes=None, **ctx_kwargs):
    """Lint a free-standing Symbol; ``shapes`` seeds inference."""
    return run_passes(AnalysisContext(symbol=symbol, known_shapes=shapes,
                                      **ctx_kwargs))


def lint_executor(executor):
    """Lint one bound Executor (graph + binding-level rules)."""
    return run_passes(AnalysisContext(symbol=executor._symbol,
                                      executor=executor))


def lint_module(module):
    """Lint a bound Module: graph, binding, fused/ZeRO/scan plans, and
    the kvstore comm plan when one is attached."""
    g = module._exec_group
    kv = getattr(module, "_kvstore", None)
    return run_passes(AnalysisContext(
        symbol=module._symbol,
        executor=g.executor if g is not None else None,
        exec_group=g, module=module, kvstore=kv,
        sched=getattr(kv, "_sched", None)))


def lint_json(text_or_dict, shapes=None):
    """Lint a symbol JSON (file contents or parsed dict): structural
    rules over the raw graph plus the full pass set over the loaded
    Symbol when it loads."""
    graph = text_or_dict
    if isinstance(graph, (str, bytes)):
        graph = _json.loads(graph)
    symbol = None
    load_error = None
    try:
        from .. import symbol as _symbol_mod
        symbol = _symbol_mod.load_json(_json.dumps(graph))
    except Exception as e:  # noqa: BLE001 — corrupt JSON is the finding
        load_error = e
    report = run_passes(AnalysisContext(symbol=symbol, known_shapes=shapes,
                                        json_graph=graph))
    if load_error is not None and "GV106" not in report.rules:
        report.add(Diagnostic(
            "GV106", f"symbol JSON does not load: "
            f"{type(load_error).__name__}: {load_error}",
            hint="regenerate the JSON with Symbol.save()"))
    return report


# ------------------------------------------------------- bind-time hooks
def resolve_mode(explicit=None):
    """'warn' | 'raise' | None from an explicit arg or the env knob."""
    mode = explicit
    if mode is None:
        mode = os.environ.get("MXNET_GRAPH_VALIDATE", "")
    if isinstance(mode, str):
        mode = mode.strip().lower()
    if mode in ("warn", "raise"):
        return mode
    if mode in (None, "", "0", "off", "false", "none"):
        return None
    log.warning("unknown MXNET_GRAPH_VALIDATE mode %r; using 'warn'", mode)
    return "warn"


def _apply_mode(report, mode, where):
    if not len(report):
        return report
    logged = report.warnings
    if mode == "warn":
        logged = logged + report.errors
    for d in logged:
        log.warning("[%s] %s", where, d.format())
    if mode == "raise" and report.errors:
        raise MXNetError(
            f"graph validation failed at {where} with "
            f"{len(report.errors)} error(s):\n"
            + "\n".join(d.format() for d in report.errors))
    return report


def validate_executor(executor, mode):
    """bind-time hook: lint the freshly bound executor per ``mode``."""
    mode = resolve_mode(mode)
    if mode is None:
        return None
    return _apply_mode(lint_executor(executor), mode, "bind")


def validate_module(module, mode=None):
    """init_optimizer-time hook: lint the armed module per ``mode``."""
    mode = resolve_mode(mode)
    if mode is None:
        return None
    return _apply_mode(lint_module(module), mode, "init_optimizer")
