"""Program-cache-key completeness verifier (CK3xx).

The program cache's correctness contract is that its key enumerates
EVERY knob that changes what a trace computes — and that contract has
already broken twice: PR 11's remat token leaking across autotune
selections, and PR 17 retrofitting ``("health", armed)`` into the
fused-program key after an armed run silently reused an unarmed trace.
Both were found by accident at runtime. This pass makes the contract a
declared, statically checked registry instead:

* :data:`KNOBS` declares every shape-affecting knob — its read markers
  (env literal, dotted accessor, bare identifier), how the key must
  carry it (a tagged ``("token", value)`` element, a bare ``element``
  identifier, or coverage through another knob such as the symbol
  signature), whether it is ``required`` to appear somewhere in the
  corpus, and whether the kernel-tier ``autotune`` key must carry it
  too;
* the pass parses the key-composition corpus (``executor.py``,
  ``module/executor_group.py``, ``program_cache.py``,
  ``kernel_tier.py``), finds every *construction scope* (a function
  that calls ``program_cache_key``, assigns ``_prog_cache_base`` or
  ``_fused_cache_key``, or extends a key with ``+ ("scan", K)``), and
  resolves what each scope's key actually contains — including one
  level of local dataflow (``extras = (...)`` feeding
  ``program_cache_key(kind, *extras)``) and key inheritance (the scan
  key extends the fused key, which calls ``program_cache_key``, which
  appends ``_prog_cache_base``).

Rules:

* **CK301** — a registered knob is read inside a construction scope but
  its key token never lands in that scope's (inherited) key — the
  PR-11/PR-17 bug shape, caught at lint time; also fired corpus-wide
  when a ``required`` knob appears in no key at all (the knob read at
  trace time in a different module entirely, e.g. the kernel tier);
* **CK302** — a tagged key element maps to no registered knob (dead or
  undeclared key freight: the registry and the key drifted);
* **CK303** — autotune-key/program-key divergence: a knob the registry
  marks ``autotune`` is missing from ``kernel_tier._key`` (a winner
  measured under one setting would leak to another), or the autotune
  key tags a knob the registry says does not affect it.

The static half is backed by a *runtime* cross-check
(``test_utils.check_cache_key_knob``): flip each registered knob, run
the same workload, and assert ``program_cache.compile_count()`` moves
while an unflipped replay stays at zero compiles.

Adding a knob: docs/analysis.md, "Cache-key registry" how-to.

CLI: ``python tools/mxlint.py --cachekey-audit`` (and inside
``--check``). Test/CLI-time only — no bind-time cost.
"""
from __future__ import annotations

import ast
import os

__all__ = ["KNOBS", "audit", "CORPUS"]

#: key-composition corpus, relative to mxnet_tpu/
CORPUS = ("executor.py", os.path.join("module", "executor_group.py"),
          "program_cache.py", "kernel_tier.py")

#: the declared registry of shape-affecting knobs. ``token``: tag of a
#: ``("token", value)`` key element; ``element``: identifier(s) whose
#: presence in the key expression carries the knob; ``covered_by``:
#: knob rides another's element (graph attrs ride the symbol
#: signature); ``reads``: markers whose presence in a construction
#: scope means the knob is read there ("MXNET_*" literals, dotted
#: accessors matched by suffix, bare identifiers); ``required``: must
#: appear in at least one key corpus-wide; ``autotune``: must also tag
#: kernel_tier's autotune key.
KNOBS = (
    dict(name="remat_policy", token="remat",
         reads=("MXNET_REMAT_POLICY", "remat.active", "remat_policy"),
         required=True, autotune=True,
         doc="gradient rematerialization policy (none|dots|all)"),
    dict(name="kernel_tier", token="ktier",
         reads=("MXNET_KERNEL_TIER", "ktier.mode", "kernel_tier.mode"),
         required=True,
         doc="kernel implementation tier (auto|xla|pallas), read at "
             "trace time by kernel_tier.resolve()"),
    dict(name="health_armed", token="health",
         reads=("MXNET_TRAIN_HEALTH", "health.armed", "health_armed"),
         required=True,
         doc="training-health plane arming (extra in-program stat ys)"),
    dict(name="comm_plan", token="comm",
         reads=("zero_armed",), required=True,
         doc="collective plan: replicated all-reduce vs ZeRO "
             "reduce-scatter"),
    dict(name="scan_length", token="scan", reads=(), required=True,
         doc="steps_per_dispatch K of the scan-fused train step"),
    dict(name="keep_grads", element=("keep_grads",),
         reads=("MXNET_FUSED_KEEP_GRADS",), required=True,
         doc="gradients materialized as fused-program outputs"),
    dict(name="optimizer_plan", element=("fused_plan_token",),
         reads=(), required=True,
         doc="optimizer update rule + hyper-structure token"),
    dict(name="watched_params", element=("watched", "_watched"),
         reads=(), required=True,
         doc="the watched (grad-taking) parameter set"),
    dict(name="metric_pairs", element=("metric_pairs",),
         reads=(), required=True,
         doc="(output, label) pairings of the in-program metrics"),
    dict(name="compute_dtype", element=("compute_dtype",),
         reads=(), required=True,
         doc="compute dtype tier (f32/bf16/quantized serving tiers)"),
    dict(name="mesh_axes", element=("_mesh_token",),
         reads=(), required=True,
         doc="SpmdPlan mesh axes/shape token (data/model partitioning)"),
    dict(name="layout_opt", element=("layout_opt_enabled",),
         reads=(), required=True,
         doc="layout-optimization pass arming"),
    dict(name="device_type", element=("device_type",),
         reads=(), required=True,
         doc="bound device type (cpu/gpu/tpu trace targets differ)"),
    dict(name="remat_segments", element=("_remat_segments",),
         reads=(), required=True,
         doc="explicit remat segment boundaries of the binding"),
    dict(name="symbol_signature", element=("symbol_signature",),
         reads=(), required=True,
         doc="graph-structure hash: op graph + every op attr"),
    # graph-attribute knobs: distinct symbols by construction, so they
    # ride the symbol signature (and the shape tuple) — registered so
    # the runtime flip check covers them and the registry is the one
    # complete list
    dict(name="decode_per_slot", covered_by="symbol_signature",
         doc="per-slot decode cache layout (get_decode_symbol)"),
    dict(name="decode_step_len", covered_by="symbol_signature",
         doc="decode window length S (chunked prefill / verify)"),
    dict(name="spec_k", covered_by="symbol_signature",
         doc="speculative proposal depth K (the verify window graph)"),
    dict(name="cache_dtype", covered_by="symbol_signature",
         doc="KV-cache storage dtype of the decode graph"),
)


def _knob(d):
    """Normalized view of one registry row."""
    return {"name": d["name"], "token": d.get("token"),
            "element": tuple(d.get("element") or ()),
            "reads": tuple(d.get("reads") or ()),
            "required": bool(d.get("required")),
            "autotune": bool(d.get("autotune")),
            "covered_by": d.get("covered_by"),
            "doc": d.get("doc", "")}


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_name(node, name):
    return (isinstance(node, ast.Name) and node.id == name) or \
        (isinstance(node, ast.Attribute) and node.attr == name)


def _references(tree, name):
    return any(_is_name(n, name) for n in ast.walk(tree))


class _Scope:
    """One construction scope's resolved key facts."""

    def __init__(self, fname, func):
        self.file = fname
        self.func = func
        self.name = func.name
        self.key_exprs = []
        self.tags = set()
        self.idents = set()
        self.mentions = set()       # read-marker surface of the scope
        self.dotted = set()
        self.calls_pck = False
        self.refs_fused = False
        self.refs_base = False
        self._collect()

    def _collect(self):
        func = self.func
        local_assigns = {}
        key_arg_names = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_assigns[t.id] = node.value
                    if _is_name(t, "_prog_cache_base"):
                        self.key_exprs.append(node.value)
                    if _is_name(t, "_fused_cache_key") and \
                            not isinstance(node.value, ast.Call):
                        self.key_exprs.append(node.value)
            if isinstance(node, ast.Call):
                callee = node.func
                if isinstance(callee, ast.Attribute) and \
                        callee.attr == "program_cache_key":
                    self.calls_pck = True
                    for arg in node.args:
                        inner = arg.value if isinstance(
                            arg, ast.Starred) else arg
                        self.key_exprs.append(inner)
                        if isinstance(inner, ast.Name):
                            key_arg_names.add(inner.id)
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, ast.Add):
                if _references(node.left, "_fused_cache_key") or \
                        _references(node.left, "_prog_cache_base"):
                    self.key_exprs.append(node.right)
            # scope read-marker surface
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                self.mentions.add(node.value)
            elif isinstance(node, ast.Name):
                self.mentions.add(node.id)
            elif isinstance(node, ast.Attribute):
                self.mentions.add(node.attr)
                d = _dotted(node)
                if d:
                    self.dotted.add(d)
        # one level of dataflow: a bare name passed (or starred) into
        # the key call resolves to its local assignment
        for nm in key_arg_names:
            if nm in local_assigns:
                self.key_exprs.append(local_assigns[nm])
        self.refs_fused = _references(func, "_fused_cache_key")
        self.refs_base = _references(func, "_prog_cache_base")
        for expr in self.key_exprs:
            for node in ast.walk(expr):
                if isinstance(node, ast.Tuple) and node.elts and \
                        isinstance(node.elts[0], ast.Constant) and \
                        isinstance(node.elts[0].value, str):
                    self.tags.add(node.elts[0].value)
                if isinstance(node, ast.Name):
                    self.idents.add(node.id)
                elif isinstance(node, ast.Attribute):
                    self.idents.add(node.attr)

    def reads(self, knob):
        """Does this scope read the knob (any marker present)?"""
        for marker in knob["reads"]:
            if marker.startswith("MXNET_"):
                if marker in self.mentions:
                    return True
            elif "." in marker:
                if any(d == marker or d.endswith("." + marker) or
                       d.endswith(marker) for d in self.dotted):
                    return True
            else:
                if marker in self.mentions or \
                        "_" + marker in self.mentions:
                    return True
        return False


def _is_construction_scope(func):
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "program_cache_key":
            return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if _is_name(t, "_prog_cache_base") or \
                        _is_name(t, "_fused_cache_key"):
                    return True
        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.Add) and \
                (_references(node.left, "_fused_cache_key") or
                 _references(node.left, "_prog_cache_base")):
            return True
    return False


def _autotune_tags(tree):
    """Tag set of the ``_key`` autotune-key function, or None."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_key":
            tags = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Tuple) and sub.elts and \
                        isinstance(sub.elts[0], ast.Constant) and \
                        isinstance(sub.elts[0].value, str):
                    tags.add(sub.elts[0].value)
            return tags
    return None


def _covered(knob, tags, idents, by_name):
    if knob["token"] is not None and knob["token"] in tags:
        return True
    if knob["element"] and any(e in idents for e in knob["element"]):
        return True
    cov = knob["covered_by"]
    if cov is not None and cov in by_name:
        return _covered(by_name[cov], tags, idents, by_name)
    return False


def audit(repo_root=None, sources=None, knobs=None):
    """Run the cache-key completeness audit; returns a result dict.

    ``sources`` (name -> source text) replaces the repo corpus for the
    seeded fixtures; ``knobs`` overrides the registry the same way.
    ``findings`` carries the CK3xx dicts; ``coverage`` maps each knob
    to where its key element was found (the registry's receipts).
    """
    rows = [_knob(d) for d in (knobs if knobs is not None else KNOBS)]
    by_name = {k["name"]: k for k in rows}
    texts = {}
    if sources is not None:
        texts = dict(sources)
    else:
        for rel in CORPUS:
            path = os.path.join(repo_root, "mxnet_tpu", rel)
            try:
                with open(path) as f:
                    texts[rel.replace(os.sep, "/")] = f.read()
            except OSError:
                continue

    findings = []
    scopes = []
    autotune_tags = None
    autotune_file = None
    for fname in sorted(texts):
        try:
            tree = ast.parse(texts[fname], filename=fname)
        except SyntaxError as e:
            findings.append({"target": fname, "rule": "XX001",
                             "severity": "info", "node": None,
                             "message": f"cachekey could not parse: {e}",
                             "hint": None})
            continue
        tags = _autotune_tags(tree)
        if tags is not None:
            autotune_tags, autotune_file = tags, fname
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    _is_construction_scope(node):
                scopes.append(_Scope(fname, node))

    # key inheritance: base -> program_cache_key -> fused -> scan
    base_tags, base_ids = set(), set()
    for s in scopes:
        if any(_is_name(t, "_prog_cache_base")
               for n in ast.walk(s.func) if isinstance(n, ast.Assign)
               for t in n.targets):
            base_tags |= s.tags
            base_ids |= s.idents
    pck_tags, pck_ids = set(base_tags), set(base_ids)
    for s in scopes:
        if s.name == "program_cache_key":
            pck_tags |= s.tags
            pck_ids |= s.idents
    fused_tags, fused_ids = set(pck_tags), set(pck_ids)
    for s in scopes:
        if any(_is_name(t, "_fused_cache_key")
               for n in ast.walk(s.func) if isinstance(n, ast.Assign)
               for t in n.targets):
            fused_tags |= s.tags | (pck_tags if s.calls_pck else set())
            fused_ids |= s.idents

    def effective(s):
        tags, idents = set(s.tags), set(s.idents)
        if s.calls_pck:
            tags |= pck_tags
            idents |= pck_ids
        if s.refs_base:
            tags |= base_tags
            idents |= base_ids
        if s.refs_fused:
            tags |= fused_tags
            idents |= fused_ids
        return tags, idents

    # CK301 (scope form): knob read inside a construction scope whose
    # key never carries it
    for s in scopes:
        tags, idents = effective(s)
        for knob in rows:
            if not knob["reads"] or not s.reads(knob):
                continue
            if not _covered(knob, tags, idents, by_name):
                findings.append({
                    "target": s.file, "rule": "CK301",
                    "severity": "error", "node": knob["name"],
                    "line": s.func.lineno,
                    "message": f"{s.file}:{s.name}() reads "
                               f"{knob['name']} (markers "
                               f"{list(knob['reads'])}) while composing "
                               "a program-cache key that never carries "
                               "it — a flipped knob would silently "
                               "reuse a stale program",
                    "hint": f"add a (\"{knob['token']}\", <value>) "
                            "element (or the registered element "
                            "identifier) to the key, or fix the "
                            "registry row" if knob["token"] else
                            "add the registered element to the key or "
                            "fix the registry row"})

    # CK301 (corpus form): a required knob appears in no key anywhere
    all_tags, all_ids = set(), set()
    for s in scopes:
        t, i = effective(s)
        all_tags |= t
        all_ids |= i
    coverage = {}
    for knob in rows:
        cov = _covered(knob, all_tags, all_ids, by_name)
        coverage[knob["name"]] = cov
        if knob["required"] and not cov:
            findings.append({
                "target": "cachekey-registry", "rule": "CK301",
                "severity": "error", "node": knob["name"], "line": 0,
                "message": f"registered knob {knob['name']} "
                           f"({knob['doc'] or 'shape-affecting'}) "
                           "appears in no program-cache key across "
                           "the corpus — programs traced under "
                           "different settings would share a cache "
                           "entry",
                "hint": "thread the knob into program_cache_key (or "
                        "the fused key) where the program is built"})

    # CK302: tagged key elements no registry row declares
    tokens = {k["token"] for k in rows if k["token"]}
    for s in scopes:
        for tag in sorted(s.tags - tokens):
            findings.append({
                "target": s.file, "rule": "CK302",
                "severity": "error", "node": tag,
                "line": s.func.lineno,
                "message": f"{s.file}:{s.name}() tags a key element "
                           f"(\"{tag}\", ...) that no registry knob "
                           "declares — dead key freight or an "
                           "undeclared knob",
                "hint": "register the knob in analysis/cachekey.KNOBS "
                        "(docs/analysis.md how-to) or drop the "
                        "element"})

    # CK303: autotune-key / program-key divergence
    if autotune_tags is not None:
        for knob in rows:
            if knob["autotune"] and knob["token"] and \
                    knob["token"] not in autotune_tags:
                findings.append({
                    "target": autotune_file, "rule": "CK303",
                    "severity": "error", "node": knob["name"],
                    "line": 0,
                    "message": f"knob {knob['name']} is registered as "
                               "autotune-affecting but kernel_tier's "
                               "_key() never carries its "
                               f"(\"{knob['token']}\", ...) element — "
                               "a winner measured under one setting "
                               "leaks to another",
                    "hint": "add the element to kernel_tier._key (the "
                            "PR-11 remat bug shape)"})
        for tag in sorted(autotune_tags & tokens):
            owner = next(k for k in rows if k["token"] == tag)
            if not owner["autotune"]:
                findings.append({
                    "target": autotune_file, "rule": "CK303",
                    "severity": "error", "node": owner["name"],
                    "line": 0,
                    "message": f"kernel_tier's _key() carries "
                               f"(\"{tag}\", ...) but the registry "
                               f"says {owner['name']} does not affect "
                               "autotune — registry/key divergence",
                    "hint": "mark the registry row autotune=True or "
                            "drop the element from _key"})

    return {"findings": findings, "coverage": coverage,
            "scopes": [f"{s.file}:{s.name}" for s in scopes],
            "ok": not findings}
