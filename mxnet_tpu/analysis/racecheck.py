"""Host-concurrency lint (RC2xx): cross-thread instance state vs locks.

PRs 13-19 grew a threaded host plane — the serve dispatch thread, the
decode scheduler thread, the checkpoint writer, the opsd HTTP handlers
— whose shared mutable state the graph-level passes cannot see. This
pass builds a *class-scoped* model of that plane, AST-only (nothing is
imported or executed):

* **lock discovery** — ``self.X = threading.Lock()/RLock()`` declares a
  lock attribute; ``threading.Condition(self.X)`` aliases the condition
  to the lock it wraps (the decode scheduler's ``_cond`` IS ``_lock``),
  a bare ``Condition()`` is its own lock. ``queue.Queue``/
  ``threading.Event``-valued attributes are safe channels — their
  method calls synchronize internally and never count as shared-state
  accesses.
* **thread entries** — a method passed as ``threading.Thread(target=
  self.M)`` anywhere in the class runs on the spawned thread; classes
  deriving from ``BaseHTTPRequestHandler`` run their ``do_*`` methods
  on server threads. A class that spawns nothing has no cross-thread
  surface and is skipped.
* **sides** — the *thread side* is the call-graph closure of the
  entries over ``self.m()`` edges; the *caller side* is the closure of
  the public methods (plus dunders). A method reachable from both (the
  decode scheduler's ``_iterate`` runs under ``pump()`` and under the
  dispatch thread) counts on both sides.
* **guards** — the lock set lexically held at each access
  (``with self.L:`` nesting), plus propagation: a private method whose
  every intra-class call site holds lock L inherits L (the
  "caller holds the lock" docstring convention, verified instead of
  trusted).
* **writes** — attribute stores/augmented stores, subscript stores,
  and mutating method calls (``append``/``add``/``update``/...) on
  attributes the class initializes to a list/dict/set display (so
  ``self._registry.add(...)`` on an internally-locked object is not
  miscounted as an unguarded container mutation). ``__init__`` accesses
  are exempt: they happen-before ``Thread.start()``.

Rules (all error severity — the CI gate demands zero unannotated):

* **RC201** — an attribute written on one side and touched on the
  other has at least one access holding no lock at all;
* **RC202** — every access is guarded, but no single lock covers all
  of them (the same attr under two different locks);
* **RC203** — two functions each nest the same two locks in opposite
  orders (lock-order inversion: the classic ABBA deadlock shape).

Suppression records intent: ``# mxlint: guarded-by(<lockname>)`` on any
access line of the attribute suppresses RC201/RC202 for that (class,
attr) and lands in the audit's ``annotated`` list — the reviewer sees
the claim, the lint stops repeating it.

CLI: ``python tools/mxlint.py --race-audit`` (and inside ``--check``);
the scanned surface is ``serve/``, ``checkpoint/``, ``telemetry/`` and
``faults/``. The audit is test/CLI-time only — nothing here runs at
bind time, so the <2% lint-overhead gate is untouched by construction
(and re-measured anyway; benchmarks/lint_overhead.py).
"""
from __future__ import annotations

import ast
import os
import re

__all__ = ["audit", "scan_source", "SCAN_DIRS"]

#: directories under mxnet_tpu/ the repo audit walks (the threaded
#: host plane; the dispatch-path modules have no thread spawns)
SCAN_DIRS = ("serve", "checkpoint", "telemetry", "faults")

_ANNOT_RE = re.compile(
    r"#\s*mxlint:\s*guarded-by\(\s*([A-Za-z_][A-Za-z0-9_.-]*)\s*\)")

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTOR = "Condition"
_SAFE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
               "Event", "Semaphore", "BoundedSemaphore", "Barrier"}
_HTTP_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
               "CGIHTTPRequestHandler"}
#: method names that mutate builtin containers (only applied to attrs
#: the class initializes to a list/dict/set display)
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "add", "discard", "update", "setdefault", "popitem",
             "sort", "reverse"}
_EXEMPT_METHODS = {"__init__", "__del__"}


def _ctor_name(call):
    """Trailing name of a Call's callee (``threading.RLock`` -> RLock)."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _self_attr(node):
    """'X' for a ``self.X`` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _contains_container_display(expr):
    for sub in ast.walk(expr):
        if isinstance(sub, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                            ast.DictComp, ast.SetComp)):
            return True
    return False


class _MethodFacts:
    __slots__ = ("accesses", "calls", "pairs")

    def __init__(self):
        self.accesses = []   # (attr, kind 'r'|'w', lineno, frozenset)
        self.calls = []      # (method name, frozenset held, lineno)
        self.pairs = []      # (outer lock, inner lock, lineno)


class _MethodVisitor(ast.NodeVisitor):
    """One method walk: accesses/calls with the lexically held locks."""

    def __init__(self, model, func):
        self.model = model
        self.facts = _MethodFacts()
        self.held = []       # stack of canonical lock names
        for stmt in func.body:
            self.visit(stmt)

    # -- lock scopes ---------------------------------------------------
    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            attr = _self_attr(item.context_expr)
            lock = self.model.canonical_lock(attr)
            if lock is not None:
                for outer in self.held:
                    if outer != lock:
                        self.facts.pairs.append(
                            (outer, lock, node.lineno))
                self.held.append(lock)
                pushed += 1
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - pushed:len(self.held)]

    # -- nested defs run in unknown contexts: analyze with no locks ---
    def visit_FunctionDef(self, node):
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- accesses ------------------------------------------------------
    def _record(self, attr, kind, lineno):
        if attr is None or self.model.is_synchronizer(attr):
            return
        self.facts.accesses.append(
            (attr, kind, lineno, frozenset(self.held)))

    def visit_Attribute(self, node):
        attr = _self_attr(node)
        if attr is not None:
            kind = "w" if isinstance(node.ctx,
                                     (ast.Store, ast.Del)) else "r"
            self._record(attr, kind, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        attr = _self_attr(node.target)
        if attr is not None:
            self._record(attr, "w", node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self._record(attr, "w", node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            owner = _self_attr(fn.value)
            if owner is not None:
                if fn.attr in _MUTATORS and \
                        owner in self.model.containers:
                    self._record(owner, "w", node.lineno)
            target = _self_attr(fn)
            if target is not None and target in self.model.methods:
                self.facts.calls.append(
                    (target, frozenset(self.held), node.lineno))
        self.generic_visit(node)


class _ClassModel:
    """The per-class concurrency model the rules evaluate over."""

    def __init__(self, node, rel_path, annotations):
        self.node = node
        self.name = node.name
        self.path = rel_path
        self.methods = {}        # name -> FunctionDef
        self.locks = {}          # attr -> canonical lock attr
        self.safe = set()        # queue/event channel attrs
        self.containers = set()  # attrs initialized to a display
        self.entries = set()
        self.facts = {}          # method -> _MethodFacts
        self.annotations = annotations   # line -> lock name claim

        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        self._discover_attrs()
        self._discover_entries()

    # -- discovery -----------------------------------------------------
    def _discover_attrs(self):
        for func in self.methods.values():
            for stmt in ast.walk(func):
                if not isinstance(stmt, ast.Assign):
                    continue
                for target in stmt.targets:
                    attr = _self_attr(target)
                    if attr is None:
                        continue
                    value = stmt.value
                    if isinstance(value, ast.Call):
                        ctor = _ctor_name(value)
                        if ctor in _LOCK_CTORS:
                            self.locks.setdefault(attr, attr)
                        elif ctor == _COND_CTOR:
                            wrapped = _self_attr(value.args[0]) \
                                if value.args else None
                            self.locks[attr] = wrapped if wrapped \
                                else attr
                        elif ctor in _SAFE_CTORS:
                            self.safe.add(attr)
                    if _contains_container_display(value):
                        self.containers.add(attr)
        # resolve one level of condition->lock aliasing
        for attr, canon in list(self.locks.items()):
            self.locks[attr] = self.locks.get(canon, canon)

    def _discover_entries(self):
        bases = {b.attr if isinstance(b, ast.Attribute) else
                 getattr(b, "id", None) for b in self.node.bases}
        if bases & _HTTP_BASES:
            self.entries.update(m for m in self.methods
                                if m.startswith("do_"))
        for func in self.methods.values():
            for call in ast.walk(func):
                if not isinstance(call, ast.Call):
                    continue
                if _ctor_name(call) != "Thread":
                    continue
                for kw in call.keywords:
                    if kw.arg != "target":
                        continue
                    target = _self_attr(kw.value)
                    if target is not None and target in self.methods:
                        self.entries.add(target)

    def canonical_lock(self, attr):
        if attr is None:
            return None
        return self.locks.get(attr)

    def is_synchronizer(self, attr):
        return attr in self.locks or attr in self.safe

    # -- analysis ------------------------------------------------------
    def analyze(self):
        if not self.entries:
            return [], []
        for name, func in self.methods.items():
            self.facts[name] = _MethodVisitor(self, func).facts
        inherited = self._propagate_guards()
        thread_side = self._closure(self.entries)
        caller_roots = {m for m in self.methods
                        if m not in self.entries and
                        (not m.startswith("_") or m.startswith("__"))}
        caller_side = self._closure(caller_roots)
        findings = self._attr_findings(thread_side, caller_side,
                                       inherited)
        findings += self._order_findings(inherited)
        annotated = self._annotated_attrs()
        keep = []
        for f in findings:
            if f["rule"] in ("RC201", "RC202") and \
                    f["node"].split(".", 1)[-1] in annotated:
                continue
            keep.append(f)
        notes = [{"file": self.path, "class": self.name, "attr": attr,
                  "lock": lock, "line": line}
                 for attr, (lock, line) in sorted(annotated.items())]
        return keep, notes

    def _closure(self, roots):
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for callee, _held, _ln in self.facts.get(
                    m, _MethodFacts()).calls:
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def _propagate_guards(self):
        """Locks every intra-class call site of a private method holds;
        fixpoint over the call graph (public methods and entries are
        externally callable with nothing held)."""
        inherited = {m: frozenset() for m in self.methods}
        callers = {}    # method -> [(caller, held at the call)]
        for name, facts in self.facts.items():
            for callee, held, _ln in facts.calls:
                callers.setdefault(callee, []).append((name, held))
        for _ in range(len(self.methods) + 1):
            changed = False
            for m in self.methods:
                if not m.startswith("_") or m.startswith("__") or \
                        m in self.entries or m not in callers:
                    continue
                guard = None
                for caller, held in callers[m]:
                    site = held | inherited[caller]
                    guard = site if guard is None else guard & site
                guard = guard or frozenset()
                if guard != inherited[m]:
                    inherited[m] = guard
                    changed = True
            if not changed:
                break
        return inherited

    def _attr_findings(self, thread_side, caller_side, inherited):
        per_attr = {}   # attr -> {"t": [...], "c": [...]}
        for name, facts in self.facts.items():
            if name in _EXEMPT_METHODS:
                continue
            sides = ("t" if name in thread_side else "") + \
                    ("c" if name in caller_side else "")
            if not sides:
                continue
            for attr, kind, lineno, held in facts.accesses:
                eff = held | inherited[name]
                rec = (kind, name, lineno, eff)
                slot = per_attr.setdefault(attr, {"t": [], "c": []})
                for side in sides:
                    slot[side].append(rec)
        findings = []
        for attr in sorted(per_attr):
            t_acc, c_acc = per_attr[attr]["t"], per_attr[attr]["c"]
            if not t_acc or not c_acc:
                continue
            if not any(kind == "w" for kind, *_ in t_acc + c_acc):
                continue
            all_acc = {(m, ln): (kind, guards)
                       for kind, m, ln, guards in t_acc + c_acc}
            unguarded = [(m, ln) for (m, ln), (k, g) in
                         sorted(all_acc.items()) if not g]
            if unguarded:
                m, ln = unguarded[0]
                findings.append(self._finding(
                    "RC201", attr, ln,
                    f"{self.name}.{attr} crosses the "
                    f"{'/'.join(sorted(self.entries))} thread boundary "
                    f"but {m}() touches it with no lock held "
                    f"(line {ln})",
                    "guard the access with the class lock, or annotate "
                    "the line with  # mxlint: guarded-by(<lock>)  and a "
                    "comment justifying benignity"))
                continue
            common = None
            for _k, g in all_acc.values():
                common = g if common is None else common & g
            if not common:
                locks = sorted({l for _k, g in all_acc.values()
                                for l in g})
                findings.append(self._finding(
                    "RC202", attr,
                    min(ln for _m, ln in all_acc),
                    f"{self.name}.{attr} is guarded inconsistently: "
                    f"accesses hold {locks} but no single lock covers "
                    "every path",
                    "pick one lock for the attribute (or annotate with "
                    "# mxlint: guarded-by(<lock>))"))
        return findings

    def _order_findings(self, inherited):
        seen = {}    # (A, B) ordered -> (method, line)
        for name, facts in self.facts.items():
            for outer, inner, ln in facts.pairs:
                seen.setdefault((outer, inner), (name, ln))
            # a method entered with a propagated (call-site) lock that
            # then takes another forms a cross-function ordering edge
            base = inherited[name]
            if not base:
                continue
            for item in ast.walk(self.methods[name]):
                if not isinstance(item, ast.With):
                    continue
                for witem in item.items:
                    lock = self.canonical_lock(
                        _self_attr(witem.context_expr))
                    if lock is None:
                        continue
                    for outer in base:
                        if outer != lock:
                            seen.setdefault((outer, lock),
                                            (name, item.lineno))
        findings = []
        for (a, b), (f1, ln1) in sorted(seen.items()):
            if (b, a) not in seen or a >= b:
                continue
            f2, ln2 = seen[(b, a)]
            findings.append(self._finding(
                "RC203", f"{a}<>{b}", ln1,
                f"{self.name} acquires {a} then {b} in {f1}() "
                f"(line {ln1}) but {b} then {a} in {f2}() (line {ln2}) "
                "— lock-order inversion can deadlock",
                "pick one acquisition order and restructure the "
                "second site"))
        return findings

    def _annotated_attrs(self):
        """attr -> (claimed lock, line) for guarded-by annotations on
        access lines of the attr (``__init__`` lines count — the
        declaration site is the natural place for the claim)."""
        out = {}
        if not self.annotations:
            return out
        for name, func in self.methods.items():
            for node in ast.walk(func):
                attr = _self_attr(node)
                if attr is None:
                    continue
                claim = self.annotations.get(node.lineno)
                if claim is not None and attr not in self.locks:
                    out.setdefault(attr, (claim, node.lineno))
        return out

    def _finding(self, rule, attr, line, message, hint):
        return {"target": self.path, "rule": rule, "severity": "error",
                "node": f"{self.name}.{attr}", "line": line,
                "message": message, "hint": hint}


def scan_source(source, rel_path="<fixture>"):
    """(findings, annotated) for one module's source text."""
    try:
        tree = ast.parse(source, filename=rel_path)
    except SyntaxError as e:
        return ([{"target": rel_path, "rule": "XX001",
                  "severity": "info", "node": None, "line": 0,
                  "message": f"racecheck could not parse: {e}",
                  "hint": None}], [])
    annotations = {}
    for lineno, line in enumerate(source.splitlines(), 1):
        m = _ANNOT_RE.search(line)
        if m:
            annotations[lineno] = m.group(1)
    findings, annotated = [], []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            f, a = _ClassModel(node, rel_path, annotations).analyze()
            findings += f
            annotated += a
    return findings, annotated


def audit(repo_root, subdirs=SCAN_DIRS, sources=None):
    """Run the race audit; returns a result dict.

    ``sources`` (name -> source text) replaces the repo walk — the
    seeded-fixture path the tests drive. ``findings`` is the list of
    unsuppressed RC2xx dicts; ``annotated`` records every guarded-by
    claim so suppression is visible, not silent.
    """
    findings, annotated, scanned = [], [], 0
    if sources is not None:
        for name in sorted(sources):
            f, a = scan_source(sources[name], name)
            findings += f
            annotated += a
            scanned += 1
    else:
        code_root = os.path.join(repo_root, "mxnet_tpu")
        for sub in subdirs:
            base = os.path.join(code_root, sub)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fname in sorted(filenames):
                    if not fname.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fname)
                    rel = os.path.relpath(path, repo_root)
                    try:
                        with open(path) as f:
                            src = f.read()
                    except OSError:
                        continue
                    fs, an = scan_source(src, rel)
                    findings += fs
                    annotated += an
                    scanned += 1
    return {"findings": findings, "annotated": annotated,
            "files_scanned": scanned, "ok": not findings}
