"""Graph verifier & hazard linter: bind-time static analysis.

The NNVM-pass discipline of the reference (InferShape/InferType run to
fixpoint before anything executes, graph_executor.cc:425), regrown over
this framework's own hazard classes: shape/dtype/structure consistency
(``graph_verifier``), use-after-donation through the fused/scan/ZeRO
plans (``donation_checker``), cross-worker collective dispatch order
(``collective_order``), program-cache key churn (``retrace_churn``),
host syncs on the fit hot path (``host_sync``), dtype flow through the
mixed-precision/int8-quant tiers (``precision_flow``, QT7xx), and the
static memory planner (``memory_planner``, ME8xx — peak HBM predicted
before anything compiles; ``memplan.py``). Registration-time siblings:
``kernelcheck.py`` validates Pallas kernel specs at ``add_variant``
(PK9xx), ``envaudit.py`` keeps MXNET_* env reads and docs/env_var.md
in lockstep. Dynamic-behavior passes cover the host plane the serving
and checkpoint PRs made load-bearing: ``racecheck.py`` (RC2xx
cross-thread shared-state lint over serve/checkpoint/telemetry/faults),
``cachekey.py`` (CK3xx program-cache-key completeness against a
declared knob registry), and ``determinism.py`` (DT4xx replay audit:
wall-clock seam, global RNG, set-order nondeterminism).

Three surfaces:

* bind time — ``sym.bind(..., validate="warn"|"raise")``,
  ``simple_bind(..., validate=...)``, or process-wide via
  ``MXNET_GRAPH_VALIDATE``; Module re-validates after the fused/ZeRO
  plans arm in ``init_optimizer``;
* CLI — ``tools/mxlint.py`` lints symbol JSON files and the bundled
  model zoo, exiting nonzero on error-severity findings;
* telemetry — findings mirror into the ``analysis.lint.findings``
  counter family and the flight-recorder ring, and ``tools/diagnose.py``
  renders them in its health reports.

Rule catalog: docs/analysis.md (ids are stable; suppress with
``MXNET_LINT_DISABLE=GV107,HS501,...``).
"""
from __future__ import annotations

from .diagnostics import Diagnostic, Report, RULES, SEVERITIES
from .passes import (AnalysisContext, PASSES, run_passes, lint_symbol,
                     lint_executor, lint_module, lint_json,
                     validate_executor, validate_module, resolve_mode,
                     attr_cache_stable)
from . import (envaudit, kernelcheck, memplan, metricaudit, precision,
               racecheck, cachekey, determinism)

__all__ = ["Diagnostic", "Report", "RULES", "SEVERITIES",
           "AnalysisContext", "PASSES", "run_passes", "lint_symbol",
           "lint_executor", "lint_module", "lint_json",
           "validate_executor", "validate_module", "resolve_mode",
           "attr_cache_stable", "envaudit", "kernelcheck", "memplan",
           "metricaudit", "precision", "racecheck", "cachekey",
           "determinism"]
