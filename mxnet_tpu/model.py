"""Checkpoint helpers + legacy FeedForward model API.

reference: python/mxnet/model.py (946 LoC): ``save_checkpoint`` /
``load_checkpoint`` (model.py:319-380), ``_create_kvstore`` decision
(model.py:40-77), and the deprecated-but-functional ``FeedForward``.
"""
from __future__ import annotations

import logging
from collections import namedtuple

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol as sym
from .context import cpu, current_context
from . import optimizer as opt
from . import metric as metric_mod
from .io import DataIter, NDArrayIter

__all__ = ["save_checkpoint", "load_checkpoint", "FeedForward",
           "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Decide (kvstore, update_on_kvstore). reference: model.py:40-77."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, str):
        from . import kvstore as kvs
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape)
                               for param in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        kv = kvstore
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """reference: model.py:79-87."""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save prefix-symbol.json + prefix-%04d.params.
    reference: model.py:319-347."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """reference: model.py:349-380."""
    symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy estimator-style API (deprecated in the reference too; kept
    for parity). reference: model.py:383-946. Thin adapter over Module."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [current_context()]
        if not isinstance(self.ctx, (list, tuple)):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    def _get_module(self, data_shapes, label_shapes=None, for_training=True):
        from .module import Module
        mod = Module(self.symbol,
                     data_names=[d[0] for d in data_shapes],
                     label_names=[l[0] for l in label_shapes]
                     if label_shapes else [],
                     context=self.ctx)
        mod.bind(data_shapes, label_shapes, for_training=for_training)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data = self._init_iter(X, y, is_train=True)
        mod = self._get_module(data.provide_data, data.provide_label)
        self._module = mod
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs or {"learning_rate": 0.01},
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1, monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._init_iter(X, None, is_train=False)
        if reset:
            data.reset()
        mod = self._get_module(data.provide_data, data.provide_label or None,
                               for_training=False)
        if self.arg_params:
            mod.set_params(self.arg_params, self.aux_params or {},
                           allow_missing=False)
        outputs = mod.predict(data, num_batch=num_batch)
        if isinstance(outputs, list):
            return [o.asnumpy() for o in outputs]
        return outputs.asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        data = self._init_iter(X, None, is_train=False)
        if reset:
            data.reset()
        mod = self._get_module(data.provide_data, data.provide_label,
                               for_training=False)
        mod.set_params(self.arg_params, self.aux_params or {})
        res = mod.score(data, eval_metric, num_batch=num_batch)
        return res[0][1]

    def _init_iter(self, X, y, is_train):
        if isinstance(X, DataIter):
            return X
        if isinstance(X, (np.ndarray, NDArray)):
            if y is None:
                y = np.zeros(X.shape[0], dtype=np.float32)
            return NDArrayIter(X, y, min(self.numpy_batch_size, X.shape[0]),
                               shuffle=is_train, last_batch_handle="roll_over")
        raise TypeError("X must be DataIter or array")

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list)
        return model
