"""Gradient-sync comm/compute overlap: bucket scheduler characterization.

Measures the ready-order bucket all-reduce (kvstore_sched.py) through
the post-hoc push/pull arrangement — a Module trained with a dist_sync
kvstore (single process, all local devices in the reduction mesh), a
~13 MiB MLP, at bucket caps of {4, 32, 64} MiB — recording per cap:

  * ``buckets_per_update`` — collectives per optimizer step;
  * ``max_in_flight`` — the most buckets simultaneously dispatched but
    not yet consumed (from the scheduler's per-bucket timing log); >= 2
    means bucket collectives pipeline instead of running serially;
  * ``exposed_comm_fraction`` — exposed / (exposed + hidden) from the
    ``kvstore.exposed.seconds`` / ``kvstore.overlap.seconds`` counters:
    the share of collective wall time the host actually waited on at
    flush, vs time the collectives ran behind other work;
  * steady-state img/s (first epoch warms compiles, second is timed).

CPU-backend safe (runs on the 8-virtual-device mesh anywhere) and
writes ``benchmarks/results/comm_overlap.json``.

Run: JAX_PLATFORMS=cpu python benchmarks/comm_overlap.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCH = 32
N_BATCHES = 8
CLASSES = 10
FEATS = 256
HIDDEN = 1024
BUCKET_MIB = (4, 32, 64)


def _net():
    import mxnet_tpu as mx
    net = mx.sym.var("data")
    for i in range(3):
        net = mx.sym.FullyConnected(net, num_hidden=HIDDEN, name=f"fc{i}")
        net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="out")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _max_in_flight(log):
    """Max simultaneously-open [dispatch_t, apply_t] windows."""
    events = []
    for b in log:
        events.append((b["dispatch_t"], 1))
        events.append((b["apply_t"], -1))
    live = peak = 0
    for _, d in sorted(events):
        live += d
        peak = max(peak, live)
    return peak


def measure(bucket_mib):
    import mxnet_tpu as mx
    import jax
    os.environ["MXNET_KVSTORE_BUCKET_BYTES"] = str(bucket_mib << 20)
    rng = np.random.RandomState(0)
    imgs = rng.rand(N_BATCHES * BATCH, FEATS).astype(np.float32)
    labels = (rng.rand(N_BATCHES * BATCH) * CLASSES).astype(np.float32)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)

    n_dev = min(8, len(jax.devices()))
    mod = mx.mod.Module(_net(), context=[mx.cpu(i) for i in range(n_dev)])
    opt = (("learning_rate", 0.05), ("momentum", 0.9))
    mod.fit(it, num_epoch=1, kvstore="dist_sync",
            initializer=mx.initializer.Xavier(), optimizer_params=opt)
    kv = mod._kvstore
    kv._sched.bucket_log.clear()

    mx.telemetry.reset()
    mx.telemetry.enable()
    it.reset()
    t0 = time.perf_counter()
    mod.fit(it, num_epoch=1, kvstore="dist_sync", optimizer_params=opt)
    elapsed = time.perf_counter() - t0
    mx.telemetry.disable()
    snap = mx.telemetry.snapshot()
    hidden = snap["counters"].get("kvstore.overlap.seconds", 0.0)
    exposed = snap["counters"].get("kvstore.exposed.seconds", 0.0)
    log = list(kv._sched.bucket_log)
    kv.close()
    total = hidden + exposed
    return {
        "bucket_mib": bucket_mib,
        "buckets_per_update": round(len(log) / N_BATCHES, 2),
        "max_in_flight": _max_in_flight(log),
        "hidden_comm_s": round(hidden, 4),
        "exposed_comm_s": round(exposed, 4),
        "exposed_comm_fraction": round(exposed / total, 4) if total else None,
        "img_per_sec": round(N_BATCHES * BATCH / elapsed, 1),
        "epoch_seconds": round(elapsed, 4),
    }


def main():
    import mxnet_tpu as mx  # noqa: F401 — fail early if the env is broken
    import jax
    results = {"batch_size": BATCH, "n_batches": N_BATCHES,
               "backend": jax.devices()[0].platform,
               "n_devices": min(8, len(jax.devices())),
               "by_bucket": [measure(m) for m in BUCKET_MIB]}
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "results", "comm_overlap.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    main()
