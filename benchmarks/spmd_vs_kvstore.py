"""SPMD vs kvstore-overlap training: paired-lap characterization.

Measures the same ~13 MiB MLP trained two ways on an 8-virtual-device
mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=8`` anywhere,
real chips on a TPU host):

  * ``kvstore`` — the ready-order bucket all-reduce path
    (kvstore_sched.py behind a single-process dist_sync store): fused
    step disabled by the store arrangement, per-key push/pull with the
    overlap scheduler; records ``exposed_comm_s`` from the
    ``kvstore.exposed.seconds`` counter (host-visible collective wait).
  * ``spmd`` — ``Module.fit(spmd=True, kvstore=None)``: ONE jitted
    program over the named mesh, gradient collectives emitted by XLA
    from the sharding specs. Exposed comm is structurally zero — there
    is no host-side collective to wait on (the column is reported as
    0.0 with the in-program note).

The two sides alternate epoch-by-epoch (paired laps) so machine drift
cancels to first order; the first epoch of each side warms compiles and
is excluded. Writes ``benchmarks/results/spmd_vs_kvstore.json``; bench
.py folds the headline ratio into its ``spmd`` variant row.

Run: JAX_PLATFORMS=cpu python benchmarks/spmd_vs_kvstore.py
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCH = 32
N_BATCHES = 8
ROUNDS = 4
CLASSES = 10
FEATS = 256
HIDDEN = 1024


def _net():
    import mxnet_tpu as mx
    net = mx.sym.var("data")
    for i in range(3):
        net = mx.sym.FullyConnected(net, num_hidden=HIDDEN, name=f"fc{i}")
        net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="out")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _setup(side, n_dev):
    """Bind + warm one arrangement; returns (module, iterator, opts)."""
    import mxnet_tpu as mx
    rng = np.random.RandomState(0)
    imgs = rng.rand(N_BATCHES * BATCH, FEATS).astype(np.float32)
    labels = (rng.rand(N_BATCHES * BATCH) * CLASSES).astype(np.float32)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)
    mod = mx.mod.Module(_net(), context=[mx.cpu(i) for i in range(n_dev)])
    opt = (("learning_rate", 0.05), ("momentum", 0.9))
    kwargs = dict(spmd=True, kvstore=None) if side == "spmd" \
        else dict(spmd=False, kvstore="dist_sync")
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params=opt, **kwargs)
    if side == "spmd":
        assert mod._fused_armed, "spmd side must run the fused program"
        assert mod._kvstore is None
    else:
        assert mod._kvstore is not None, \
            "kvstore side must run the store path"
    return mod, it, opt, kwargs


def _timed_epoch(mod, it, opt, kwargs):
    import jax
    it.reset()
    laps, lap = [], [time.perf_counter()]

    def cb(param):
        m = param.eval_metric
        if getattr(m, "_pending", None):
            float(jax.device_get(m._pending[-1][0]))
        laps.append(time.perf_counter() - lap[0])
        lap[0] = time.perf_counter()

    mod.fit(it, num_epoch=1, optimizer_params=opt,
            batch_end_callback=cb, **kwargs)
    return laps


def main(quiet=False):
    """``quiet`` suppresses the stdout JSON line (bench.py embeds the
    result in its own single-line payload instead)."""
    import mxnet_tpu as mx
    import jax

    n_dev = min(8, len(jax.devices()))
    sides = {}
    for side in ("kvstore", "spmd"):
        sides[side] = _setup(side, n_dev)

    laps = {"kvstore": [], "spmd": []}
    exposed = hidden = 0.0
    for r in range(ROUNDS):
        for side in ("kvstore", "spmd"):       # paired: same seconds
            mod, it, opt, kwargs = sides[side]
            mx.telemetry.reset()
            mx.telemetry.enable()
            try:
                laps[side].extend(_timed_epoch(mod, it, opt, kwargs))
            finally:
                snap = mx.telemetry.snapshot()["counters"]
                mx.telemetry.disable()
            if side == "kvstore":
                exposed += snap.get("kvstore.exposed.seconds", 0.0)
                hidden += snap.get("kvstore.overlap.seconds", 0.0)

    kv = sides["kvstore"][0]._kvstore
    if kv is not None:
        kv.close()

    def img_s(ls):
        return BATCH / statistics.median(ls)

    result = {
        "n_devices": n_dev,
        "batch": BATCH,
        "rounds": ROUNDS,
        "spmd": {
            "img_per_sec": round(img_s(laps["spmd"]), 1),
            "exposed_comm_s": 0.0,
            "note": "collectives live inside the jitted program; no "
                    "host-side collective wait exists to expose",
        },
        "kvstore": {
            "img_per_sec": round(img_s(laps["kvstore"]), 1),
            "exposed_comm_s": round(exposed, 4),
            "hidden_comm_s": round(hidden, 4),
        },
        "spmd_vs_kvstore": round(img_s(laps["spmd"]) /
                                 img_s(laps["kvstore"]), 3),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "results", "spmd_vs_kvstore.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    if not quiet:
        print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
