#!/usr/bin/env python
"""Bind-time validation (MXNET_GRAPH_VALIDATE=warn) overhead gate.

The static-analysis passes run inside ``Executor.__init__`` when
validation is on; the promise (ISSUE 5 acceptance) is that warn mode
adds **< 2% to bind wall time**. Two measurements:

1. **warm binds** — the steady state: the graph verifier's fixpoint
   entry shapes are memoized per (symbol, shapes) on the symbol object,
   so every rebind of a symbol the process has already validated
   (train/eval pairs, force_rebind, bucketing cycles — the paths the
   program cache exists for) pays dict-lookup prices. This is the
   asserted < 2% gate.
2. **cold binds** — first validation of a fresh symbol: the memo is
   dropped before every bind, so each one pays the full fixpoint
   inference walk. Reported alongside (the walk is the same O(nodes)
   python pass ``simple_bind`` itself runs once for shape allocation,
   so this bounds near the per-bind inference share).

Run: JAX_PLATFORMS=cpu python benchmarks/lint_overhead.py
Writes benchmarks/results/lint_overhead.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import mxnet_tpu as mx                              # noqa: E402
from mxnet_tpu.models import resnet                 # noqa: E402

GATE_PCT = 2.0
REPEATS = 7
BINDS_PER_ROUND = 5
SHAPE = (8, 3, 32, 32)


def timed_binds(net, validate):
    """Wall time of BINDS_PER_ROUND simple_binds (no device compute is
    forced: bind cost = inference + runner build + array allocation,
    which is exactly what validation rides on)."""
    t0 = time.perf_counter()
    for _ in range(BINDS_PER_ROUND):
        net.simple_bind(ctx=mx.cpu(), data=SHAPE, validate=validate)
    return time.perf_counter() - t0


def measure(net, drop_memo):
    """Interleaved off/warn rounds; returns (t_off, t_warn) minima."""
    all_off, all_warn = [], []
    timed_binds(net, None)                  # settle allocator caches
    timed_binds(net, "warn")
    for _ in range(REPEATS):
        if drop_memo and hasattr(net, "_mx_lint_memo"):
            del net._mx_lint_memo
        all_off.append(timed_binds(net, None))
        if drop_memo and hasattr(net, "_mx_lint_memo"):
            del net._mx_lint_memo
        if drop_memo:
            # cold mode: every validated bind re-walks the fixpoint, so
            # drop the memo before each individual bind
            t = 0.0
            for _ in range(BINDS_PER_ROUND):
                if hasattr(net, "_mx_lint_memo"):
                    del net._mx_lint_memo
                t0 = time.perf_counter()
                net.simple_bind(ctx=mx.cpu(), data=SHAPE, validate="warn")
                t += time.perf_counter() - t0
            all_warn.append(t)
        else:
            all_warn.append(timed_binds(net, "warn"))
    return min(all_off), min(all_warn)


def main():
    net = resnet.get_symbol(10, 20, "3,32,32")

    t_off_warm, t_warn_warm = measure(net, drop_memo=False)
    warm_pct = (t_warn_warm / t_off_warm - 1.0) * 100.0

    t_off_cold, t_warn_cold = measure(net, drop_memo=True)
    cold_pct = (t_warn_cold / t_off_cold - 1.0) * 100.0

    n_nodes = len(net._topo_nodes())
    result = {
        "metric": "lint_bind_overhead",
        "gate_pct": GATE_PCT,
        "model": "resnet20",
        "graph_nodes": n_nodes,
        "binds_per_round": BINDS_PER_ROUND,
        "repeats": REPEATS,
        "bind_s_off_warm": t_off_warm / BINDS_PER_ROUND,
        "bind_s_warn_warm": t_warn_warm / BINDS_PER_ROUND,
        "warm_overhead_pct": warm_pct,
        "bind_s_off_cold": t_off_cold / BINDS_PER_ROUND,
        "bind_s_warn_cold": t_warn_cold / BINDS_PER_ROUND,
        "cold_overhead_pct": cold_pct,
    }
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, "lint_overhead.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    print(f"wrote {out_path}")

    assert warm_pct < GATE_PCT, (
        f"warm-bind validation overhead {warm_pct:.3f}% >= "
        f"{GATE_PCT}% gate")
    print(f"OK: warm {warm_pct:+.3f}% (< {GATE_PCT}% gate) | "
          f"cold first-validation {cold_pct:+.2f}% (reported)")


if __name__ == "__main__":
    main()
