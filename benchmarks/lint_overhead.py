#!/usr/bin/env python
"""Bind-time validation (MXNET_GRAPH_VALIDATE=warn) overhead gate.

The static-analysis passes run inside ``Executor.__init__`` when
validation is on; the promise (ISSUE 5 acceptance) is that warn mode
adds **< 2% to bind wall time**. Two measurements:

1. **warm binds** — the steady state: the graph verifier's fixpoint
   entry shapes are memoized per (symbol, shapes) on the symbol object,
   so every rebind of a symbol the process has already validated
   (train/eval pairs, force_rebind, bucketing cycles — the paths the
   program cache exists for) pays dict-lookup prices. This is the
   asserted < 2% gate.
2. **cold binds** — first validation of a fresh symbol: the memo is
   dropped before every bind, so each one pays the full fixpoint
   inference walk. Reported alongside (the walk is the same O(nodes)
   python pass ``simple_bind`` itself runs once for shape allocation,
   so this bounds near the per-bind inference share).

Run: JAX_PLATFORMS=cpu python benchmarks/lint_overhead.py
Writes benchmarks/results/lint_overhead.json.
"""
from __future__ import annotations

import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import mxnet_tpu as mx                              # noqa: E402
from mxnet_tpu.models import resnet                 # noqa: E402

GATE_PCT = 2.0
PAIRS = 100
SHAPE = (8, 3, 32, 32)


def timed_bind(net, validate):
    """Wall time of one simple_bind (no device compute is forced: bind
    cost = inference + runner build + array allocation, which is
    exactly what validation rides on)."""
    t0 = time.perf_counter()
    net.simple_bind(ctx=mx.cpu(), data=SHAPE, validate=validate)
    return time.perf_counter() - t0


def measure(net, drop_memo):
    """Median per-pair overhead ratio over PAIRS adjacent (off, warn)
    bind pairs, plus the median per-bind seconds of each mode.

    A single bind here is ~30ms and the host's per-bind noise floor is
    mushy (GC, scheduler preemption, allocator growth — each worth
    10-20% of a bind), so neither means nor minima of independent
    samples resolve a 2% signal.  Paired adjacent binds share their
    noise regime, the in-pair order alternates so neither mode
    systematically goes first, the collector runs *between* pairs and
    is disabled *inside* them (executors are cyclic garbage — with GC
    off for the whole run they accumulate and skew the tail), and the
    median of the per-pair ratios discards the spikes that do land."""
    ratios, offs, warns = [], [], []
    timed_bind(net, None)                   # settle allocator caches
    timed_bind(net, "warn")
    for i in range(PAIRS):
        gc.collect()
        gc.disable()
        # cold mode: every validated bind re-walks the fixpoint, so
        # drop the memo before each warn bind
        if drop_memo and hasattr(net, "_mx_lint_memo"):
            del net._mx_lint_memo
        if i % 2 == 0:
            t_off = timed_bind(net, None)
            t_warn = timed_bind(net, "warn")
        else:
            t_warn = timed_bind(net, "warn")
            t_off = timed_bind(net, None)
        gc.enable()
        ratios.append(t_warn / t_off - 1.0)
        offs.append(t_off)
        warns.append(t_warn)
    med = lambda xs: sorted(xs)[len(xs) // 2]
    return med(offs), med(warns), med(ratios) * 100.0


def main():
    net = resnet.get_symbol(10, 20, "3,32,32")

    # the gated number is the median of five independent warm
    # measures: one measure's median still wobbles ~±2% when the host
    # drifts into a noisy regime for a few seconds, five don't wobble
    # together
    warm_runs = sorted((measure(net, drop_memo=False) for _ in range(5)),
                       key=lambda r: r[2])
    t_off_warm, t_warn_warm, warm_pct = warm_runs[2]
    t_off_cold, t_warn_cold, cold_pct = measure(net, drop_memo=True)

    n_nodes = len(net._topo_nodes())
    result = {
        "metric": "lint_bind_overhead",
        "gate_pct": GATE_PCT,
        "model": "resnet20",
        "graph_nodes": n_nodes,
        "pairs": PAIRS,
        "bind_s_off_warm": t_off_warm,
        "bind_s_warn_warm": t_warn_warm,
        "warm_overhead_pct": warm_pct,
        "bind_s_off_cold": t_off_cold,
        "bind_s_warn_cold": t_warn_cold,
        "cold_overhead_pct": cold_pct,
    }
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, "lint_overhead.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    print(f"wrote {out_path}")

    assert warm_pct < GATE_PCT, (
        f"warm-bind validation overhead {warm_pct:.3f}% >= "
        f"{GATE_PCT}% gate")
    print(f"OK: warm {warm_pct:+.3f}% (< {GATE_PCT}% gate) | "
          f"cold first-validation {cold_pct:+.2f}% (reported)")


if __name__ == "__main__":
    main()
