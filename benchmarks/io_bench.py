"""Data-pipeline throughput benchmark (reference methodology:
example/image-classification + iter_image_recordio_2.cc's OMP decode).

Packs a synthetic JPEG RecordIO set, then measures end-to-end iterator
throughput (RecordIO read -> JPEG decode -> augment -> batch -> optional
prefetch-to-device) in images/sec for BOTH pipelines:

  * mp      — multiprocess decode workers + shared-memory staging
              (mp_decode.py, the analog of the reference's OMP parser);
  * threads — the in-process thread-pool ImageIter fallback.

The number to beat is the bench model's consumption rate: ResNet-50 on
one v5e-class chip consumes ~1000-2000 img/s, so the mp pipeline must
sustain more than that per multicore host (it scales with worker
processes; the per-core rate times cores is the host projection).

    python benchmarks/io_bench.py [--images 512] [--batch-size 64]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import recordio  # noqa: E402


def make_synthetic_pack(prefix, n, size=256):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import im2rec
    rng = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        buf = im2rec._encode(img, quality=90)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), buf))
    rec.close()


def _drain(it, epochs):
    # warm epoch (worker/threads startup, caches)
    for _ in it:
        pass
    it.reset()
    tic = time.perf_counter()
    seen = 0
    for _ in range(epochs):
        for batch in it:
            seen += batch.data[0].shape[0] - batch.pad
        it.reset()
    return seen / (time.perf_counter() - tic)


def measure_mp(prefix, batch_size, data_shape, device=None, epochs=2,
               num_workers=None):
    """Returns (img_per_sec, actual_worker_count) or None."""
    it = mx.image.ImageRecordIter(
        prefix + ".rec", data_shape, batch_size,
        path_imgidx=prefix + ".idx", rand_crop=True, rand_mirror=True,
        num_workers=num_workers, prefetch=False)
    if not type(it).__name__ == "MPImageRecordIter":
        return None
    wrapped = mx.io.PrefetchingIter(it, device=device)
    try:
        return _drain(wrapped, epochs), it._W
    finally:
        it.close()


def measure_threads(prefix, batch_size, data_shape, device=None, epochs=2):
    aug = mx.image.CreateAugmenter(data_shape, rand_crop=True,
                                   rand_mirror=True)
    it = mx.image.ImageIter(
        batch_size, data_shape, path_imgrec=prefix + ".rec",
        aug_list=aug, num_threads=os.cpu_count() or 4)
    it = mx.io.PrefetchingIter(it, device=device)
    return _drain(it, epochs)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--images", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--size", type=int, default=256)
    p.add_argument("--crop", type=int, default=224)
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--to-device", action="store_true",
                   help="include prefetch-to-device placement")
    args = p.parse_args()
    shape = (3, args.crop, args.crop)
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "synth")
        make_synthetic_pack(prefix, args.images, args.size)
        dev = mx.context.current_context() if args.to_device else None
        mp_res = measure_mp(prefix, args.batch_size, shape, device=dev,
                            num_workers=args.workers)
        th_img_s = measure_threads(prefix, args.batch_size, shape,
                                   device=dev)
    cores = os.cpu_count() or 1
    mp_img_s, workers = mp_res if mp_res else (None, None)
    print(json.dumps({
        "metric": "imagerecorditer_decode_augment_img_per_sec",
        "value": round(mp_img_s or th_img_s, 1),
        "unit": "img/s",
        "pipeline": "mp" if mp_img_s else "threads",
        "mp_img_per_sec": None if mp_img_s is None else round(mp_img_s, 1),
        "threads_img_per_sec": round(th_img_s, 1),
        "batch_size": args.batch_size,
        "prefetch_to_device": bool(args.to_device),
        "cores": cores,
        "mp_workers": workers,
        "host_projection_img_per_sec": None if mp_img_s is None else
        round(mp_img_s / workers * cores, 1),
    }))


if __name__ == "__main__":
    main()
