"""Per-step dispatch overhead: K-step scan fit vs the per-batch loop.

Measures the dispatch/compile amortization layer on the XLA CPU backend
(deterministic, runs anywhere): a small conv net trained through
``Module.fit`` at ``steps_per_dispatch`` K in {1, 4, 8}, recording

  * dispatches per batch — the ``executor.dispatch`` telemetry counter
    (every ``telemetry.wrap_dispatch`` submission) divided by batches;
    K=1 pays one dispatch per batch, K=8 pays 1/8;
  * steady-state img/s over the epoch (first epoch compiles, second is
    timed);

and writes ``benchmarks/results/step_overhead.json``. The companion
non-slow gate lives in tests/test_scan_fit.py (K=8 must issue <= 2
dispatches per 8 batches).

Run: JAX_PLATFORMS=cpu python benchmarks/step_overhead.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCH = 32
N_BATCHES = 32
CLASSES = 10
KS = (1, 4, 8)


def _net():
    import mxnet_tpu as mx
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def measure(K):
    import mxnet_tpu as mx
    rng = np.random.RandomState(0)
    imgs = rng.rand(N_BATCHES * BATCH, 1, 16, 16).astype(np.float32)
    labels = (rng.rand(N_BATCHES * BATCH) * CLASSES).astype(np.float32)
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)

    mod = mx.mod.Module(_net(), context=mx.cpu())
    opt = (("learning_rate", 0.05), ("momentum", 0.9))
    mod.fit(it, num_epoch=1, steps_per_dispatch=K,
            initializer=mx.initializer.Xavier(), optimizer_params=opt)

    mx.telemetry.reset()
    mx.telemetry.enable()
    it.reset()
    t0 = time.perf_counter()
    mod.fit(it, num_epoch=1, steps_per_dispatch=K, optimizer_params=opt)
    elapsed = time.perf_counter() - t0
    mx.telemetry.disable()
    snap = mx.telemetry.snapshot()
    dispatches = snap["counters"].get("executor.dispatch", 0)
    return {
        "steps_per_dispatch": K,
        "batches": N_BATCHES,
        "dispatches": dispatches,
        "dispatches_per_batch": round(dispatches / N_BATCHES, 4),
        "img_per_sec": round(N_BATCHES * BATCH / elapsed, 1),
        "epoch_seconds": round(elapsed, 4),
    }


def main():
    import mxnet_tpu as mx  # noqa: F401 — fail early if the env is broken
    results = {"batch_size": BATCH, "n_batches": N_BATCHES,
               "backend": "cpu", "by_k": [measure(K) for K in KS]}
    k1 = next(r for r in results["by_k"] if r["steps_per_dispatch"] == 1)
    for r in results["by_k"]:
        r["speedup_vs_k1"] = round(r["img_per_sec"] / k1["img_per_sec"], 3)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "results", "step_overhead.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
