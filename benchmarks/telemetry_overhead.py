#!/usr/bin/env python
"""Disabled-telemetry fast-path + always-on flight-recorder overhead gate.

The telemetry subsystem promises that when it is OFF (the default), the
instrumentation woven through executor/kvstore/io/Module.fit costs under
2% of a small Module.fit loop. Two measurements back that:

1. **A/B fit timing** — the same fit epoch with (a) telemetry disabled
   (the shipped fast path: every site does one ``enabled()`` branch /
   null-span) and (b) the telemetry API monkeypatched to bare no-op
   lambdas (the cheapest instrumentation physically expressible in
   Python, standing in for an uninstrumented build). Their ratio bounds
   what the real branch logic adds over the floor.
2. **Primitive scaling** — the per-call cost of the disabled
   ``span()``/``enabled()`` primitives times the number of telemetry
   call sites hit per batch (counted by running one enabled epoch),
   divided by the measured disabled batch time. This is the analytic
   overhead bound and the asserted gate: it must stay < 2%.

The flight recorder (telemetry/flightrec.py) is ALWAYS ON — its whole
point is recording when nobody enabled anything — so its ring gets the
same two measurements (A/B recorder-on vs recorder-off epochs, plus
note()-cost x notes-per-batch analytic bound) under the same <2% gate.

The training-health plane (telemetry/health.py) promises that arming
(``MXNET_TRAIN_HEALTH=1``) keeps a fit loop within the same <2% bound:
per-step stats ride the already-jitted program as extra ys and the
param-norm/update-ratio reading is one amortised pass per dispatch
window (no added dispatches either way), so the host-side cost is one
detector ``observe()`` per batch plus one stat-window decode per
dispatch. A/B armed-vs-unarmed K=8 scan fits on a dedicated
larger-compute config corroborate; the analytic host bound is the
gate.

The live ops endpoint (telemetry/opsd.py) promises zero dispatch-path
interaction: an out-of-process scraper paced well beyond production
cadence hammers /metrics + /healthz while K=8 scan windows run — the
A/B delta sits under the same <2% gate, every mid-loop response body
is verified, and the fused step must record zero recompiles while
being scraped.

Run: JAX_PLATFORMS=cpu python benchmarks/telemetry_overhead.py
Writes benchmarks/results/telemetry_overhead.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import mxnet_tpu as mx
from mxnet_tpu import telemetry as tm
from mxnet_tpu.telemetry import core as tm_core
from mxnet_tpu.telemetry import flightrec as tm_flight

GATE_PCT = 2.0
BATCH = 32
N = 32 * 40          # 40 batches per epoch
REPEATS = 5


def build_module():
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=64),
                act_type="relu"),
            num_hidden=10),
        name="softmax")
    return mx.mod.Module(net, context=mx.cpu())


def make_iter():
    X = np.random.rand(N, 32).astype("f")
    Y = (np.random.rand(N) * 10).astype("f")
    return mx.io.NDArrayIter(X, Y, batch_size=BATCH)


def timed_epoch(mod, it):
    """Wall time of one full epoch (device work forced to completion)."""
    it.reset()
    t0 = time.perf_counter()
    for batch in it:
        mod.forward_backward(batch)
        mod.update()
    mx.nd.waitall()
    return time.perf_counter() - t0


def fit_once(mod, it):
    mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.05})


def main():
    tm.disable()
    tm.reset()
    it = make_iter()
    mod = build_module()
    fit_once(mod, it)                       # warm: bind + compile
    it.reset()

    # ---- 1. A/B: disabled fast path vs bare-lambda no-op floor --------
    # interleaved rounds so thermal/scheduler drift hits both arms alike
    null = tm_core.null_span
    noop_api = {"span": lambda *a, **k: null,
                "enabled": lambda: False,
                "record_event": lambda *a, **k: None,
                "event": lambda *a, **k: None}
    real_api = {name: getattr(tm, name) for name in noop_api}

    all_disabled, all_noop = [], []
    timed_epoch(mod, it)                    # settle caches before timing
    for _ in range(REPEATS):
        all_disabled.append(timed_epoch(mod, it))
        try:
            for name, fn in noop_api.items():
                setattr(tm, name, fn)
            all_noop.append(timed_epoch(mod, it))
        finally:
            for name, fn in real_api.items():
                setattr(tm, name, fn)
    t_disabled, t_noop = min(all_disabled), min(all_noop)
    ab_overhead_pct = (t_disabled / t_noop - 1.0) * 100.0

    # ---- 2. primitive cost x call sites per batch ---------------------
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        with tm.span("x"):
            pass
    span_ns = (time.perf_counter() - t0) / reps * 1e9
    t0 = time.perf_counter()
    for _ in range(reps):
        tm.enabled()
    enabled_ns = (time.perf_counter() - t0) / reps * 1e9

    # count telemetry activity per batch by running one enabled epoch
    tm.enable()
    tm.reset()
    it.reset()
    nb = 0
    for batch in it:
        mod.forward_backward(batch)
        mod.update()
        nb += 1
    tm.disable()
    sites_per_batch = (len(tm.get_spans()) + len(tm.get_events())) / nb
    # each site ~ one enabled() check + one null-span protocol when off;
    # double it for guard checks that don't open spans
    calls_per_batch = sites_per_batch * 2
    batch_s = t_disabled / nb
    analytic_pct = (calls_per_batch * (span_ns + enabled_ns) / 1e9
                    / batch_s) * 100.0
    tm.reset()

    # ---- 3. always-on flight-recorder ring ----------------------------
    # A/B: ring recording (the shipped default) vs recorder disabled,
    # interleaved like measurement 1
    all_rec_on, all_rec_off = [], []
    tm_flight.configure(enabled=True)
    timed_epoch(mod, it)                    # settle
    for _ in range(REPEATS):
        try:
            tm_flight.configure(enabled=True)
            all_rec_on.append(timed_epoch(mod, it))
            tm_flight.configure(enabled=False)
            all_rec_off.append(timed_epoch(mod, it))
        finally:
            tm_flight.configure(enabled=True)
    flight_ab_pct = (min(all_rec_on) / min(all_rec_off) - 1.0) * 100.0

    # primitive: one ring note (dict build + clock + deque append)
    t0 = time.perf_counter()
    for _ in range(reps):
        tm_flight.note("bench.note", i=1)
    note_ns = (time.perf_counter() - t0) / reps * 1e9

    # ---- 4. ARMED step-time attribution A/B (the trace plane's cost
    # when it is actually recording: per-step phase clocks, histograms,
    # the straggler detector, and the window-boundary block). The
    # GATED lap runs the K=8 scan path — attribution is per *window
    # boundary* by design (the ISSUE's "don't de-async the scan fast
    # path"), so its cost amortizes over K batches exactly like the
    # dispatch it instruments. The K=1 per-step figures are recorded
    # unasserted: there every step IS a boundary, and the block's
    # serialization is the cost the design accepts for full-resolution
    # attribution (on real >1ms production steps it is noise; against
    # THIS benchmark's sub-ms micro-batches it reads in the tens of
    # percent — that is the micro-step, not the instrument).
    from mxnet_tpu.telemetry import stepattr as tm_step

    def fit_epoch_timed(K, m=mod):
        it.reset()
        t0 = time.perf_counter()
        m.fit(it, num_epoch=1, steps_per_dispatch=K,
              optimizer_params={"learning_rate": 0.05})
        return time.perf_counter() - t0

    armed = {}
    for K in (8, 1):
        all_armed, all_unarmed = [], []
        fit_epoch_timed(K)                  # settle / compile
        for _ in range(REPEATS):
            try:
                tm_step.configure(armed=True)
                all_armed.append(fit_epoch_timed(K))
            finally:
                tm_step.configure(armed=False)
            all_unarmed.append(fit_epoch_timed(K))
        tm_step.configure(armed=None)
        tm_step.reset()
        armed[K] = (min(all_armed), min(all_unarmed),
                    all_armed, all_unarmed)
    armed_ab_pct = (armed[8][0] / armed[8][1] - 1.0) * 100.0
    armed_k1_ab_pct = (armed[1][0] / armed[1][1] - 1.0) * 100.0

    # analytic bound: one begin/note/end bookkeeping cycle per window
    # (5 histogram observes + the amortized straggler check) over the
    # K=8 window time
    tm_step.configure(armed=True)
    t0 = time.perf_counter()
    for i in range(20_000):
        tm_step.step_begin(0, i)
        tm_step.note("assemble", 0.0)
        tm_step.note("dispatch", 0.0)
        tm_step.note("device", 0.0)
        tm_step.step_end(steps=8)
    step_cycle_ns = (time.perf_counter() - t0) / 20_000 * 1e9
    tm_step.configure(armed=None)
    tm_step.reset()
    tm.reset()
    windows_per_epoch = nb / 8.0
    armed_analytic_pct = (windows_per_epoch * step_cycle_ns / 1e9
                          / armed[8][1]) * 100.0

    # notes per batch, counted against a ring large enough not to wrap
    tm_flight.configure(capacity=1_000_000)
    tm_flight.clear()
    it.reset()
    for batch in it:
        mod.forward_backward(batch)
        mod.update()
    notes_per_batch = len(tm_flight.get_records()) / nb
    tm_flight.clear()
    tm_flight.configure(capacity=512)
    flight_analytic_pct = (notes_per_batch * note_ns / 1e9 / batch_s) \
        * 100.0

    # ---- 4b. training-health plane A/B (in-program stats + detector)
    # Arming keys the fused program cache, and the flag is captured at
    # optimizer setup — so the armed arm is a SECOND module whose
    # program carries the stat ys. The benchmark's shared micro-config
    # (sub-ms steps) cannot see a fixed per-window cost honestly, so
    # this arm runs its own larger config where real compute dominates:
    # the per-step stats (grad norm / loss / nonfinite) fuse with the
    # backward pass, and the param-norm / update-ratio reading is ONE
    # amortised pass per K-step dispatch window (a per-step read of the
    # donated scan carry defeats the in-place update — measured as an
    # O(params) copy every step). Arms alternate order each round and
    # every timed fit ends in waitall(): the armed epoch drains stats
    # inside fit while the unarmed one returns with device work still
    # in flight, so without the barrier the comparison penalises the
    # armed arm for syncing. The hard gate is the analytic host bound —
    # one detector observe() per batch plus one stat-window decode per
    # dispatch — under the same noise discipline as the armed-tracing
    # arm above. Detector knobs are set so no rule fires: steady-state
    # cost is the observe pass, not the escalation ladder.
    from mxnet_tpu.telemetry import health as tm_health

    H_BATCH, H_NB, H_HID, H_K = 512, 16, 512, 8
    h_net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.var("data"),
                                      num_hidden=H_HID),
                act_type="relu"),
            num_hidden=10),
        name="softmax")
    h_X = np.random.rand(H_BATCH * H_NB, 32).astype("f")
    h_Y = (np.random.rand(H_BATCH * H_NB) * 10).astype("f")
    h_it = mx.io.NDArrayIter(h_X, h_Y, batch_size=H_BATCH)
    mod_h = mx.mod.Module(h_net, context=mx.cpu())
    mod_hu = mx.mod.Module(h_net, context=mx.cpu())

    def health_epoch(m):
        h_it.reset()
        t0 = time.perf_counter()
        m.fit(h_it, num_epoch=1, steps_per_dispatch=H_K,
              optimizer_params={"learning_rate": 0.05})
        mx.nd.waitall()
        return time.perf_counter() - t0

    _QUIET = {"k_mad": 1e12, "plateau_tol": 0.0,
              "ratio_band": (0.0, 1e30), "collapse_frac": 0.0}
    tm_health.configure(armed=True, **_QUIET)
    health_epoch(mod_h)                     # compile the armed program
    health_epoch(mod_h)                     # settle
    tm_health.configure(armed=False)
    health_epoch(mod_hu)
    health_epoch(mod_hu)
    all_h_armed, all_h_unarmed, h_diffs = [], [], []
    for i in range(2 * REPEATS):
        if i % 2 == 0:
            tm_health.configure(armed=False)
            u = health_epoch(mod_hu)
            tm_health.configure(armed=True, **_QUIET)
            a = health_epoch(mod_h)
        else:
            tm_health.configure(armed=True, **_QUIET)
            a = health_epoch(mod_h)
            tm_health.configure(armed=False)
            u = health_epoch(mod_hu)
        all_h_armed.append(a)
        all_h_unarmed.append(u)
        h_diffs.append(a - u)
    h_base = sorted(all_h_unarmed)[len(all_h_unarmed) // 2]
    h_diff_med = sorted(h_diffs)[len(h_diffs) // 2]
    health_ab_pct = (h_diff_med / h_base) * 100.0

    # analytic host bound, part 1: one detector observe() per batch
    # (the drain hands the monitor K stat dicts per window boundary)
    bench_mon = tm_health.HealthMonitor(window=64, **_QUIET)
    t0 = time.perf_counter()
    for i in range(20_000):
        bench_mon.observe({"grad_norm": 1.0 + (i % 7) * 0.01,
                           "param_norm": 10.0,
                           "update_ratio": 1e-3,
                           "loss": [2.3 - (i % 11) * 1e-3],
                           "nonfinite": 0.0})
    observe_ns = (time.perf_counter() - t0) / 20_000 * 1e9

    # part 2: one stat-window decode per dispatch — device_get of the
    # ready K-stacked pytree plus per-step record splitting, measured
    # against a synthetic window shaped exactly like the armed
    # program's output
    import jax.numpy as jnp
    ready_h = {"grad_norm": jnp.arange(H_K, dtype=jnp.float32) + 1.0,
               "loss": jnp.full((H_K, 1), 2.3, jnp.float32),
               "nonfinite": jnp.zeros((H_K,), jnp.float32),
               "param_norm": jnp.asarray(10.0, jnp.float32),
               "update_ratio": jnp.asarray(1e-3, jnp.float32)}
    _records = type(mod_h._exec_group)._health_records
    for _ in range(200):
        _records(ready_h)
    t0 = time.perf_counter()
    for _ in range(2_000):
        _records(ready_h)
    decode_ns = (time.perf_counter() - t0) / 2_000 * 1e9
    tm_health.configure(armed=None)
    tm_health.reset()
    tm.reset()
    health_analytic_pct = ((H_NB * observe_ns
                            + (H_NB / float(H_K)) * decode_ns)
                           / 1e9 / h_base) * 100.0

    # ---- 5. live ops endpoint under scrape load -----------------------
    # the opsd daemon promises zero dispatch-path interaction. The
    # scraper runs OUT of process (a scraper never shares the training
    # GIL in production; an in-process busy-loop client mostly measures
    # its own spin) paced at 20 Hz — ~300x the default Prometheus
    # cadence — while K=8 scan epochs run. Single epochs here are
    # ~30 ms, smaller than one scrape period, so the A/B times a
    # 20-epoch *window* per sample: every window provably absorbs
    # scrapes mid-loop (the child verifies each response body) and the
    # window wall time must stay under the same <2% gate. The fused
    # step must not recompile while being scraped.
    import subprocess
    import tempfile

    from mxnet_tpu.telemetry import opsd as tm_opsd

    CHILD_SRC = r"""
import json, os, sys, time, urllib.request
url, out_path, period = sys.argv[1], sys.argv[2], float(sys.argv[3])
stats = {"scrapes": 0, "errors": 0, "metrics_ok": 0, "healthz_ok": 0}
while True:
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(url + "/metrics", timeout=5) as r:
            m = r.read().decode()
        with urllib.request.urlopen(url + "/healthz", timeout=5) as r:
            h = json.loads(r.read().decode())
        stats["scrapes"] += 2
        stats["metrics_ok"] += int(m.startswith("# ") and "mxnet_" in m)
        stats["healthz_ok"] += int(isinstance(h.get("ok"), bool))
    except Exception:
        stats["errors"] += 1
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(stats, f)
    os.replace(tmp, out_path)
    time.sleep(max(0.0, period - (time.perf_counter() - t0)))
"""
    # 5 Hz is ~75x the default Prometheus cadence (1/15 s) and, on a
    # single-core box where the scraper process steals real cycles
    # (client AND server share the core — production scrapers live on
    # another host), keeps even the whole round-trip cost visibly under
    # the gate. Windows drift a few percent with machine warmup, so the
    # arms alternate order per round and the gate compares paired
    # means, not cross-arm minima.
    SCRAPE_HZ = 5.0
    EPOCHS_PER_WINDOW = 20
    OPS_ROUNDS = 6

    def window_timed(K, n_epochs):
        t0 = time.perf_counter()
        for _ in range(n_epochs):
            fit_epoch_timed(K)
        return time.perf_counter() - t0

    fit_epoch_timed(8)                      # settle / compile
    jit_cache = mod._exec_group.executor._jit_cache
    programs_before = len(jit_cache)
    stats = {"scrapes": 0, "errors": 0, "metrics_ok": 0, "healthz_ok": 0}
    all_scraped, all_quiet = [], []
    with tempfile.TemporaryDirectory() as tmpd:
        stats_path = os.path.join(tmpd, "scrape_stats.json")

        def scraped_window():
            srv = tm_opsd.serve_ops(port=0)
            child = subprocess.Popen(
                [sys.executable, "-c", CHILD_SRC, srv.url, stats_path,
                 str(1.0 / SCRAPE_HZ)])
            try:
                # wait for the first completed scrape (the child writes
                # stats after each one) so interpreter startup — a fat
                # one-off CPU burst on a small box — never lands inside
                # the timed window
                deadline = time.perf_counter() + 10.0
                while not os.path.exists(stats_path) and \
                        time.perf_counter() < deadline:
                    time.sleep(0.01)
                return window_timed(8, EPOCHS_PER_WINDOW)
            finally:
                child.terminate()
                child.wait(timeout=10)
                tm_opsd.stop_ops()
                with open(stats_path) as f:
                    for k, v in json.load(f).items():
                        stats[k] += v   # each child restarts at zero
                os.remove(stats_path)

        for i in range(OPS_ROUNDS):
            if i % 2 == 0:
                all_scraped.append(scraped_window())
                all_quiet.append(window_timed(8, EPOCHS_PER_WINDOW))
            else:
                all_quiet.append(window_timed(8, EPOCHS_PER_WINDOW))
                all_scraped.append(scraped_window())
    opsd_ab_pct = (sum(all_scraped) / sum(all_quiet) - 1.0) * 100.0
    opsd_compile_delta = len(jit_cache) - programs_before

    # every response taken mid-loop must be a real artifact, not just a
    # 200: the child checks each /metrics scrape parses as a registry
    # dump and each /healthz carries a verdict
    pairs = stats["scrapes"] // 2
    opsd_scrape_ok = (pairs > 0 and stats["errors"] == 0
                      and stats["metrics_ok"] == pairs
                      and stats["healthz_ok"] == pairs)

    result = {
        "metric": "telemetry_disabled_overhead",
        "gate_pct": GATE_PCT,
        "batches_per_epoch": nb,
        "batch_size": BATCH,
        "repeats": REPEATS,
        "epoch_s_disabled": t_disabled,
        "epoch_s_noop_floor": t_noop,
        "epoch_s_disabled_all": all_disabled,
        "epoch_s_noop_all": all_noop,
        "ab_overhead_pct": ab_overhead_pct,
        "span_call_ns_disabled": span_ns,
        "enabled_call_ns": enabled_ns,
        "telemetry_sites_per_batch": sites_per_batch,
        "analytic_overhead_pct": analytic_pct,
        "flight_recorder": {
            "gate_pct": GATE_PCT,
            "epoch_s_ring_on": min(all_rec_on),
            "epoch_s_ring_off": min(all_rec_off),
            "epoch_s_ring_on_all": all_rec_on,
            "epoch_s_ring_off_all": all_rec_off,
            "ab_overhead_pct": flight_ab_pct,
            "note_call_ns": note_ns,
            "notes_per_batch": notes_per_batch,
            "analytic_overhead_pct": flight_analytic_pct,
        },
        "armed_tracing": {
            "gate_pct": GATE_PCT,
            "gated_path": "K=8 scan (window-boundary attribution)",
            "epoch_s_armed": armed[8][0],
            "epoch_s_unarmed": armed[8][1],
            "epoch_s_armed_all": armed[8][2],
            "epoch_s_unarmed_all": armed[8][3],
            "ab_overhead_pct": armed_ab_pct,
            "step_cycle_ns": step_cycle_ns,
            "analytic_overhead_pct": armed_analytic_pct,
            "k1_per_step": {
                "note": "K=1: every step is a window boundary — the "
                        "per-step block serializes dispatch; recorded "
                        "unasserted (full-resolution attribution cost "
                        "against sub-ms micro-batches)",
                "epoch_s_armed": armed[1][0],
                "epoch_s_unarmed": armed[1][1],
                "ab_overhead_pct": armed_k1_ab_pct,
            },
        },
        "train_health": {
            "gate_pct": GATE_PCT,
            "gated_path": f"K={H_K} scan, health-armed program "
                          f"(batch={H_BATCH}, hidden={H_HID}: per-step "
                          "stats as extra ys + one window-level param "
                          "reading; paired order-alternating epochs, "
                          "median diff over median unarmed epoch)",
            "batch_size": H_BATCH,
            "batches_per_epoch": H_NB,
            "steps_per_dispatch": H_K,
            "epoch_s_armed": min(all_h_armed),
            "epoch_s_unarmed": min(all_h_unarmed),
            "epoch_s_armed_all": all_h_armed,
            "epoch_s_unarmed_all": all_h_unarmed,
            "ab_overhead_pct": health_ab_pct,
            "observe_call_ns": observe_ns,
            "window_decode_ns": decode_ns,
            "analytic_overhead_pct": health_analytic_pct,
        },
        "ops_endpoint": {
            "gate_pct": GATE_PCT,
            "gated_path": f"{EPOCHS_PER_WINDOW}-epoch K=8 scan windows "
                          f"vs an out-of-process {SCRAPE_HZ:g} Hz "
                          "/metrics + /healthz scraper (paired means, "
                          "arms alternate order per round)",
            "scrape_hz": SCRAPE_HZ,
            "epochs_per_window": EPOCHS_PER_WINDOW,
            "rounds": OPS_ROUNDS,
            "window_s_scraped_mean": sum(all_scraped) / len(all_scraped),
            "window_s_quiet_mean": sum(all_quiet) / len(all_quiet),
            "window_s_scraped_all": all_scraped,
            "window_s_quiet_all": all_quiet,
            "ab_overhead_pct": opsd_ab_pct,
            "scrapes": stats["scrapes"],
            "scrape_errors": stats["errors"],
            "scrape_bodies_verified": stats["metrics_ok"]
            + stats["healthz_ok"],
            "compile_delta_under_scrape": opsd_compile_delta,
            "gate_overhead_pass": bool(opsd_ab_pct < GATE_PCT),
            "gate_no_compiles_pass": bool(opsd_compile_delta == 0),
            "gate_scrape_ok_pass": bool(opsd_scrape_ok),
        },
    }
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, "telemetry_overhead.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    print(f"wrote {out_path}")

    assert analytic_pct < GATE_PCT, (
        f"disabled telemetry analytic overhead {analytic_pct:.3f}% "
        f">= {GATE_PCT}% gate")
    # the A/B delta is noise-prone on shared machines; report it, and
    # only fail when it is both large and consistent with the analysis
    if ab_overhead_pct > GATE_PCT and analytic_pct > GATE_PCT / 2:
        raise AssertionError(
            f"disabled telemetry A/B overhead {ab_overhead_pct:.3f}% "
            f">= {GATE_PCT}% gate")
    assert flight_analytic_pct < GATE_PCT, (
        f"always-on flight-recorder analytic overhead "
        f"{flight_analytic_pct:.3f}% >= {GATE_PCT}% gate")
    if flight_ab_pct > GATE_PCT and flight_analytic_pct > GATE_PCT / 2:
        raise AssertionError(
            f"flight-recorder A/B overhead {flight_ab_pct:.3f}% "
            f">= {GATE_PCT}% gate")
    # armed tracing pays real work per step (phase clocks + histograms
    # + the boundary block); the same noise discipline applies — the
    # analytic bound is the hard gate, A/B corroborates
    assert armed_analytic_pct < GATE_PCT, (
        f"armed step-attribution analytic overhead "
        f"{armed_analytic_pct:.3f}% >= {GATE_PCT}% gate")
    if armed_ab_pct > GATE_PCT and armed_analytic_pct > GATE_PCT / 2:
        raise AssertionError(
            f"armed step-attribution A/B overhead {armed_ab_pct:.3f}% "
            f">= {GATE_PCT}% gate")
    print(f"OK: analytic {analytic_pct:.4f}% | A/B {ab_overhead_pct:+.2f}%"
          f" (< {GATE_PCT}% gate)")
    print(f"OK: flight ring analytic {flight_analytic_pct:.4f}% | "
          f"A/B {flight_ab_pct:+.2f}% (< {GATE_PCT}% gate)")
    print(f"OK: armed tracing analytic {armed_analytic_pct:.4f}% | "
          f"A/B {armed_ab_pct:+.2f}% (< {GATE_PCT}% gate)")
    # the health plane's in-program stats ride the existing dispatch;
    # the host side is one observe() per batch — same gate split
    assert health_analytic_pct < GATE_PCT, (
        f"training-health analytic overhead {health_analytic_pct:.3f}% "
        f">= {GATE_PCT}% gate")
    if health_ab_pct > GATE_PCT and health_analytic_pct > GATE_PCT / 2:
        raise AssertionError(
            f"training-health A/B overhead {health_ab_pct:.3f}% "
            f">= {GATE_PCT}% gate")
    print(f"OK: train health analytic {health_analytic_pct:.4f}% | "
          f"A/B {health_ab_pct:+.2f}% (< {GATE_PCT}% gate)")
    # ops endpoint: the dispatch path must not notice the scraper —
    # no recompiles, correct scrape bodies, overhead under the gate
    assert opsd_compile_delta == 0, (
        f"fused step recompiled {opsd_compile_delta} program(s) while "
        "being scraped — the ops endpoint touched the dispatch path")
    assert opsd_scrape_ok, (
        f"scrape correctness failed mid-loop: {stats['scrapes']} "
        f"scrapes, {stats['errors']} errors, "
        f"{stats['metrics_ok']}/{stats['healthz_ok']} bodies verified")
    assert opsd_ab_pct < GATE_PCT, (
        f"ops endpoint scrape-load A/B overhead {opsd_ab_pct:.3f}% "
        f">= {GATE_PCT}% gate")
    print(f"OK: ops endpoint A/B {opsd_ab_pct:+.2f}% under "
          f"{stats['scrapes']} scrapes, compile delta "
          f"{opsd_compile_delta} (< {GATE_PCT}% gate)")


if __name__ == "__main__":
    main()
