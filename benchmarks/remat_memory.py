"""Remat-policy memory benchmark at the resnet20 bench point.

Measures what ``MXNET_REMAT_POLICY`` actually buys: the fused train
step's saved-residual bytes (the activations stored between the forward
and backward halves of the one XLA program — ``remat.residual_bytes``,
a pure trace, backend-independent) under each policy, plus the
batch-bucket headroom math: with a budget calibrated to "the ``none``
policy just fits at the bench batch", which larger batch bucket does
each policy admit (``telemetry.memory.batch_headroom``)?

Writes ``benchmarks/results/remat_memory.json``; the tests gate
``all < dots < none`` and bench.py attaches the summary to the BENCH
payload so the r06 measurement records the roofline delta alongside
the kernel-tier selections.

    python benchmarks/remat_memory.py
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402

BATCH = 32
BUCKETS = (32, 64, 128, 256)


def measure(batch=BATCH, num_layers=20, quiet=False):
    """Residual bytes per policy for one resnet20 fused-step binding.
    Returns the result dict (never raises into bench.py)."""
    import mxnet_tpu as mx
    from mxnet_tpu import remat
    from mxnet_tpu.models import resnet
    from mxnet_tpu.telemetry.memory import batch_headroom

    sym = resnet.get_symbol(num_classes=10, num_layers=num_layers,
                            image_shape="3,32,32")
    rng = np.random.RandomState(0)
    imgs = rng.rand(2 * batch, 3, 32, 32).astype(np.float32)
    labels = (rng.rand(2 * batch) * 10).astype(np.float32)

    reports = {}
    for policy in remat.POLICIES:
        remat.set_active(None)
        mx.random.seed(0)
        it = mx.io.NDArrayIter(imgs, labels, batch_size=batch)
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9}, remat=policy)
        rep = mod._exec_group.fused_memory_report()
        reports[policy] = rep
        if not quiet:
            print(f"[remat_memory] {policy:>4}: residual "
                  f"{rep['residual_bytes'] / 1e6:.2f} MB  donate "
                  f"{rep['donated_args']}", file=sys.stderr)
    remat.set_active(None)

    # headroom: budget = fixed + what `none` needs at the bench batch —
    # i.e. exactly the machine the unrematerialized step saturates; the
    # admitted bucket per policy shows the freed bytes becoming batch
    fixed = reports["none"]["param_bytes"] + \
        reports["none"]["state_bytes"]
    per_sample = {p: (r["residual_bytes"] + r["batch_bytes"]) / batch
                  for p, r in reports.items()}
    budget = fixed + per_sample["none"] * batch
    admitted = {p: batch_headroom(budget, fixed, per_sample[p], BUCKETS)
                for p in reports}

    out = {
        "batch": batch,
        "buckets": list(BUCKETS),
        "policies": {p: {
            "residual_bytes": r["residual_bytes"],
            "residual_mb": round(r["residual_bytes"] / 1e6, 3),
            "donated_args": r["donated_args"],
            "admitted_bucket": admitted[p],
        } for p, r in reports.items()},
        "fixed_bytes": int(fixed),
        "budget_bytes": int(budget),
        "residual_ratio_all_vs_none": round(
            reports["all"]["residual_bytes"]
            / max(1, reports["none"]["residual_bytes"]), 4),
        "gate_all_lt_none": bool(reports["all"]["residual_bytes"]
                                 < reports["none"]["residual_bytes"]),
        "gate_dots_lt_none": bool(reports["dots"]["residual_bytes"]
                                  < reports["none"]["residual_bytes"]),
    }
    return out


def main(quiet=False):
    try:
        out = measure(quiet=quiet)
    except Exception as e:      # bench variant must never sink the run
        return {"error": f"{type(e).__name__}: {e}"}
    try:
        results_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "results")
        os.makedirs(results_dir, exist_ok=True)
        with open(os.path.join(results_dir, "remat_memory.json"),
                  "w") as f:
            json.dump(out, f, indent=1)
    except OSError:
        pass
    return out


if __name__ == "__main__":
    print(json.dumps(main(), indent=1))
