"""Perf diagnosis: structural diff of our fused train step vs the flax
referent's, on the compiled TPU executables.

Dumps both optimized-HLO texts, counts the op classes that explain
schedule/fusion gaps (transposes, dtype converts, copies, fusions,
all-reduce), and times targeted program variants (e.g. the fused step
WITHOUT gradient outputs) to attribute the wall-clock difference.

    python benchmarks/perf_diag.py          # needs the TPU (one process!)
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import numpy as np  # noqa: E402

BATCH = 256
NUM_CLASSES = 1000
LR, MOMENTUM = 0.1, 0.9


def hlo_stats(text):
    ops = re.findall(r"^\s*(?:ROOT )?%?[\w.-]+ = [\w\[\]{}, ]* (\w+)\(",
                     text, re.M)
    from collections import Counter
    c = Counter(ops)
    interesting = {k: c[k] for k in
                   ("transpose", "convert", "copy", "fusion", "convolution",
                    "dot", "reduce", "custom-call", "bitcast",
                    "dynamic-update-slice", "all-reduce") if c.get(k)}
    # transposes/converts inside fusions don't show at top level; count
    # them anywhere in the text too
    interesting["transpose_any"] = len(re.findall(r"transpose\(", text))
    interesting["convert_any"] = len(re.findall(r"convert\(", text))
    interesting["copy_any"] = len(re.findall(r"copy\(", text))
    interesting["total_top_level"] = sum(c.values())
    return interesting


from benchmarks.pallas_smoke import _force, _time_median  # noqa: E402


def time_program(fn, reps=10):
    return _time_median(lambda: _force(fn()), reps=reps)


def setup_ours():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    rng = np.random.RandomState(0)
    imgs = rng.rand(BATCH, 3, 224, 224).astype(np.float32)
    labels = (rng.rand(BATCH) * NUM_CLASSES).astype(np.float32)
    sym = resnet.get_symbol(num_classes=NUM_CLASSES, num_layers=50,
                            image_shape="3,224,224")
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)
    mod = mx.mod.Module(sym, context=mx.tpu(), compute_dtype=jnp.bfloat16)
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": LR, "momentum": MOMENTUM})
    assert mod._fused_armed
    eg = mod._exec_group
    exe = eg.executor
    arg_vals = exe._arg_vals()
    w = {nm: arg_vals.pop(nm) for nm in eg._fused_watched}
    lrs, wds = mod._fused_lr_wd()
    lr_arr = jnp.asarray([lrs[nm] for nm in eg._fused_watched],
                         jnp.float32)
    wd_arr = jnp.asarray([wds[nm] for nm in eg._fused_watched],
                         jnp.float32)
    args = (w, arg_vals, exe._aux_vals(), jax.random.PRNGKey(0),
            eg._fused_states, lr_arr, wd_arr)
    return mod, eg, exe, args


def main():
    import jax
    import jax.numpy as jnp

    out = {}
    mod, eg, exe, args = setup_ours()
    w, arg_vals, aux_vals, rng_key, states, lr_arr, wd_arr = args

    # ---- full fused program (donation disabled so we can re-run) ----
    runner = exe._runner
    loss_mask = exe._loss_mask
    watched = eg._fused_watched
    plan_init, plan_update = mod._optimizer.fused_plan()

    def step_full(w, rest, aux_vals, rng, states, lr_arr, wd_arr):
        def f(wv):
            return runner({**rest, **wv}, aux_vals, True, rng)
        outs, vjp_fn, new_aux = jax.vjp(f, w, has_aux=True)
        heads = [jnp.ones(o.shape, o.dtype) if is_loss
                 else jnp.zeros(o.shape, o.dtype)
                 for o, is_loss in zip(outs, loss_mask)]
        (grads,) = vjp_fn(heads)
        new_w, new_states = {}, {}
        for i, nm in enumerate(watched):
            nw, ns = plan_update(w[nm], grads[nm].astype(w[nm].dtype),
                                 states[nm], lr_arr[i], wd_arr[i])
            new_w[nm] = nw
            new_states[nm] = ns
        return outs, new_aux, new_w, new_states, grads

    def step_nograds(w, rest, aux_vals, rng, states, lr_arr, wd_arr):
        outs, new_aux, new_w, new_states, _ = step_full(
            w, rest, aux_vals, rng, states, lr_arr, wd_arr)
        return outs, new_aux, new_w, new_states

    def step_lossonly(w, rest, aux_vals, rng, states, lr_arr, wd_arr):
        outs, new_aux, new_w, new_states, _ = step_full(
            w, rest, aux_vals, rng, states, lr_arr, wd_arr)
        return [jnp.sum(o) for o in outs], new_aux, new_w, new_states

    variants = {}
    for name, fn in (("full", step_full), ("nograds", step_nograds),
                     ("lossonly", step_lossonly)):
        jitted = jax.jit(fn)
        print(f"[diag] compiling ours/{name}", file=sys.stderr, flush=True)
        compiled = jitted.lower(*args).compile()
        if name == "full":
            with open("/tmp/hlo_ours.txt", "w") as f:
                f.write(compiled.as_text())
            out["hlo_ours"] = hlo_stats(compiled.as_text())
        t = time_program(lambda j=jitted: j(*args)[0][0])
        variants[name] = round(t * 1e3, 1)
    out["ours_ms"] = variants

    # ---- flax referent ----
    from benchmarks.flax_resnet50 import make_train_step
    step, init = make_train_step(BATCH, LR, MOMENTUM, NUM_CLASSES)
    state = init(jax.random.PRNGKey(0))
    rngnp = np.random.RandomState(0)
    x = jax.device_put(rngnp.rand(BATCH, 224, 224, 3).astype(np.float32))
    y = jax.device_put((rngnp.rand(BATCH) * NUM_CLASSES).astype(np.int32))
    print("[diag] compiling flax", file=sys.stderr, flush=True)
    compiled = step.lower(state, x, y).compile()
    with open("/tmp/hlo_flax.txt", "w") as f:
        f.write(compiled.as_text())
    out["hlo_flax"] = hlo_stats(compiled.as_text())

    state_box = [state]

    def flax_once():
        state_box[0], loss = step(state_box[0], x, y)
        return loss

    out["flax_ms"] = round(time_program(flax_once) * 1e3, 1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
