#!/usr/bin/env python
"""Disabled fault-plane overhead gate: <1% on the K=8 fused-step point.

The fault-injection plane (mxnet_tpu/faults) promises the telemetry
discipline: when ``MXNET_FAULTS`` is unset (the shipped default), every
``faults.point(...)`` woven through the failure seams costs one
module-global load + one ``is None`` branch — nothing on the training
hot path may get measurably slower. Two measurements back that, on the
SAME benchmark point the dispatch-amortization work is graded on (K=8
``steps_per_dispatch`` scan windows over a prefetching iterator, so the
``io.decode`` seam — the only per-batch point — is actually exercised):

1. **A/B fit timing** — one epoch with the plane disarmed (the shipped
   fast path) vs the same epoch with ``faults.point`` monkeypatched to
   a bare no-op lambda (the cheapest call physically expressible,
   standing in for a build with the plane compiled out). Interleaved
   rounds, min-of-repeats.
2. **Primitive scaling** — the per-call cost of the disarmed ``point()``
   times the measured points-per-batch (counted by arming every known
   point with a never-firing ``prob=0`` trigger for one epoch), divided
   by the disabled batch time. This analytic bound is the asserted
   gate: it must stay < 1%.

Run: JAX_PLATFORMS=cpu python benchmarks/fault_overhead.py
Writes benchmarks/results/fault_overhead.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu.faults import plane as fplane

GATE_PCT = 1.0
K = 8
BATCH = 32
N = 32 * 40          # 40 batches = 5 full K=8 windows per epoch
REPEATS = 5


def build_module():
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(
            mx.sym.Activation(
                mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=64),
                act_type="relu"),
            num_hidden=10),
        name="softmax")
    return mx.mod.Module(net, context=mx.cpu())


def make_iter():
    X = np.random.rand(N, 32).astype("f")
    Y = (np.random.rand(N) * 10).astype("f")
    return mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=BATCH))


def timed_fit(mod, it):
    it.reset()
    t0 = time.perf_counter()
    mod.fit(it, num_epoch=1, steps_per_dispatch=K,
            optimizer_params={"learning_rate": 0.05})
    mx.nd.waitall()
    return time.perf_counter() - t0


def main():
    faults.clear()
    it = make_iter()
    mod = build_module()
    timed_fit(mod, it)                      # warm: bind + compile

    # ---- 1. A/B: disarmed plane vs bare-lambda no-op floor ------------
    # every call site spells the seam `_faults.point(...)` against the
    # package object, so patching the package attribute reaches all of
    # them; fplane.point is patched too for direct importers
    real_point = fplane.point
    noop = lambda *a, **k: None             # noqa: E731
    all_disabled, all_noop = [], []
    timed_fit(mod, it)                      # settle caches
    for _ in range(REPEATS):
        all_disabled.append(timed_fit(mod, it))
        try:
            fplane.point = faults.point = noop
            all_noop.append(timed_fit(mod, it))
        finally:
            fplane.point = faults.point = real_point
    t_disabled, t_noop = min(all_disabled), min(all_noop)
    ab_overhead_pct = (t_disabled / t_noop - 1.0) * 100.0

    # ---- 2. primitive cost x points per batch -------------------------
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        faults.point("bench.unarmed")
    point_ns = (time.perf_counter() - t0) / reps * 1e9

    # count point traversals per batch: arm every known seam with a
    # never-firing trigger and run one epoch
    spec = ";".join(f"{p}:prob=0,seed=0" for p in faults.KNOWN_POINTS)
    nb = N // BATCH
    with faults.scope(spec):
        timed_fit(mod, it)
        points_per_batch = sum(faults.calls().values()) / nb
    batch_s = t_disabled / nb
    analytic_pct = (points_per_batch * point_ns / 1e9 / batch_s) * 100.0

    result = {
        "metric": "fault_plane_disabled_overhead",
        "gate_pct": GATE_PCT,
        "point": f"fused-step K={K}",
        "batches_per_epoch": nb,
        "batch_size": BATCH,
        "repeats": REPEATS,
        "epoch_s_disabled": t_disabled,
        "epoch_s_noop_floor": t_noop,
        "epoch_s_disabled_all": all_disabled,
        "epoch_s_noop_all": all_noop,
        "ab_overhead_pct": ab_overhead_pct,
        "point_call_ns_disabled": point_ns,
        "points_per_batch": points_per_batch,
        "analytic_overhead_pct": analytic_pct,
    }
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, "fault_overhead.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    print(f"wrote {out_path}")

    # stop the prefetch producer before interpreter teardown (it blocks
    # on its bounded queue after the post-epoch reset; a daemon thread
    # killed inside XLA teardown aborts noisily)
    it._stop.set()
    try:
        while True:
            it._queue.get_nowait()
    except Exception:
        pass
    it._thread.join(timeout=2)

    assert analytic_pct < GATE_PCT, (
        f"disabled fault-plane analytic overhead {analytic_pct:.4f}% "
        f">= {GATE_PCT}% gate")
    # the A/B delta is noise-prone on shared machines; report it, and
    # only fail when it is both large and consistent with the analysis
    if ab_overhead_pct > GATE_PCT and analytic_pct > GATE_PCT / 2:
        raise AssertionError(
            f"disabled fault-plane A/B overhead {ab_overhead_pct:.3f}% "
            f">= {GATE_PCT}% gate")
    print(f"OK: analytic {analytic_pct:.5f}% | A/B "
          f"{ab_overhead_pct:+.2f}% (< {GATE_PCT}% gate)")


if __name__ == "__main__":
    main()
