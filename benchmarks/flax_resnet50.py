"""Pure JAX/Flax ResNet-50 training referent for bench.py.

This is the BASELINE.json north-star referent: the throughput a user
would get writing the model directly against the standard JAX stack
(flax.linen + optax), with TPU best practices — NHWC layout, bfloat16
compute over float32 master params, SGD momentum, one fused jitted
train step with donated state. bench.py compares the framework's
Module.fit throughput against this on the same chip / batch / dtype.

Architecture: canonical ResNet-50 v1 (7x7/64/s2 stem, 3-4-6-3
bottleneck stages, expansion 4) — same FLOP class as the framework's
models/resnet.py symbol (reference example/image-classification).
"""
from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

import flax.linen as nn
import optax

STAGE_SIZES = [3, 4, 6, 3]
STAGE_WIDTHS = [64, 128, 256, 512]


class Bottleneck(nn.Module):
    width: int
    stride: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        residual = x
        y = conv(self.width, (1, 1))(x)
        y = nn.relu(bn()(y))
        y = conv(self.width, (3, 3), strides=(self.stride, self.stride),
                 padding=[(1, 1), (1, 1)])(y)
        y = nn.relu(bn()(y))
        y = conv(self.width * 4, (1, 1))(y)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.width * 4, (1, 1),
                            strides=(self.stride, self.stride))(residual)
            residual = bn()(residual)
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5,
                                 dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, (n_blocks, width) in enumerate(zip(STAGE_SIZES,
                                                  STAGE_WIDTHS)):
            for b in range(n_blocks):
                stride = 2 if i > 0 and b == 0 else 1
                x = Bottleneck(width, stride, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def make_train_step(batch_size, learning_rate=0.1, momentum=0.9,
                    num_classes=1000):
    """Returns (jitted_step, initial_state, example_batch_fn).

    state = (params, batch_stats, opt_state); step(state, images,
    labels) -> (new_state, loss) as one donated jitted XLA program.
    """
    model = ResNet50(num_classes=num_classes)
    tx = optax.sgd(learning_rate, momentum=momentum)

    def init(rng):
        variables = model.init(rng, jnp.zeros((1, 224, 224, 3),
                                              jnp.float32), train=False)
        params = variables["params"]
        batch_stats = variables["batch_stats"]
        return params, batch_stats, tx.init(params)

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(labels, num_classes)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        return loss, mutated["batch_stats"]

    def step(state, images, labels):
        params, batch_stats, opt_state = state
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (new_params, new_stats, new_opt), loss

    return jax.jit(step, donate_argnums=(0,)), init
