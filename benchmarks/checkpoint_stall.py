"""Exposed training stall per checkpoint: async vs synchronous write.

The acceptance gate of the async-checkpointing tentpole (ISSUE 9): the
stall a snapshot imposes on the training thread under the async manager
must be < 10% of what the synchronous write costs, at the resnet20
bench point (the same model/batch bench.py's cpu-fallback measures).

Protocol — paired lap on whatever backend the process has:

  * one warmup fit (compiles the fused program; both configurations
    reuse it through the process-wide program cache);
  * SYNC lap: ``CheckpointManager(async_write=False)`` saving every
    batch — the training thread pays capture + device→host + pickle +
    fsync + commit inline; ``ckpt.exposed_stall.seconds`` records it;
  * ASYNC lap: ``async_write=True``, same cadence — the training
    thread pays only the capture dispatch (+ any queue back-pressure);
    the writer thread's cost lands in ``ckpt.snapshot.seconds``.

Writes ``benchmarks/results/checkpoint_stall.json``; ``main(quiet=
True)`` returns the dict for bench.py's ``ckpt`` row.

Run: JAX_PLATFORMS=cpu python benchmarks/checkpoint_stall.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import shutil

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCH = 32
N_BATCHES = 6
CLASSES = 10


def _fit_once(mx, sym, imgs, labels, mgr=None):
    it = mx.io.NDArrayIter(imgs, labels, batch_size=BATCH)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(it, num_epoch=1, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            checkpoint=mgr)
    return mod


def _hist(snap, name):
    rec = snap["histograms"].get(name) or {}
    return rec.get("mean"), rec.get("count", 0)


def main(quiet=False):
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    rng = np.random.RandomState(0)
    imgs = rng.rand(N_BATCHES * BATCH, 3, 32, 32).astype(np.float32)
    labels = (rng.rand(N_BATCHES * BATCH) * CLASSES).astype(np.float32)
    sym = resnet.get_symbol(num_classes=CLASSES, num_layers=20,
                            image_shape="3,32,32")

    def log(msg):
        if not quiet:
            print(f"[checkpoint_stall] {msg}", file=sys.stderr,
                  flush=True)

    log("warmup (compile)")
    _fit_once(mx, sym, imgs, labels)

    root = tempfile.mkdtemp(prefix="ckpt_stall_")
    try:
        mx.telemetry.enable()
        results = {}
        for mode, async_write in (("sync", False), ("async", True)):
            mx.telemetry.reset()
            log(f"{mode} lap: snapshot every batch")
            mgr = mx.checkpoint.CheckpointManager(
                os.path.join(root, mode), keep_last=2,
                async_write=async_write, every_n_batches=1)
            try:
                _fit_once(mx, sym, imgs, labels, mgr=mgr)
                mgr.wait()
            finally:
                mgr.close()
            snap = mx.telemetry.snapshot()
            exposed_mean, n = _hist(snap, "ckpt.exposed_stall.seconds")
            write_mean, _ = _hist(snap, "ckpt.snapshot.seconds")
            results[mode] = {"exposed_stall_s_mean": exposed_mean,
                             "write_s_mean": write_mean,
                             "n_snapshots": n}
        mx.telemetry.disable()
        mx.telemetry.reset()

        # committed checkpoint size (all ranks replicate params, so one
        # directory is representative)
        latest = mx.checkpoint.latest_checkpoint(
            os.path.join(root, "async"))
        nbytes = 0
        if latest:
            for f in os.listdir(latest[1]):
                nbytes += os.path.getsize(os.path.join(latest[1], f))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    sync_exposed = results["sync"]["exposed_stall_s_mean"] or 0.0
    async_exposed = results["async"]["exposed_stall_s_mean"] or 0.0
    ratio = (async_exposed / sync_exposed) if sync_exposed else None
    out = {
        "model": "resnet20_cifar_b32",
        "n_snapshots_per_lap": results["async"]["n_snapshots"],
        "checkpoint_bytes": nbytes,
        "sync_exposed_stall_s_mean": sync_exposed,
        "async_exposed_stall_s_mean": async_exposed,
        "async_write_s_mean": results["async"]["write_s_mean"],
        "exposed_ratio": round(ratio, 4) if ratio is not None else None,
        "gate": "async exposed stall < 10% of the synchronous write",
        "gate_pass": bool(ratio is not None and ratio < 0.10),
    }
    if not quiet:
        print(json.dumps(out, indent=2))
        res_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "results")
        os.makedirs(res_dir, exist_ok=True)
        path = os.path.join(res_dir, "checkpoint_stall.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {path}", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
