"""On-device Pallas kernel smoke: Mosaic-compile and execute the
framework's built-in kernels on the REAL backend, check numerics against
their XLA compositions, and time both.

The reference's ``mx.rtc`` executed nvrtc-compiled kernels on the device
(reference: src/common/mxrtc.cc:1-141); the analog here must likewise be
proven on hardware — interpret-mode CI (the CPU test mesh) cannot catch
Mosaic lowering errors, VMEM overflows, or tiling illegalities. Run on a
TPU host this Mosaic-compiles for real; on CPU it degrades to interpret
mode and says so in the output.

    python benchmarks/pallas_smoke.py
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def _force(x):
    """Force execution through the remote-chip tunnel (device_get of a
    tiny slice completes only after the producing program does)."""
    import jax
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def _time_median(fn, reps=5):
    fn()                                   # warm (compile already done)
    laps = []
    for _ in range(reps):
        tic = time.perf_counter()
        fn()
        laps.append(time.perf_counter() - tic)
    return statistics.median(laps)


def smoke_flash_attention(B=2, H=8, T=2048, D=128, causal=True):
    """Mosaic-compile the flash kernel at a realistic long-context shape
    and check it against the exact XLA attention composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.rtc import flash_attention
    from mxnet_tpu.parallel.ring_attention import attention as xla_attn

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v,
                                                    causal=causal))
    exact = jax.jit(lambda q, k, v: xla_attn(q, k, v, causal=causal))

    out_f = flash(q, k, v)
    out_x = exact(q, k, v)
    err = float(jnp.max(jnp.abs(out_f - out_x)))
    ok = bool(err < 2e-4)

    t_flash = _time_median(lambda: _force(flash(q, k, v)))
    t_xla = _time_median(lambda: _force(exact(q, k, v)))
    return {"ok": ok, "max_abs_err": err, "shape": [B, H, T, D],
            "causal": causal,
            "pallas_ms": round(t_flash * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_sgd_mom(shape=(2048, 1000)):
    """Mosaic-compile the fused SGD-momentum kernel on a ResNet-50-fc-
    sized parameter and check against the XLA composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.rtc import pallas_sgd_mom_update

    lr, momentum, wd = 0.1, 0.9, 1e-4
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    m = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)

    pallas = jax.jit(lambda w, g, m: pallas_sgd_mom_update(
        w, g, m, lr=lr, momentum=momentum, wd=wd))

    def xla(w, g, m):
        gp = g + wd * w
        new_m = momentum * m - lr * gp
        return w + new_m, new_m

    xla = jax.jit(xla)
    wp, mp_ = pallas(w, g, m)
    wx, mx_ = xla(w, g, m)
    err = float(jnp.max(jnp.maximum(jnp.abs(wp - wx), jnp.abs(mp_ - mx_))))
    ok = bool(err < 1e-5)
    t_pallas = _time_median(lambda: _force(pallas(w, g, m)[0]))
    t_xla = _time_median(lambda: _force(xla(w, g, m)[0]))
    return {"ok": ok, "max_abs_err": err, "shape": list(shape),
            "pallas_ms": round(t_pallas * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_softmax_ce(N=None, C=None):
    """Mosaic-compile the fused softmax-CE forward+backward kernels at
    the ResNet-50 head shape and gate against the SoftmaxOutput XLA
    composition (loss-head custom-VJP contract: backward ignores the
    incoming cotangent)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import get_op

    on_tpu = jax.default_backend() == "tpu"
    N = N or (2048 if on_tpu else 64)
    C = C or 1000
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({})
    rng = np.random.RandomState(2)
    d = jnp.asarray(rng.randn(N, C).astype(np.float32))
    lab = jnp.asarray((rng.rand(N) * C).astype(np.float32))

    def loss(fn):
        return lambda dd: fn(attrs, [dd, lab], [], True, None)[0][0].sum()

    xla = jax.jit(jax.grad(loss(sm.forward)))
    pal = jax.jit(jax.grad(loss(sm.variant_fn("pallas"))))
    gx, gp = xla(d), pal(d)
    err = float(jnp.max(jnp.abs(gx - gp)))
    ok = bool(err < 2e-4)
    t_pal = _time_median(lambda: _force(pal(d)))
    t_xla = _time_median(lambda: _force(xla(d)))
    return {"ok": ok, "max_abs_err": err, "shape": [N, C],
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_conv_bn_relu(shape=None):
    """Mosaic-compile the fused conv+BN+ReLU epilogue kernels at a
    ResNet-50 stage shape and gate fwd+aux+grad against the
    Convolution->BatchNorm->ReLU XLA composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import get_op

    on_tpu = jax.default_backend() == "tpu"
    n, c, hw, nf = shape or ((32, 64, 56, 64) if on_tpu
                             else (2, 8, 8, 8))
    cbr = get_op("FusedConvBNReLU")
    attrs = cbr.normalize_attrs(dict(kernel=(3, 3), num_filter=nf,
                                     pad=(1, 1), fix_gamma=False))
    rng = np.random.RandomState(3)
    data = jnp.asarray(rng.randn(n, c, hw, hw).astype(np.float32))
    wgt = jnp.asarray(rng.randn(nf, c, 3, 3).astype(np.float32) * 0.1)
    gam = jnp.asarray(rng.rand(nf).astype(np.float32) + 0.5)
    bet = jnp.asarray(rng.randn(nf).astype(np.float32))
    mm, mv = jnp.zeros(nf, "float32"), jnp.ones(nf, "float32")

    def run(fn):
        def f(d_):
            outs, new_aux = fn(attrs, [d_, wgt, gam, bet], [mm, mv],
                               True, None)
            return outs[0], new_aux
        return jax.jit(f)

    xla, pal = run(cbr.forward), run(cbr.variant_fn("pallas"))
    (yx, ax_), (yp, ap_) = xla(data), pal(data)
    err = float(jnp.max(jnp.abs(yx - yp)))
    err_aux = max(float(jnp.max(jnp.abs(a - b)))
                  for a, b in zip(ax_, ap_))
    ok = bool(err < 2e-4 and err_aux < 2e-4)
    t_pal = _time_median(lambda: _force(pal(data)[0]))
    t_xla = _time_median(lambda: _force(xla(data)[0]))
    return {"ok": ok, "max_abs_err": max(err, err_aux),
            "shape": [n, c, hw, hw], "num_filter": nf,
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_adam(shape=None):
    """Mosaic-compile the fused Adam kernel against the adam_update XLA
    composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import pallas_adam_update

    on_tpu = jax.default_backend() == "tpu"
    shape = shape or ((2048, 1000) if on_tpu else (128, 64))
    rng = np.random.RandomState(4)
    w = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    mean = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
    var = jnp.asarray(np.abs(rng.randn(*shape)).astype(np.float32))
    kw = dict(lr=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=1e-4)

    pallas = jax.jit(lambda *a: pallas_adam_update(*a, **kw))

    def xla(w, g, mean, var):
        gp = g + kw["wd"] * w
        new_mean = kw["beta1"] * mean + (1 - kw["beta1"]) * gp
        new_var = kw["beta2"] * var + (1 - kw["beta2"]) * gp * gp
        new_w = w - kw["lr"] * new_mean / (jnp.sqrt(new_var) +
                                           kw["epsilon"])
        return new_w, new_mean, new_var

    xla = jax.jit(xla)
    outs_p, outs_x = pallas(w, g, mean, var), xla(w, g, mean, var)
    err = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(outs_p, outs_x))
    ok = bool(err < 1e-5)
    t_pal = _time_median(lambda: _force(pallas(w, g, mean, var)[0]))
    t_xla = _time_median(lambda: _force(xla(w, g, mean, var)[0]))
    return {"ok": ok, "max_abs_err": err, "shape": list(shape),
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_layernorm(N=None, C=None):
    """Mosaic-compile the fused LayerNorm fwd + hand-bwd kernels at a
    transformer-block shape and gate value+grad against the LayerNorm
    XLA composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.ops.pallas_kernels import fused_layernorm

    on_tpu = jax.default_backend() == "tpu"
    N = N or (4096 if on_tpu else 64)
    C = C or (1024 if on_tpu else 128)
    ln = get_op("LayerNorm")
    attrs = ln.normalize_attrs({})
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(N, C).astype(np.float32))
    g = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(C).astype(np.float32))

    def loss(fn):
        return lambda xx: (fn(xx) ** 2).sum()

    pal_f = jax.jit(lambda xx: fused_layernorm(xx, g, b)[0])
    xla_f = jax.jit(lambda xx: ln.forward(attrs, [xx, g, b], [],
                                          True, None)[0][0])
    err = float(jnp.max(jnp.abs(pal_f(x) - xla_f(x))))
    pal_g = jax.jit(jax.grad(loss(pal_f)))
    xla_g = jax.jit(jax.grad(loss(xla_f)))
    gerr = float(jnp.max(jnp.abs(pal_g(x) - xla_g(x))))
    ok = bool(err < 2e-4 and gerr < 2e-2)
    t_pal = _time_median(lambda: _force(pal_f(x)))
    t_xla = _time_median(lambda: _force(xla_f(x)))
    return {"ok": ok, "max_abs_err": max(err, gerr), "shape": [N, C],
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_bias_gelu(N=None, C=None):
    """Mosaic-compile the fused bias+GeLU epilogue (fwd + hand dx
    kernel) at an MLP-block shape against the XLA composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import fused_bias_gelu, \
        _bias_gelu_xla

    on_tpu = jax.default_backend() == "tpu"
    N = N or (8192 if on_tpu else 64)
    C = C or (4096 if on_tpu else 128)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(N, C).astype(np.float32))
    b = jnp.asarray(rng.randn(C).astype(np.float32))

    pal = jax.jit(lambda xx, bb: fused_bias_gelu(xx, bb))
    xla = jax.jit(lambda xx, bb: _bias_gelu_xla({}, xx, bb))
    err = float(jnp.max(jnp.abs(pal(x, b) - xla(x, b))))
    pg = jax.jit(jax.grad(lambda xx: (pal(xx, b) ** 2).sum()))
    xg = jax.jit(jax.grad(lambda xx: (xla(xx, b) ** 2).sum()))
    gerr = float(jnp.max(jnp.abs(pg(x) - xg(x))))
    ok = bool(err < 2e-4 and gerr < 2e-3)
    t_pal = _time_median(lambda: _force(pal(x, b)))
    t_xla = _time_median(lambda: _force(xla(x, b)))
    return {"ok": ok, "max_abs_err": max(err, gerr), "shape": [N, C],
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_embedding(N=None, V=None, D=None):
    """Mosaic-compile the scalar-prefetch embedding gather at an
    LM-vocabulary shape against jnp.take, incl. the scatter-add bwd."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import fused_embedding

    on_tpu = jax.default_backend() == "tpu"
    N = N or (8192 if on_tpu else 64)
    V = V or (32768 if on_tpu else 512)
    D = D or (512 if on_tpu else 128)
    rng = np.random.RandomState(7)
    ids = jnp.asarray((rng.rand(N) * V).astype(np.int32))
    w = jnp.asarray(rng.randn(V, D).astype(np.float32))

    pal = jax.jit(lambda ww: fused_embedding(ids, ww))
    xla = jax.jit(lambda ww: jnp.take(ww, ids, axis=0))
    err = float(jnp.max(jnp.abs(pal(w) - xla(w))))
    pg = jax.jit(jax.grad(lambda ww: (pal(ww) ** 2).sum()))
    xg = jax.jit(jax.grad(lambda ww: (xla(ww) ** 2).sum()))
    gerr = float(jnp.max(jnp.abs(pg(w) - xg(w))))
    ok = bool(err == 0.0 and gerr < 1e-4)
    t_pal = _time_median(lambda: _force(pal(w)))
    t_xla = _time_median(lambda: _force(xla(w)))
    return {"ok": ok, "max_abs_err": max(err, gerr), "shape": [N, V, D],
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_int8_dense(M=None, N=None, K=None):
    """Mosaic-compile the int8 dequant-fused dense kernel against its
    f32-dequant XLA composition (the int8 inference tier's hot rung)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.ops.quant import quantize_per_channel

    on_tpu = jax.default_backend() == "tpu"
    M = M or (1024 if on_tpu else 32)
    N = N or (4096 if on_tpu else 64)
    K = K or (4096 if on_tpu else 128)
    qfc = get_op("QuantizedFullyConnected")
    attrs = qfc.normalize_attrs({"num_hidden": N, "no_bias": True})
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(M, K).astype(np.float32))
    wq_np, s_np = quantize_per_channel(rng.randn(N, K).astype(np.float32))
    wq, s = jnp.asarray(wq_np), jnp.asarray(s_np)

    def run(fn):
        return jax.jit(lambda xx: fn(attrs, [xx, wq, s], [], False,
                                     None)[0][0])

    xla, pal = run(qfc.forward), run(qfc.variant_fn("pallas"))
    err = float(jnp.max(jnp.abs(xla(x) - pal(x))))
    ok = bool(err < 2e-2)
    t_pal = _time_median(lambda: _force(pal(x)))
    t_xla = _time_median(lambda: _force(xla(x)))
    return {"ok": ok, "max_abs_err": err, "shape": [M, N, K],
            "pallas_ms": round(t_pal * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


_SMOKES = (("flash_attention", smoke_flash_attention),
           ("sgd_mom_update", smoke_sgd_mom),
           ("adam_update", smoke_adam),
           ("softmax_cross_entropy", smoke_softmax_ce),
           ("fused_conv_bn_relu", smoke_conv_bn_relu),
           ("layernorm", smoke_layernorm),
           ("bias_gelu", smoke_bias_gelu),
           ("embedding", smoke_embedding),
           ("int8_dense", smoke_int8_dense))


def _write_report(res):
    """Per-kernel win/loss vs XLA -> benchmarks/results/ so the tier's
    autotune decisions stay auditable against measured evidence."""
    out = {"backend": res.get("backend"),
           "mosaic_compiled": res.get("mosaic_compiled"), "kernels": {}}
    for name, _fn in _SMOKES:
        rec = res.get(name)
        if not isinstance(rec, dict):
            continue
        row = {k: rec.get(k) for k in ("ok", "max_abs_err", "pallas_ms",
                                       "xla_ms", "shape") if k in rec}
        if rec.get("error"):
            row["error"] = rec["error"]
        if rec.get("pallas_ms") and rec.get("xla_ms"):
            row["winner"] = "pallas" if rec["pallas_ms"] < rec["xla_ms"] \
                else "xla"
            row["speedup_vs_xla"] = round(rec["xla_ms"] /
                                          rec["pallas_ms"], 3)
        out["kernels"][name] = row
    try:
        results_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "results")
        os.makedirs(results_dir, exist_ok=True)
        with open(os.path.join(results_dir, "pallas_kernels.json"),
                  "w") as f:
            json.dump(out, f, indent=1)
    except OSError:
        pass


def run_pallas_smoke():
    """Returns the smoke-result dict (never raises: a Mosaic failure is
    itself the finding, recorded as ok=False + the error)."""
    import jax
    backend = jax.default_backend()
    res = {"backend": backend,
           "mosaic_compiled": backend == "tpu"}   # interpret gate
    for name, fn in _SMOKES:
        try:
            res[name] = fn()
        except Exception as e:
            res[name] = {"ok": False,
                         "error": f"{type(e).__name__}: {e}",
                         "traceback": traceback.format_exc()[-1500:]}
    _write_report(res)
    return res


if __name__ == "__main__":
    print(json.dumps(run_pallas_smoke(), indent=1))
