"""On-device Pallas kernel smoke: Mosaic-compile and execute the
framework's built-in kernels on the REAL backend, check numerics against
their XLA compositions, and time both.

The reference's ``mx.rtc`` executed nvrtc-compiled kernels on the device
(reference: src/common/mxrtc.cc:1-141); the analog here must likewise be
proven on hardware — interpret-mode CI (the CPU test mesh) cannot catch
Mosaic lowering errors, VMEM overflows, or tiling illegalities. Run on a
TPU host this Mosaic-compiles for real; on CPU it degrades to interpret
mode and says so in the output.

    python benchmarks/pallas_smoke.py
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def _force(x):
    """Force execution through the remote-chip tunnel (device_get of a
    tiny slice completes only after the producing program does)."""
    import jax
    return float(np.asarray(jax.device_get(x)).ravel()[0])


def _time_median(fn, reps=5):
    fn()                                   # warm (compile already done)
    laps = []
    for _ in range(reps):
        tic = time.perf_counter()
        fn()
        laps.append(time.perf_counter() - tic)
    return statistics.median(laps)


def smoke_flash_attention(B=2, H=8, T=2048, D=128, causal=True):
    """Mosaic-compile the flash kernel at a realistic long-context shape
    and check it against the exact XLA attention composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.rtc import flash_attention
    from mxnet_tpu.parallel.ring_attention import attention as xla_attn

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v,
                                                    causal=causal))
    exact = jax.jit(lambda q, k, v: xla_attn(q, k, v, causal=causal))

    out_f = flash(q, k, v)
    out_x = exact(q, k, v)
    err = float(jnp.max(jnp.abs(out_f - out_x)))
    ok = bool(err < 2e-4)

    t_flash = _time_median(lambda: _force(flash(q, k, v)))
    t_xla = _time_median(lambda: _force(exact(q, k, v)))
    return {"ok": ok, "max_abs_err": err, "shape": [B, H, T, D],
            "causal": causal,
            "pallas_ms": round(t_flash * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def smoke_sgd_mom(shape=(2048, 1000)):
    """Mosaic-compile the fused SGD-momentum kernel on a ResNet-50-fc-
    sized parameter and check against the XLA composition."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.rtc import pallas_sgd_mom_update

    lr, momentum, wd = 0.1, 0.9, 1e-4
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    m = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)

    pallas = jax.jit(lambda w, g, m: pallas_sgd_mom_update(
        w, g, m, lr=lr, momentum=momentum, wd=wd))

    def xla(w, g, m):
        gp = g + wd * w
        new_m = momentum * m - lr * gp
        return w + new_m, new_m

    xla = jax.jit(xla)
    wp, mp_ = pallas(w, g, m)
    wx, mx_ = xla(w, g, m)
    err = float(jnp.max(jnp.maximum(jnp.abs(wp - wx), jnp.abs(mp_ - mx_))))
    ok = bool(err < 1e-5)
    t_pallas = _time_median(lambda: _force(pallas(w, g, m)[0]))
    t_xla = _time_median(lambda: _force(xla(w, g, m)[0]))
    return {"ok": ok, "max_abs_err": err, "shape": list(shape),
            "pallas_ms": round(t_pallas * 1e3, 2),
            "xla_ms": round(t_xla * 1e3, 2)}


def run_pallas_smoke():
    """Returns the smoke-result dict (never raises: a Mosaic failure is
    itself the finding, recorded as ok=False + the error)."""
    import jax
    backend = jax.default_backend()
    res = {"backend": backend,
           "mosaic_compiled": backend == "tpu"}   # rtc.py interpret gate
    for name, fn in (("flash_attention", smoke_flash_attention),
                     ("sgd_mom_update", smoke_sgd_mom)):
        try:
            res[name] = fn()
        except Exception as e:
            res[name] = {"ok": False,
                         "error": f"{type(e).__name__}: {e}",
                         "traceback": traceback.format_exc()[-1500:]}
    return res


if __name__ == "__main__":
    print(json.dumps(run_pallas_smoke(), indent=1))
