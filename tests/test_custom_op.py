"""CustomOp bridge: python ops inside nd/sym graphs via pure_callback.

reference behavior: python/mxnet/operator.py:396-660 + the standard
Softmax CustomOp example (example/numpy-ops/custom_softmax.py) —
a registered prop must work imperatively, symbolically, and train
inside Module.fit with gradients flowing through the python backward.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], g * y * (1.0 - y))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        return _Sigmoid()


def test_custom_nd():
    x = mx.nd.array(np.array([[0.0, 1.0], [-1.0, 2.0]], np.float32))
    y = mx.nd.Custom(x, op_type="test_sigmoid")
    expect = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y.asnumpy(), expect, rtol=1e-6)


def test_custom_sym_forward_backward():
    data = sym.var("data")
    out = sym.Custom(data, op_type="test_sigmoid", name="sig")
    exe = out.simple_bind(mx.cpu(), grad_req="write", data=(3, 4))
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    exe.arg_dict["data"]._set(x)
    exe.forward(is_train=True)
    y = exe.outputs[0].asnumpy()
    np.testing.assert_allclose(y, 1 / (1 + np.exp(-x)), rtol=1e-6)
    head = np.ones_like(y)
    exe.backward([mx.nd.array(head)])
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               y * (1 - y), rtol=1e-5)


def test_custom_infer_shape_through_graph():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=6, name="fc")
    net = sym.Custom(net, op_type="test_sigmoid", name="sig")
    args, outs, _ = net.infer_shape(data=(5, 3))
    assert outs[0] == (5, 6)


def test_custom_trains_in_module():
    """reference-style gate: a logistic regressor through the python
    sigmoid must fit a separable blob."""
    rng = np.random.RandomState(42)
    n = 200
    x = rng.randn(n, 2).astype(np.float32)
    labels = (x[:, 0] + x[:, 1] > 0).astype(np.float32).reshape(-1, 1)

    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=1, name="fc")
    s = sym.Custom(fc, op_type="test_sigmoid", name="sig")
    # logistic loss via LinearRegressionOutput on the sigmoid (grad = p - y)
    out = sym.LinearRegressionOutput(s, name="lro")

    it = mx.io.NDArrayIter(x, labels, batch_size=20,
                           label_name="lro_label")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("lro_label",), context=mx.cpu())
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="mse",
            initializer=mx.initializer.Uniform(0.5))
    it.reset()
    preds = mod.predict(it).asnumpy().ravel()[:n]
    acc = ((preds > 0.5) == (labels.ravel()[:len(preds)] > 0.5)).mean()
    assert acc > 0.9, f"custom-op logistic regression accuracy {acc}"


def test_legacy_numpy_op():
    """DEPRECATED reference API parity (reference operator.py NumpyOp):
    numpy forward/backward mutated in place, symbol via instance call."""
    class NumpySigmoid(mx.operator.NumpyOp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]]

        def forward(self, in_data, out_data):
            out_data[0][:] = 1.0 / (1.0 + np.exp(-in_data[0]))

        def backward(self, out_grad, in_data, out_data, in_grad):
            y = out_data[0]
            in_grad[0][:] = out_grad[0] * y * (1.0 - y)

    op = NumpySigmoid()
    x = sym.var("x")
    s = op(x, name="legsig")
    exe = s.simple_bind(mx.cpu(), x=(4, 3), grad_req="write")
    xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    exe.arg_dict["x"][:] = xv
    out = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, 1 / (1 + np.exp(-xv)), rtol=1e-5)
    exe.backward([mx.nd.array(np.ones((4, 3), np.float32))])
    expect = out * (1 - out)
    np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(), expect,
                               rtol=1e-5)


def test_legacy_ndarray_op():
    """reference operator.py NDArrayOp: bodies see NDArrays."""
    class NdScale(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0].asnumpy() * 3.0

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0].asnumpy() * 3.0

    op = NdScale()
    x = sym.var("x")
    exe = op(x).simple_bind(mx.cpu(), x=(2, 2), grad_req="write")
    exe.arg_dict["x"][:] = np.ones((2, 2), np.float32)
    out = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, 3.0 * np.ones((2, 2)))
    exe.backward([mx.nd.array(np.full((2, 2), 2.0, np.float32))])
    np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(),
                               np.full((2, 2), 6.0))
