"""Deterministic fault-injection plane + hardened degradation paths
(mxnet_tpu/faults, ISSUE 10).

The acceptance matrix: for each instrumented seam — checkpoint write,
snapshot D2H, kvstore collective, IO decode, serve dispatch — an
injected TRANSIENT fault must recover via its policy (retry / skip /
shed) with bit-identical results where the policy claims transparency,
and an injected PERMANENT fault must degrade along the documented path
(quarantine / DeadWorkerError / breaker-open). All of it runs in
tier-1: no process kills, no wall-clock sleeps, no @slow — the fault
plane plus FakeClock make every path scriptable (docs/faults.md).
"""
import importlib.util
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu.faults import (CircuitBreaker, CircuitOpenError,
                              InjectedFault, RetryPolicy, retry_call)
from mxnet_tpu.serve import FakeClock, QueueFullError, ShedError
from mxnet_tpu.telemetry import metrics as _metrics


def _cval(name, **labels):
    m = _metrics.get_metric(name, **labels)
    return m.value if m is not None else 0


def _fast_policy(attempts=3):
    return RetryPolicy(attempts=attempts, base_s=0.0, jitter=0.0)


# ------------------------------------------------------------- the plane
def _fire_pattern(spec, n=6):
    """Which of n calls to one armed point raise (1-based indices)."""
    hits = []
    with faults.scope(f"p:{spec}"):
        for i in range(1, n + 1):
            try:
                faults.point("p")
            except Exception:
                hits.append(i)
    return hits


def test_trigger_grammar_matrix():
    assert _fire_pattern("nth=3") == [3]
    assert _fire_pattern("once") == [1]
    assert _fire_pattern("always") == [1, 2, 3, 4, 5, 6]
    assert _fire_pattern("every=2") == [2, 4, 6]
    assert _fire_pattern("first=2") == [1, 2]


def test_prob_trigger_seeded_deterministic():
    a = _fire_pattern("prob=0.5,seed=11", n=32)
    b = _fire_pattern("prob=0.5,seed=11", n=32)
    assert a == b and 0 < len(a) < 32      # same seed, same script
    assert _fire_pattern("prob=0", n=16) == []
    assert _fire_pattern("prob=1", n=4) == [1, 2, 3, 4]


def test_error_kinds_and_msg():
    with faults.scope("p:once,error=os,msg=disk full"):
        with pytest.raises(OSError, match="disk full") as ei:
            faults.point("p")
        assert ei.value.mx_fault_point == "p"
    with faults.scope("p:once,error=timeout"):
        with pytest.raises(TimeoutError):
            faults.point("p")
    with faults.scope("p:once"):
        with pytest.raises(InjectedFault):
            faults.point("p")


def test_latency_injection_no_error():
    with faults.scope("p:latency=1ms,first=2") as plane:
        faults.point("p")
        faults.point("p")
        faults.point("p")
        assert faults.fired("p") == 2       # slept twice, raised never


@pytest.mark.parametrize("bad", [
    "noseparator", "p:", "p:nth=0", "p:prob=2", "p:wat=1",
    "p:once;p:always", "p:once,error=bogus", "p:latency=xyz",
])
def test_bad_specs_raise(bad):
    with pytest.raises(mx.base.MXNetError):
        faults.parse_spec(bad)


def test_point_noop_when_disarmed_and_scope_restores():
    assert not faults.enabled()
    faults.point("anything")                # must be a no-op
    with faults.scope("a:once"):
        assert faults.enabled()
        with faults.scope("b:once"):        # nested scope replaces
            assert faults.calls("a") == 0
            with pytest.raises(InjectedFault):
                faults.point("b")
        assert faults.enabled()             # outer restored
        with pytest.raises(InjectedFault):
            faults.point("a")
    assert not faults.enabled()


def test_injection_counter_and_ring():
    before = _cval("faults.injected", point="p")
    with faults.scope("p:always"):
        with pytest.raises(InjectedFault):
            faults.point("p", extra="ctx")
    assert _cval("faults.injected", point="p") == before + 1
    recs = [r for r in mx.telemetry.flightrec.get_records()
            if r.get("kind") == "fault.injected"]
    assert recs and recs[-1]["point"] == "p" and recs[-1]["extra"] == "ctx"


# ----------------------------------------------------------------- retry
def test_retry_policy_backoff_curve():
    p = RetryPolicy(attempts=5, base_s=0.1, multiplier=2.0, max_s=0.5,
                    jitter=0.0)
    assert [p.backoff(k) for k in (1, 2, 3, 4)] == \
        [0.1, 0.2, 0.4, 0.5]                # capped at max_s


def test_retry_success_after_transient_counts():
    site = "t.transient"
    before = _cval("retry.retries", site=site)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("flaky")
        return "ok"

    assert retry_call(flaky, _fast_policy(5), site=site) == "ok"
    assert len(calls) == 3
    assert _cval("retry.retries", site=site) == before + 2


def test_retry_gives_up_after_attempts():
    site = "t.permanent"
    before = _cval("retry.giveups", site=site)
    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("dead")),
                   _fast_policy(3), site=site)
    assert _cval("retry.giveups", site=site) == before + 1


def test_retry_deadline_budget():
    # first backoff (1s) overruns the 0.1s budget: give up after ONE
    # attempt without sleeping
    p = RetryPolicy(attempts=10, base_s=1.0, jitter=0.0, deadline_s=0.1,
                    sleep=lambda s: pytest.fail("must not sleep"))
    calls = []
    with pytest.raises(OSError):
        retry_call(lambda: calls.append(1) or
                   (_ for _ in ()).throw(OSError("x")), p, site="t.dl")
    assert len(calls) == 1


def test_retry_give_up_hook_converts():
    class Hard(Exception):
        pass

    with pytest.raises(Hard) as ei:
        retry_call(lambda: (_ for _ in ()).throw(OSError("soft")),
                   _fast_policy(5), site="t.hook",
                   give_up=lambda exc: Hard("converted"))
    assert isinstance(ei.value.__cause__, OSError)


def test_retry_env_parsing(monkeypatch):
    monkeypatch.setenv("MXNET_RETRY_XYZ",
                       "attempts=7,base=0.25,mult=3,max=9,deadline=60,"
                       "jitter=0")
    p = RetryPolicy.from_env("xyz")
    assert (p.attempts, p.base_s, p.multiplier, p.max_s, p.deadline_s,
            p.jitter) == (7, 0.25, 3.0, 9.0, 60.0, 0.0)
    monkeypatch.setenv("MXNET_RETRY_XYZ", "bogus=1")
    with pytest.raises(mx.base.MXNetError):
        RetryPolicy.from_env("xyz")


# --------------------------------------------------------------- breaker
def test_breaker_state_machine():
    b = CircuitBreaker(threshold=2, cooldown_s=1.0, site="m")
    assert b.acquire(0.0)
    b.record_failure(0.0)
    assert b.state == "closed"              # 1 < threshold
    assert b.acquire(0.1)
    b.record_failure(0.1)
    assert b.state == "open"                # consecutive threshold hit
    assert not b.acquire(0.5)               # cooldown running
    assert not b.admit_allowed(0.5)
    assert b.retry_after(0.5) == pytest.approx(0.6)
    assert b.admit_allowed(1.2)             # probe possible
    assert b.acquire(1.2) and b.state == "half_open"
    assert not b.acquire(1.3)               # single probe in flight
    b.record_failure(1.3)                   # probe failed: open again
    assert b.state == "open" and b.retry_after(1.4) > 0
    assert b.acquire(2.4)                   # next probe
    b.record_success(2.5)
    assert b.state == "closed" and b.consecutive_failures == 0


def test_breaker_success_resets_consecutive():
    b = CircuitBreaker(threshold=3, cooldown_s=1.0)
    for t in (0.0, 0.1):
        b.acquire(t)
        b.record_failure(t)
    b.acquire(0.2)
    b.record_success(0.2)
    b.acquire(0.3)
    b.record_failure(0.3)
    assert b.state == "closed"              # non-consecutive failures


# --------------------------------------------------- seam: ckpt.write/d2h
BATCH, FEATS, CLASSES = 4, 6, 3


def _mlp(prefix="f", dropout=0.0):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=8, name=f"{prefix}1")
    act = mx.sym.Activation(fc, act_type="relu")
    if dropout:
        act = mx.sym.Dropout(act, p=dropout)
    fc2 = mx.sym.FullyConnected(act, num_hidden=CLASSES,
                                name=f"{prefix}2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _fit_mod(ckpt=None, every=2, it=None, prefix="f", seed=7,
             num_epoch=1):
    X = np.random.RandomState(0).rand(6 * BATCH, FEATS).astype("f")
    y = np.random.RandomState(1).randint(
        0, CLASSES, (6 * BATCH,)).astype("f")
    mx.random.seed(seed)
    if it is None:
        it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(prefix), context=mx.cpu())
    rs = np.random.RandomState(2)
    args = {f"{prefix}1_weight": mx.nd.array(
                rs.randn(8, FEATS).astype("f") * 0.1),
            f"{prefix}1_bias": mx.nd.array(np.zeros(8, "f")),
            f"{prefix}2_weight": mx.nd.array(
                rs.randn(CLASSES, 8).astype("f") * 0.1),
            f"{prefix}2_bias": mx.nd.array(np.zeros(CLASSES, "f"))}
    mod.fit(it, num_epoch=num_epoch, arg_params=args,
            optimizer_params={"learning_rate": 0.05},
            checkpoint=ckpt)
    return mod


def test_ckpt_write_transient_retried_commit_intact(tmp_path):
    """nth=1 on ckpt.write: the first attempt fails, the retry commits
    — transparently (the committed state restores bit-identically to
    the module that was saved), with no .tmp- residue."""
    d = str(tmp_path / "ck")
    mgr = mx.checkpoint.CheckpointManager(d, retry_policy=_fast_policy())
    mod = _fit_mod()
    before = _cval("retry.retries", site="ckpt.write")
    with faults.scope("ckpt.write:nth=1"):
        mgr.save(mod, 3, 5, block=True)
    assert _cval("retry.retries", site="ckpt.write") >= before + 1
    assert mgr.latest() is not None
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]
    assert mgr.quarantined == []
    mgr.close()

    # transparency: the retried commit restores bit-for-bit into a
    # module holding unrelated (freshly initialized) params
    mod2 = mx.mod.Module(_mlp("f"), context=mx.cpu())
    mod2.bind([("data", (BATCH, FEATS))], [("softmax_label", (BATCH,))])
    mod2.init_params(mx.initializer.Xavier())
    cursor = mx.checkpoint.restore_module(mod2, d)
    assert cursor == {"epoch": 3, "nbatch": 5}
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_array_equal(a1[k].asnumpy(), a2[k].asnumpy())


def test_ckpt_write_permanent_quarantine_writer_survives(tmp_path):
    """always on ckpt.write: retries exhaust, the seq is quarantined
    (counted + ring-recorded, wait() raises once), the staging dir is
    swept, and the writer thread keeps committing later snapshots."""
    d = str(tmp_path / "ck")
    mgr = mx.checkpoint.CheckpointManager(d, retry_policy=_fast_policy())
    mod = _fit_mod()
    q_before = _cval("ckpt.quarantined")
    f_before = _cval("ckpt.failures")
    with faults.scope("ckpt.write:always"):
        seq = mgr.save(mod, 0, 1)
        with pytest.raises(InjectedFault):
            mgr.wait()
    assert mgr.quarantined == [seq]
    assert mgr.latest() is None
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]
    assert _cval("ckpt.quarantined") == q_before + 1
    assert _cval("ckpt.failures") == f_before + 1
    recs = [r for r in mx.telemetry.flightrec.get_records()
            if r.get("kind") == "ckpt.quarantine"]
    assert recs and recs[-1]["seq"] == seq
    # the writer thread survived: the next save commits normally
    mgr.save(mod, 0, 2, block=True)
    assert mgr.latest() is not None
    mgr.wait()                              # error raised once, cleared
    mgr.close()


def test_ckpt_d2h_transient_retried(tmp_path):
    d = str(tmp_path / "ck")
    mgr = mx.checkpoint.CheckpointManager(d, retry_policy=_fast_policy())
    mod = _fit_mod()
    with faults.scope("ckpt.d2h:nth=1"):
        mgr.save(mod, 1, 0, block=True)
    assert mgr.latest() is not None
    mgr.close()


def test_ckpt_injected_fit_bit_identical(tmp_path):
    """The transparency gate the ISSUE names: a fit whose mid-run
    checkpoint write failed once (and retried) produces the same final
    params AND the same committed checkpoint as an uninjected fit."""
    da, db = str(tmp_path / "a"), str(tmp_path / "b")
    mgr_a = mx.checkpoint.CheckpointManager(da, every_n_batches=2,
                                            retry_policy=_fast_policy())
    mgr_b = mx.checkpoint.CheckpointManager(db, every_n_batches=2,
                                            retry_policy=_fast_policy())
    with faults.scope("ckpt.write:nth=1"):
        mod_a = _fit_mod(ckpt=mgr_a)
        mgr_a.wait()
    mod_b = _fit_mod(ckpt=mgr_b)
    mgr_b.wait()
    a, _ = mod_a.get_params()
    b, _ = mod_b.get_params()
    for k in a:
        np.testing.assert_array_equal(a[k].asnumpy(), b[k].asnumpy())
    # both runs committed the same number of checkpoints (none lost)
    assert len(mgr_a.list_committed()) == len(mgr_b.list_committed())
    mgr_a.close()
    mgr_b.close()


# ------------------------------------------------ seam: kvstore.collective
def test_collective_transient_retry_transparent(monkeypatch):
    monkeypatch.setenv("MXNET_RETRY_COLLECTIVE",
                       "attempts=3,base=0,jitter=0")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.array(np.zeros(5, "f")))
    out = mx.nd.zeros(5)
    before = _cval("retry.retries", site="kvstore.collective")
    with faults.scope("kvstore.collective:nth=1"):
        kv.push("w", mx.nd.array(np.arange(5, dtype="f")))
        kv.pull("w", out=out)
    np.testing.assert_array_equal(out.asnumpy(),
                                  np.arange(5, dtype="f"))
    assert _cval("retry.retries", site="kvstore.collective") >= before + 1
    kv.close()


def test_collective_permanent_dead_peer_raises_deadworker(monkeypatch):
    """Liveness decides: a persistent collective failure with a dead
    peer converts to DeadWorkerError IMMEDIATELY (clean=False) instead
    of burning the retry budget."""
    monkeypatch.setenv("MXNET_RETRY_COLLECTIVE",
                       "attempts=3,base=0,jitter=0")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.array(np.zeros(5, "f")))
    monkeypatch.setattr(kv, "get_dead_nodes",
                        lambda timeout_ms=2000: [2])
    attempts_before = _cval("retry.attempts", site="kvstore.collective")
    with faults.scope("kvstore.collective:always"):
        with pytest.raises(mx.checkpoint.DeadWorkerError) as ei:
            kv.push("w", mx.nd.array(np.ones(5, "f")))
            kv.pull("w", out=mx.nd.zeros(5))
    assert ei.value.dead_ranks == [2] and not ei.value.clean
    # exactly one attempt: the liveness check short-circuits the budget
    assert _cval("retry.attempts",
                 site="kvstore.collective") == attempts_before + 1
    kv.close(abort=True)


def test_collective_permanent_alive_reraises_after_budget(monkeypatch):
    monkeypatch.setenv("MXNET_RETRY_COLLECTIVE",
                       "attempts=2,base=0,jitter=0")
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.array(np.zeros(5, "f")))
    before = _cval("retry.giveups", site="kvstore.collective")
    with faults.scope("kvstore.collective:always"):
        with pytest.raises(InjectedFault):
            kv.push("w", mx.nd.array(np.ones(5, "f")))
            kv.pull("w", out=mx.nd.zeros(5))
    assert _cval("retry.giveups", site="kvstore.collective") == before + 1
    kv.close(abort=True)


# ---------------------------------------------------------- seam: io.decode
def test_io_decode_skip_with_record():
    X = np.arange(24, dtype="f").reshape(6, 4)
    y = np.arange(6, dtype="f")
    before = _cval("io.decode.skipped")
    with faults.scope("io.decode:nth=3"):
        it = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, y, batch_size=1),
                                   on_decode_error="skip")
        rows = [b.data[0].asnumpy()[0, 0] for b in it]
    assert rows == [0.0, 4.0, 12.0, 16.0, 20.0]     # batch 3 skipped
    assert it.skipped_batches == 1
    assert _cval("io.decode.skipped") == before + 1
    recs = [r for r in mx.telemetry.flightrec.get_records()
            if r.get("kind") == "io.decode.skip"]
    assert recs and "InjectedFault" in recs[-1]["error"]


def test_io_decode_raise_is_default():
    X = np.arange(8, dtype="f").reshape(2, 4)
    with faults.scope("io.decode:nth=1"):
        it = mx.io.PrefetchingIter(
            mx.io.NDArrayIter(X, np.zeros(2, "f"), batch_size=1))
        with pytest.raises(InjectedFault):
            for _ in it:
                pass
    with pytest.raises(mx.base.MXNetError):
        mx.io.PrefetchingIter(
            mx.io.NDArrayIter(X, np.zeros(2, "f"), batch_size=1),
            on_decode_error="bogus")


def test_io_decode_consecutive_skip_cap():
    X = np.arange(24, dtype="f").reshape(6, 4)
    with faults.scope("io.decode:always"):
        it = mx.io.PrefetchingIter(
            mx.io.NDArrayIter(X, np.zeros(6, "f"), batch_size=1),
            on_decode_error="skip", max_decode_skip=3)
        with pytest.raises(mx.base.MXNetError,
                           match="consecutive decode failures"):
            for _ in it:
                pass


def test_io_skip_training_equivalence():
    """Skipped-batch bookkeeping is transparent: training through a
    decode failure under the skip policy equals training on the same
    data with that batch REMOVED — bit-identical params."""
    X = np.random.RandomState(3).rand(6 * BATCH, FEATS).astype("f")
    y = np.random.RandomState(4).randint(
        0, CLASSES, (6 * BATCH,)).astype("f")

    def fit(it, seed=5):
        mx.random.seed(seed)
        mod = mx.mod.Module(_mlp("sk"), context=mx.cpu())
        rs = np.random.RandomState(6)
        args = {"sk1_weight": mx.nd.array(
                    rs.randn(8, FEATS).astype("f") * 0.1),
                "sk1_bias": mx.nd.array(np.zeros(8, "f")),
                "sk2_weight": mx.nd.array(
                    rs.randn(CLASSES, 8).astype("f") * 0.1),
                "sk2_bias": mx.nd.array(np.zeros(CLASSES, "f"))}
        mod.fit(it, num_epoch=1, arg_params=args,
                optimizer_params={"learning_rate": 0.05})
        a, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in a.items()}

    with faults.scope("io.decode:nth=3"):       # batch 3 fails decode
        injected = fit(mx.io.PrefetchingIter(
            mx.io.NDArrayIter(X, y, batch_size=BATCH),
            on_decode_error="skip"))
    keep = np.r_[0:2 * BATCH, 3 * BATCH:6 * BATCH]  # drop batch 3's rows
    reference = fit(mx.io.NDArrayIter(X[keep], y[keep],
                                      batch_size=BATCH))
    assert injected.keys() == reference.keys()
    for k in injected:
        np.testing.assert_array_equal(injected[k], reference[k],
                                      err_msg=k)


# ------------------------------------------------------ seam: serve.dispatch
def _serve_module(prefix="sv"):
    mod = mx.mod.Module(_mlp(prefix), context=mx.cpu())
    mod.bind([("data", (4, FEATS))], [("softmax_label", (4,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    return mod


def test_serve_dispatch_transient_failure_keeps_serving():
    clock = FakeClock()
    server = mx.serve.serve(_serve_module(), ladder=[1, 2], start=False,
                            clock=clock, default_deadline_ms=50)
    x = np.random.RandomState(0).rand(1, FEATS).astype("f")
    errors_before = _cval("serve.errors", model="default")
    with faults.scope("serve.dispatch:nth=1"):
        h1 = server.submit({"data": x})
        clock.advance(0.06)
        server.pump()
        assert h1.done() and isinstance(h1.exception(), InjectedFault)
        h2 = server.submit({"data": x})
        clock.advance(0.06)
        server.pump()
    assert h2.done() and h2.exception() is None     # server kept serving
    assert _cval("serve.errors", model="default") == errors_before + 1
    entry = server._registry.entry("default")
    assert entry.breaker.state == "closed"          # 1 < threshold (5)


def test_serve_breaker_opens_probes_and_recovers():
    clock = FakeClock()
    server = mx.serve.serve(_serve_module("bk"), ladder=[1, 2],
                            start=False, clock=clock,
                            default_deadline_ms=50, breaker_threshold=2,
                            breaker_cooldown_ms=1000)
    x = np.random.RandomState(0).rand(1, FEATS).astype("f")
    entry = server._registry.entry("default")
    with faults.scope("serve.dispatch:always"):
        for _ in range(2):                  # two consecutive failures
            h = server.submit({"data": x})
            clock.advance(0.06)
            server.pump()
            assert isinstance(h.exception(), InjectedFault)
    assert entry.breaker.state == "open"
    # open: admission rejected fast with a retry-after hint, and the
    # scheduler wait is bounded by the probe instant
    with pytest.raises(CircuitOpenError) as ei:
        server.submit({"data": x})
    assert 0 < ei.value.retry_after_ms <= 1000
    assert _metrics.get_metric("serve.breaker.state",
                               model="default").value == 2
    # cooldown elapses: the queued request becomes the half-open probe
    clock.advance(1.0)
    h = server.submit({"data": x})
    clock.advance(0.06)
    assert server.pump() == 1
    assert h.done() and h.exception() is None
    assert entry.breaker.state == "closed"
    assert _cval("serve.breaker.transitions", to="open",
                 model="default") >= 1


def test_serve_breaker_failed_probe_reopens():
    clock = FakeClock()
    server = mx.serve.serve(_serve_module("bk2"), ladder=[1],
                            start=False, clock=clock,
                            default_deadline_ms=50, breaker_threshold=1,
                            breaker_cooldown_ms=500)
    x = np.random.RandomState(0).rand(1, FEATS).astype("f")
    entry = server._registry.entry("default")
    with faults.scope("serve.dispatch:always"):
        h = server.submit({"data": x})
        clock.advance(0.06)
        server.pump()
        assert entry.breaker.state == "open"
        clock.advance(0.5)                  # probe window
        h2 = server.submit({"data": x})
        clock.advance(0.06)
        server.pump()                       # probe fails too
        assert isinstance(h2.exception(), InjectedFault)
    assert entry.breaker.state == "open"    # re-opened
    assert entry.breaker.retry_after(clock.now()) > 0


def test_serve_shed_doomed_and_queue_full_backpressure():
    clock = FakeClock()
    server = mx.serve.serve(_serve_module("sh"), ladder=[1, 2],
                            start=False, clock=clock, max_queue=4,
                            shed_watermark=2, default_deadline_ms=50)
    x = np.random.RandomState(0).rand(1, FEATS).astype("f")
    shed_before = _cval("serve.shed", model="default")
    rej_before = _cval("serve.rejected", model="default")
    # two requests whose deadlines expire unserved
    doomed = [server.submit({"data": x}, deadline_ms=10)
              for _ in range(2)]
    clock.advance(5.0)
    # depth at watermark: this admission sheds the doomed first
    h = server.submit({"data": x}, deadline_ms=60000)
    for d in doomed:
        assert d.done() and isinstance(d.exception(), ShedError)
        assert d.exception().retry_after_ms >= 1
    assert _cval("serve.shed", model="default") == shed_before + 2
    assert _cval("serve.rejected", model="default") == rej_before
    clock.advance(60.0)
    server.pump()
    assert h.done() and h.exception() is None   # the viable one served
    # queue full (all viable): rejected with a drain-time hint,
    # counted under serve.rejected, NOT serve.shed
    hs = [server.submit({"data": x}, deadline_ms=600000)
          for _ in range(4)]
    with pytest.raises(QueueFullError) as ei:
        server.submit({"data": x}, deadline_ms=600000)
    assert ei.value.retry_after_ms >= 1
    assert _cval("serve.rejected", model="default") == rej_before + 1
    assert _cval("serve.shed", model="default") == shed_before + 2
    clock.advance(600.0)
    server.pump()
    assert all(hh.exception() is None for hh in hs)


# --------------------------------------------------------- warm restart
def test_serve_warm_restart_zero_compiles(tmp_path):
    """The ROADMAP-5 remainder: kill the server 'process' (abandon the
    object mid-load with queued work), restore from the
    CheckpointManager-managed state, and serve again — zero compiles
    past the warmup mark, bitwise-identical outputs, acked requests
    keeping their results and unacked ones failing loudly."""
    d = str(tmp_path / "serve-ck")
    mod = _serve_module("wr")
    clock = FakeClock()
    server = mx.serve.serve(mod, ladder=[1, 2], start=False,
                            clock=clock, default_deadline_ms=50)
    x = np.random.RandomState(0).rand(1, FEATS).astype("f")
    acked = server.submit({"data": x})
    clock.advance(0.06)
    server.pump()
    ref = acked.result()[0].asnumpy()           # accepted AND acked
    mgr = mx.checkpoint.CheckpointManager(d)
    seq = server.checkpoint_to(mgr)
    assert seq >= 1
    mgr.close()

    # mid-load kill: a request is queued but never dispatched
    unacked = server.submit({"data": x})
    server.stop(drain=False)                    # the 'process dies'
    assert isinstance(unacked.exception(), mx.base.MXNetError)
    assert np.array_equal(acked.result()[0].asnumpy(), ref)

    # restart: rebuild from the committed serve state
    server2 = mx.serve.restore_server(d, clock=FakeClock())
    assert server2.models == ["default"]
    import mxnet_tpu.program_cache as pc
    mark = pc.compile_count()
    h = server2.submit({"data": x})
    server2._clock.advance(0.06)
    server2.pump()
    np.testing.assert_array_equal(h.result()[0].asnumpy(), ref)
    assert pc.compile_count() == mark, \
        "steady-state serving after warm restart must not compile"
    assert server2.stats()["compiles_since_warmup"] == 0


def test_serve_warm_restart_survives_damaged_newest(tmp_path):
    """A truncated newest serve commit falls back to the previous one
    (the same damage-tolerant walk training resume uses)."""
    d = str(tmp_path / "serve-ck")
    server = mx.serve.serve(_serve_module("wd"), ladder=[1],
                            start=False, clock=FakeClock())
    mgr = mx.checkpoint.CheckpointManager(d)
    server.checkpoint_to(mgr)
    server.checkpoint_to(mgr)
    mgr.close()
    committed = mx.checkpoint.CheckpointManager(d).list_committed()
    assert len(committed) == 2
    with open(os.path.join(committed[-1][1], "state.pkl"), "r+b") as f:
        f.truncate(16)                      # damage the newest
    server2 = mx.serve.restore_server(d, clock=FakeClock())
    assert server2.models == ["default"]

    # and a serve payload never restores as training state
    mod = _fit_mod(prefix="wd2")
    assert mx.checkpoint.restore_module(mod, d) is None


def test_restore_server_empty_dir_raises(tmp_path):
    with pytest.raises(mx.base.MXNetError, match="no committed serve"):
        mx.serve.restore_server(str(tmp_path / "empty"))


# ------------------------------------------------------------- diagnose
def _diagnose():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "diagnose_faults_test", os.path.join(root, "tools",
                                             "diagnose.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_diagnose_faults_section_crash_path():
    diagnose = _diagnose()
    report = {
        "type": "crash_report", "time": "t", "pid": 1, "where": "x",
        "metrics": {
            "counters": {
                'faults.injected{point="ckpt.write"}': 3,
                'retry.attempts{site="ckpt.write"}': 5,
                'retry.retries{site="ckpt.write"}': 2,
                'retry.giveups{site="ckpt.write"}': 1,
                'serve.shed{model="m"}': 4,
                'serve.breaker.transitions{model="m",to="open"}': 1,
                "io.decode.skipped": 2,
                "ckpt.quarantined": 1,
            },
            "gauges": {'serve.breaker.state{model="m"}': 2.0},
            "histograms": {}},
        "ring": [{"kind": "fault.injected", "ts_us": 1,
                  "point": "ckpt.write", "call": 1},
                 {"kind": "ckpt.quarantine", "ts_us": 2, "seq": 7,
                  "error": "OSError: disk full"}],
    }
    out = diagnose.render_crash(report)
    assert "faults / degradation:" in out
    assert "injections fired: 3 (ckpt.write x3)" in out
    assert "retries [ckpt.write]: 2 retried over 5 attempts, 1 GAVE UP" \
        in out
    assert "breaker [m]: OPEN (1 trips)" in out
    assert "load shed [m]: 4 request(s)" in out
    assert "decode skips: 2" in out
    assert "1 seq(s) QUARANTINED" in out
    assert "ckpt.quarantine" in out


def test_diagnose_faults_section_jsonl_path(tmp_path):
    diagnose = _diagnose()
    lines = [
        json.dumps({"type": "counter", "name": "faults.injected",
                    "labels": {"point": "io.decode"}, "value": 2}),
        json.dumps({"type": "counter", "name": "retry.retries",
                    "labels": {"site": "kvstore.collective"},
                    "value": 1}),
        json.dumps({"type": "counter", "name": "retry.attempts",
                    "labels": {"site": "kvstore.collective"},
                    "value": 3}),
        json.dumps({"type": "gauge", "name": "serve.breaker.state",
                    "labels": {"model": "m"}, "value": 1.0}),
        json.dumps({"type": "event", "kind": "io.decode.skip",
                    "ts_us": 9, "payload": {}}),
    ]
    out = diagnose.render_jsonl(lines)
    assert "faults / degradation:" in out
    assert "injections fired: 2 (io.decode x2)" in out
    assert "retries [kvstore.collective]: 1 retried over 3 attempts" \
        in out
    assert "breaker [m]: half-open" in out


def test_diagnose_no_faults_section_when_clean():
    diagnose = _diagnose()
    report = {"type": "crash_report", "time": "t", "pid": 1,
              "where": "x", "metrics": {"counters": {}, "gauges": {},
                                        "histograms": {}}, "ring": []}
    assert "faults / degradation" not in diagnose.render_crash(report)
