"""Chaos worker: dist training that survives a mid-epoch peer kill.

The end-to-end composition of the recovery story (ISSUE 9 tentpole c):
N workers train over dist_sync with async checkpointing + elastic mode;
one worker ``os._exit``s mid-epoch (no shutdown, no goodbye — the
heartbeat layer and the survivors' broken collectives are the only
signals). Survivors must:

  save (their managers' last committed checkpoint is already on disk;
  a boundary detection also cuts an emergency one)
  -> raise ``DeadWorkerError`` instead of hanging
  -> re-exec themselves over the survivor cluster
     (``checkpoint.reexec_survivor``: n-1 workers, remapped ranks,
     generation-bumped coordinator port)
  -> resume from the last committed checkpoint and train to completion.

Identity contract: ``CHAOS_STABLE_ID`` (set once by the launcher) keys
each worker's data shard and checkpoint directory, so both survive the
rank remapping — after the re-form, old rank 2 may be new rank 1 but
still trains its own shard from its own checkpoints.

Markers on stdout (the test greps these): ``CHAOS_START``,
``CHAOS_DEAD_SEEN`` (detection), ``CHAOS_DONE`` (final metrics).
Exit codes: 0 success, 17 the planned kill, anything else a bug.

Fleet forensics feed: with ``CHAOS_TELEMETRY_DIR`` set, telemetry is
enabled and every batch overwrites this worker's per-rank jsonl dump
(``rank<stable>_gen<g>.jsonl``) — so the doomed worker leaves a dump
frozen at its kill point, survivors' generation-0 dumps capture the
``dead_node`` detection, and their generation-1 dumps show the re-formed
run. ``CHAOS_DONE`` also writes a ``fleet<stable>.json`` registry
snapshot (taken while the kvstore is still live, so rank identity comes
from the dist plane) for the cross-rank merge assertions. The test
feeds all of it to ``tools/fleetstat.py``.
"""
import hashlib
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import mxnet_tpu as mx  # noqa: E402


def _net():
    data = mx.sym.var("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=16,
                                                name="fc1"),
                          act_type="relu")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=2,
                                                      name="fc2"),
                                name="softmax")


def main():
    stable_id = int(os.environ["CHAOS_STABLE_ID"])
    kill_id = int(os.environ.get("CHAOS_KILL_STABLE_ID", "-1"))
    kill_at = os.environ.get("CHAOS_KILL_AT", "")   # "epoch:batch"
    num_epoch = int(os.environ.get("CHAOS_EPOCHS", "4"))
    gen = mx.checkpoint.recovery_generation()

    telemetry_dir = os.environ.get("CHAOS_TELEMETRY_DIR", "")
    jsonl_path = None
    if telemetry_dir:
        os.makedirs(telemetry_dir, exist_ok=True)
        jsonl_path = os.path.join(telemetry_dir,
                                  f"rank{stable_id}_gen{gen}.jsonl")
        mx.telemetry.enable()

    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    print(f"CHAOS_START stable={stable_id} rank={rank} "
          f"nworker={nworker} gen={gen}", flush=True)

    # per-worker shard of the planted-signal task, keyed by the STABLE
    # id: the shard follows the worker through re-forms
    rng = np.random.RandomState(100 + stable_id)
    n = 256
    X = rng.rand(n, 16).astype("f")
    y = (X[:, 3] > 0.5).astype("f")
    X[:, 0] = y * 3.0
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False)

    mod = mx.mod.Module(_net(), context=mx.cpu())
    mgr = mx.checkpoint.CheckpointManager(
        os.environ["MXNET_CKPT_DIR"], every_n_batches=2)

    kill_tuple = None
    if gen == 0 and kill_at:
        ep, nb = kill_at.split(":")
        kill_tuple = (int(ep), int(nb))
    pause_s = float(os.environ.get("CHAOS_PAUSE_S", "0"))

    def cb(p):
        # dump BEFORE the kill check: the doomed worker's last dump is
        # its state at the kill batch — the stale file whose wall-clock
        # gap the fleet report surfaces as the death timeline
        if jsonl_path:
            mx.telemetry.jsonl.dump(jsonl_path)
        if kill_tuple is not None and (p.epoch, p.nbatch) == kill_tuple:
            if stable_id == kill_id:
                print(f"CHAOS_KILL stable={stable_id} at "
                      f"epoch={p.epoch} nbatch={p.nbatch}", flush=True)
                os._exit(17)    # die without any shutdown: pure chaos
            # survivors idle past the heartbeat horizon so detection
            # lands BEFORE their next collective — the clean boundary
            # path. (A post-death collective is a gloo coin flip:
            # usually a fast error the patience path converts, but it
            # can hang — wedged watchdog — or hard-abort the process,
            # which nothing in-process can survive.)
            if pause_s:
                time.sleep(pause_s)

    def epoch_cb(epoch, sym, arg, aux):
        pass

    try:
        mod.fit(it, num_epoch=num_epoch, kvstore=kv,
                initializer=mx.initializer.Xavier(rnd_type="uniform",
                                                  magnitude=2),
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9},
                batch_end_callback=cb, epoch_end_callback=epoch_cb,
                checkpoint=mgr, resume=(gen > 0), elastic=True)
    except mx.checkpoint.DeadWorkerError as e:
        print(f"CHAOS_DEAD_SEEN stable={stable_id} rank={rank} "
              f"dead={e.dead_ranks} clean={e.clean}", flush=True)
        if jsonl_path:
            # the detection-time dump: carries the dead_node event and
            # the recovery.* counters this survivor recorded
            mx.telemetry.jsonl.dump(jsonl_path)
        mgr.close()                 # last commits must land before exec
        kv.close(abort=True)        # drop grads staged at the dead peer
        mx.checkpoint.reexec_survivor(e.dead_ranks)
        raise AssertionError("reexec_survivor returned")  # unreachable

    args, _ = mod.get_params()
    digest = hashlib.sha1()
    for nm in sorted(args):
        digest.update(np.ascontiguousarray(
            np.round(args[nm].asnumpy().astype(np.float64), 5)).tobytes())
    acc = mod.score(it, "acc")[0][1]
    if jsonl_path:
        mx.telemetry.jsonl.dump(jsonl_path)
        # registry snapshot while the kvstore is still live — rank
        # identity must come from the dist plane, not the env fallback
        with open(os.path.join(telemetry_dir,
                               f"fleet{stable_id}.json"), "w") as f:
            json.dump(mx.telemetry.fleet.snapshot(), f)
    mgr.close()
    kv.close()
    print(f"CHAOS_DONE stable={stable_id} rank={rank} gen={gen} "
          f"nworker={nworker} acc={acc:.3f} "
          f"params={digest.hexdigest()[:16]}", flush=True)
    assert acc > 0.8, f"stable {stable_id} failed to learn: {acc}"


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # surface the failure on stdout so the test's wedge/failure
        # diagnostics capture it even when stderr is lost
        print(f"CHAOS_ERROR stable={os.environ.get('CHAOS_STABLE_ID')}",
              flush=True)
        traceback.print_exc()
        raise
