"""Continuous decode batching (ISSUE 15, ROADMAP 3b).

Pins the tentpole end to end: the per-slot ``attention_decode``
lowering ((B, 1) cursor vector, per-slot masked softmax, one-hot slot
writes), the ``BatchedKVCacheDecoder`` driver (staggered sequences
reproduce independent ``KVCacheDecoder`` runs, bit-clean slot reuse,
host-side per-slot overflow), the ``DecodeScheduler`` (FakeClock-
deterministic staggered arrivals/finishes, streaming delivery,
EOS/max-new/deadline retirement, an overflowing slot failing alone),
and the zero-steady-state-compile contract: ``compile_count()`` delta
== 0 across arbitrary join/leave at every slot rung, including rung
migrations. Satellites ride along: slot-pooled export artifacts
(``Predictor.reset_slot``), memplan's slot-pool KV bytes + an ME801
trip at a toy capacity x slot count, and the telemetry surface.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.models import transformer as tfm
from mxnet_tpu.ops.registry import get_op
from mxnet_tpu.serve import FakeClock, QueueFullError

V, D, L, H, T = 64, 32, 2, 4, 16      # tiny LM; T doubles as capacity


@pytest.fixture(scope="module")
def trained():
    """One trained parameter set shared by every pool/reference pair."""
    sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L, n_head=H,
                         seq_len=8, include_loss=False, max_seq_len=T)
    mod = mx.mod.Module(sym, label_names=[])
    mod.bind([("data", (1, 8))], None, for_training=False)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          magnitude=2))
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def _args_nd(trained):
    return {k: mx.nd.array(v) for k, v in trained.items()}


def _pooled_module(trained, slots, compute_dtype=None,
                   pos_embed="rotary", capacity=T):
    dec = mx.mod.Module(
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, capacity=capacity,
                              per_slot=True, pos_embed=pos_embed,
                              max_seq_len=capacity),
        data_names=("data", "pos_ids") if pos_embed == "learned"
        else ("data",), label_names=[], compute_dtype=compute_dtype)
    shapes = [("data", (slots, 1))] + (
        [("pos_ids", (slots, 1))] if pos_embed == "learned" else [])
    dec.bind(shapes, None, for_training=False)
    dec.init_params(initializer=None, arg_params=_args_nd(trained),
                    aux_params={}, allow_missing=True)
    return dec


def _scalar_decoder(trained, compute_dtype=None, pos_embed="rotary",
                    capacity=T):
    m = mx.mod.Module(
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, capacity=capacity,
                              pos_embed=pos_embed,
                              max_seq_len=capacity),
        data_names=("data", "pos_ids") if pos_embed == "learned"
        else ("data",), label_names=[], compute_dtype=compute_dtype)
    shapes = [("data", (1, 1))] + ([("pos_ids", (1,))]
                                   if pos_embed == "learned" else [])
    m.bind(shapes, None, for_training=False)
    m.init_params(initializer=None, arg_params=_args_nd(trained),
                  aux_params={}, allow_missing=True)
    return tfm.KVCacheDecoder(m, capacity=capacity, pos_embed=pos_embed)


def _ref_logits(trained, tokens, **kw):
    """Per-step logits of ONE sequence through the scalar decoder."""
    d = _scalar_decoder(trained, **kw)
    return [d.step(np.asarray([[t]], np.int32)).asnumpy()[0, 0]
            for t in tokens]


def _ref_greedy(trained, prompt, n, **kw):
    d = _scalar_decoder(trained, **kw)
    for t in prompt[:-1]:
        d.step(np.asarray([[t]], np.int32))
    cur, out = int(prompt[-1]), []
    for _ in range(n):
        lg = d.step(np.asarray([[cur]], np.int32)).asnumpy()[0, 0]
        cur = int(np.argmax(lg))
        out.append(cur)
    return out


_sched_seq = [0]


def _sched(trained, ladder, clock=None, pos_embed="rotary",
           compute_dtype=None, capacity=T, name=None, **kw):
    sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                n_head=H, capacity=capacity,
                                per_slot=True, pos_embed=pos_embed,
                                max_seq_len=capacity)
    # unique engine name per scheduler: the serve.decode.* counters are
    # process-global per model label, so stats() stays per-instance
    _sched_seq[0] += 1
    eng = mx.serve.DecodeEngine(name or f"lmdec{_sched_seq[0]}", sym,
                                _args_nd(trained), capacity=capacity,
                                ladder=ladder,
                                compute_dtype=compute_dtype)
    return mx.serve.DecodeScheduler(
        eng, clock=clock if clock is not None else FakeClock(), **kw)


# ================================================ per-slot op lowering
def test_per_slot_infer_shape_and_cursor_binding():
    sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                n_head=H, capacity=T, per_slot=True)
    _args, outs, auxs = sym.infer_shape(data=(4, 1))
    assert outs == [(4, 1, V)]
    by_name = dict(zip(sym.list_auxiliary_states(), auxs))
    cursors = {n: s for n, s in by_name.items()
               if n.endswith("cache_pos")}
    assert len(cursors) == L
    assert set(cursors.values()) == {(4, 1)}       # per-slot vector
    caches = {n: s for n, s in by_name.items() if n.endswith("k_cache")}
    assert set(caches.values()) == {(4, H, T, D // H)}


def test_per_slot_cursor_binds_int32(trained):
    dec = _pooled_module(trained, slots=3, compute_dtype="bfloat16")
    exe = dec._exec_group.executor
    cursors = [nm for nm in exe.aux_dict if nm.endswith("cache_pos")]
    assert cursors
    for nm in cursors:
        cell = exe.aux_dict[nm]
        assert cell.asjax().dtype == jnp.int32
        assert tuple(cell.shape) == (3, 1)


def test_per_slot_window_lowering():
    """S>1 per-slot windows (ISSUE 18): each slot writes S cache rows
    at its own cursor, the causal mask staggers per slot, and the
    cursor vector advances by S."""
    sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=1,
                                n_head=H, per_slot=True, step_len=2)
    _args, outs, _auxs = sym.infer_shape(data=(4, 2))
    assert outs == [(4, 2, V)]
    op = get_op("attention_decode")
    rs = np.random.RandomState(3)
    B, Hh, S, Dh, C = 2, 1, 2, 4, 8
    q, k, v = (jnp.asarray(rs.randn(B, Hh, S, Dh).astype(np.float32))
               for _ in range(3))
    kc = jnp.asarray(rs.randn(B, Hh, C, Dh).astype(np.float32))
    vc = jnp.asarray(rs.randn(B, Hh, C, Dh).astype(np.float32))
    cur = jnp.asarray([[0], [3]], jnp.int32)
    outs, auxs = op.forward({"capacity": C, "per_slot": True},
                            [q, k, v], [kc, vc, cur], False, None)
    k2, v2, cur2 = auxs
    assert np.array_equal(np.asarray(cur2), [[2], [5]])
    # slot 0 wrote rows 0..1, slot 1 rows 3..4; everything else intact
    assert np.array_equal(np.asarray(k2[0, :, :2]), np.asarray(k[0]))
    assert np.array_equal(np.asarray(k2[1, :, 3:5]), np.asarray(k[1]))
    assert np.array_equal(np.asarray(k2[0, :, 2:]),
                          np.asarray(kc[0, :, 2:]))
    assert np.array_equal(np.asarray(v2[1, :, :3]),
                          np.asarray(vc[1, :, :3]))


def test_per_slot_eager_overflow_names_slots():
    op = get_op("attention_decode")
    q = jnp.zeros((3, 1, 1, 4))
    cache = jnp.zeros((3, 1, 4, 4))
    cur = jnp.asarray([[4], [1], [4]], jnp.int32)
    with pytest.raises(mx.base.MXNetError, match=r"slot\(s\) \[0, 2\]"):
        op.forward({"capacity": 4, "per_slot": True}, [q, q, q],
                   [cache, cache, cur], False, None)


def test_rope_per_batch_positions():
    """rope_apply over (B, T) positions == per-row application of the
    (T,) path at each row's positions."""
    from mxnet_tpu.ops.nn import rope_apply
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(3, 2, 1, 8).astype(np.float32))
    pos = jnp.asarray([[5], [0], [11]], jnp.int32)
    got = rope_apply(x, pos)
    for b in range(3):
        ref = rope_apply(x[b:b + 1], pos[b])
        np.testing.assert_array_equal(np.asarray(got[b:b + 1]),
                                      np.asarray(ref))


# ============================================== batched driver parity
@pytest.mark.parametrize("compute_dtype,tol", [
    (None, 2e-6), ("bfloat16", 2e-2)])
def test_staggered_batched_decode_matches_independent(trained,
                                                      compute_dtype,
                                                      tol):
    """Acceptance (parity gate): SLOTS sequences decoded concurrently
    with staggered join/leave reproduce per-sequence KVCacheDecoder
    outputs — f32 ~1e-6, bf16 2e-2 — including a slot reused by a
    later sequence."""
    slots = 3
    dec = _pooled_module(trained, slots, compute_dtype=compute_dtype)
    drv = tfm.BatchedKVCacheDecoder(dec, capacity=T)
    rs = np.random.RandomState(1)
    seqs = [rs.randint(0, V, 6).astype(np.int32) for _ in range(4)]
    refs = [_ref_logits(trained, s, compute_dtype=compute_dtype)
            for s in seqs]

    got = {i: [] for i in range(4)}
    live = {}                       # slot -> [seq_index, next_pos]
    joins = {0: (0, 0), 2: (1, 1), 3: (2, 2)}   # iteration -> (seq, slot)
    for it in range(64):
        if it in joins:
            si, slot = joins[it]
            drv.join(slot)
            live[slot] = [si, 0]
        if not live:
            break
        toks = np.zeros((slots, 1), np.int32)
        for slot, (si, k) in live.items():
            toks[slot, 0] = seqs[si][k]
        out = drv.step(toks).asnumpy()
        for slot, (si, k) in list(live.items()):
            got[si].append(out[slot, 0])
            live[slot][1] += 1
            if live[slot][1] >= len(seqs[si]):
                drv.leave(slot)
                del live[slot]
                if si == 0:         # slot reuse mid-flight
                    drv.join(slot)
                    live[slot] = [3, 0]
    for i in range(4):
        assert len(got[i]) == len(seqs[i])
        for t in range(len(seqs[i])):
            np.testing.assert_allclose(
                np.asarray(got[i][t], np.float32),
                np.asarray(refs[i][t], np.float32),
                rtol=tol, atol=tol, err_msg=f"seq {i} step {t}")


def test_slot_reuse_is_bit_clean(trained):
    """A sequence decoded in a slot that previously held (and retired)
    another sequence is BITWISE identical to the same sequence on a
    fresh pool — the masked softmax zeroes stale positions exactly."""
    slots = 2
    rs = np.random.RandomState(2)
    a = rs.randint(0, V, T).astype(np.int32)        # fills the slot
    b = rs.randint(0, V, 5).astype(np.int32)

    dec1 = _pooled_module(trained, slots)
    drv1 = tfm.BatchedKVCacheDecoder(dec1, capacity=T)
    drv1.join(0)
    for t in range(T):
        drv1.step(np.asarray([[a[t]], [0]], np.int32))
    drv1.leave(0)
    drv1.join(0)                                    # reuse
    reused = [drv1.step(np.asarray([[tok], [0]], np.int32))
              .asnumpy()[0, 0] for tok in b]

    dec2 = _pooled_module(trained, slots)
    drv2 = tfm.BatchedKVCacheDecoder(dec2, capacity=T)
    drv2.join(0)
    fresh = [drv2.step(np.asarray([[tok], [0]], np.int32))
             .asnumpy()[0, 0] for tok in b]
    for t in range(len(b)):
        np.testing.assert_array_equal(reused[t], fresh[t])


def test_driver_overflow_raises_before_dispatch(trained):
    """Satellite: the host-side per-slot overflow check — the pinned
    program can never see a concrete cursor, so the driver raises
    BEFORE dispatch, naming the slot, and batchmates are untouched."""
    dec = _pooled_module(trained, 2)
    drv = tfm.BatchedKVCacheDecoder(dec, capacity=T)
    drv.join(0)
    drv.join(1)
    toks = np.zeros((2, 1), np.int32)
    for _ in range(T):
        drv.step(toks)
    with pytest.raises(mx.base.MXNetError, match=r"slot\(s\) \[0, 1\]"):
        drv.step(toks)
    # retiring the overflowing slot unblocks its batchmate... which
    # here means retiring 0 still leaves 1 overflowing
    drv.leave(0)
    with pytest.raises(mx.base.MXNetError, match=r"slot\(s\) \[1\]"):
        drv.step(toks)
    drv.leave(1)
    drv.join(0)                     # fresh sequence decodes fine
    out = drv.step(toks)
    assert out.shape == (2, 1, V)


def test_learned_positions_per_slot(trained):
    """Per-slot pos_ids feed: staggered learned-position decode matches
    the scalar driver."""
    sym = tfm.get_symbol(vocab_size=V, d_model=D, n_layer=1, n_head=H,
                         seq_len=8, include_loss=False,
                         pos_embed="learned", max_seq_len=T)
    mod = mx.mod.Module(sym, label_names=[])
    mod.bind([("data", (1, 8))], None, for_training=False)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          magnitude=2))
    args, _ = mod.get_params()
    args = {k: v.asnumpy() for k, v in args.items()}

    def scalar_ref(tokens):
        m = mx.mod.Module(
            tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=1,
                                  n_head=H, capacity=T,
                                  pos_embed="learned", max_seq_len=T),
            data_names=("data", "pos_ids"), label_names=[])
        m.bind([("data", (1, 1)), ("pos_ids", (1,))], None,
               for_training=False)
        m.init_params(initializer=None,
                      arg_params={k: mx.nd.array(v)
                                  for k, v in args.items()},
                      aux_params={}, allow_missing=True)
        d = tfm.KVCacheDecoder(m, capacity=T, pos_embed="learned")
        return [d.step(np.asarray([[t]], np.int32)).asnumpy()[0, 0]
                for t in tokens]

    dec = mx.mod.Module(
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=1,
                              n_head=H, capacity=T, per_slot=True,
                              pos_embed="learned", max_seq_len=T),
        data_names=("data", "pos_ids"), label_names=[])
    dec.bind([("data", (2, 1)), ("pos_ids", (2, 1))], None,
             for_training=False)
    dec.init_params(initializer=None,
                    arg_params={k: mx.nd.array(v)
                                for k, v in args.items()},
                    aux_params={}, allow_missing=True)
    drv = tfm.BatchedKVCacheDecoder(dec, capacity=T,
                                    pos_embed="learned")
    rs = np.random.RandomState(3)
    s0 = rs.randint(0, V, 5).astype(np.int32)
    s1 = rs.randint(0, V, 4).astype(np.int32)
    r0, r1 = scalar_ref(s0), scalar_ref(s1)
    drv.join(0)
    got0, got1 = [], []
    for it in range(7):
        if it == 2:
            drv.join(1)             # staggered: slot 1 two steps later
        toks = np.zeros((2, 1), np.int32)
        if it < len(s0):
            toks[0, 0] = s0[it]
        if 2 <= it < 2 + len(s1):
            toks[1, 0] = s1[it - 2]
        out = drv.step(toks).asnumpy()
        if it < len(s0):
            got0.append(out[0, 0])
        if 2 <= it < 2 + len(s1):
            got1.append(out[1, 0])
    for got, ref in ((got0, r0), (got1, r1)):
        for t in range(len(ref)):
            np.testing.assert_allclose(got[t], ref[t], rtol=1e-5,
                                       atol=2e-6)


# ========================================== scheduler (FakeClock path)
def test_scheduler_staggered_arrivals_deterministic(trained):
    """Acceptance: FakeClock-scripted staggered arrivals/finishes —
    batched greedy outputs match N independent KVCacheDecoder runs,
    and a rerun of the same script is bit-identical."""
    rs = np.random.RandomState(4)
    prompts = [rs.randint(0, V, 2 + i % 3).tolist() for i in range(6)]
    lens = [3 + i % 4 for i in range(6)]

    def run():
        clock = FakeClock()
        sched = _sched(trained, ladder=[1, 2, 4], clock=clock)
        outs = [None] * 6
        hs = []
        for i, p in enumerate(prompts):
            hs.append(sched.submit(p, max_new_tokens=lens[i]))
            sched.pump(max_iterations=1 + i % 2)   # staggered progress
            clock.advance(0.001)
        sched.pump()
        for i, h in enumerate(hs):
            outs[i] = list(h.result(timeout=5))
            assert h.finish_reason == "length"
        # stats snapshot NOW: compile_count is process-global, and the
        # reference decoders bound below compile their own programs
        return outs, sched.stats()

    outs, st = run()
    assert st["responses"] == 6 and st["errors"] == 0
    assert st["compiles_since_warmup"] == 0
    for i, p in enumerate(prompts):
        assert outs[i] == _ref_greedy(trained, p, lens[i]), i
    outs2, st2 = run()
    assert outs2 == outs                       # deterministic replay
    assert st2["responses"] == 6 and st2["compiles_since_warmup"] == 0


def test_zero_compiles_across_join_leave_every_rung(trained):
    """Acceptance: compile_count() delta == 0 after warmup across
    arbitrary join/leave on every slot rung, including the rung
    migrations the churn forces."""
    sched = _sched(trained, ladder=[1, 2, 4])
    assert sched.engine.warmup_compiles >= 3      # one per rung
    mark = mx.program_cache.compile_count()
    rs = np.random.RandomState(5)
    # wave 1: single sequence (rung 1)
    h = sched.submit(rs.randint(0, V, 2).tolist(), max_new_tokens=2)
    sched.pump()
    # wave 2: four at once (grow 1 -> 4), retire down through 2 -> 1
    hs = [sched.submit(rs.randint(0, V, 2).tolist(),
                       max_new_tokens=2 + i) for i in range(4)]
    sched.pump()
    # wave 3: churn — overlapping arrivals while others finish
    for i in range(5):
        hs.append(sched.submit(rs.randint(0, V, 2).tolist(),
                               max_new_tokens=3))
        sched.pump(max_iterations=2)
    sched.pump()
    for hh in [h] + hs:
        hh.result(timeout=5)
    assert mx.program_cache.compile_count() - mark == 0
    assert sched.engine.compiles_since_warmup() == 0
    assert sched.stats()["migrations"] >= 2
    assert sched.engine.programs_resident()
    # every rung's program stayed pinned
    assert len(sched.engine.program_keys()) == 3


def test_scheduler_overflow_fails_alone(trained):
    """Satellite: a sequence overflowing its slot's cache slice errors
    ALONE — its batchmates' outputs are unaffected."""
    sched = _sched(trained, ladder=[2])
    rs = np.random.RandomState(6)
    long_prompt = rs.randint(0, V, T).tolist()     # fills capacity
    ok_prompt = rs.randint(0, V, 3).tolist()
    h_over = sched.submit(long_prompt, max_new_tokens=8)
    h_ok = sched.submit(ok_prompt, max_new_tokens=4)
    sched.pump()
    with pytest.raises(mx.base.MXNetError, match="overflow"):
        h_over.result(timeout=5)
    st = sched.stats()
    assert st["errors"] == 1 and st["responses"] == 1
    assert list(h_ok.result(timeout=5)) == _ref_greedy(
        trained, ok_prompt, 4)


def test_scheduler_streaming_eos_and_limits(trained):
    """Streaming callbacks fire in order (late subscribers replay);
    EOS retires without emitting; max_new_tokens caps length; submit
    validation rejects bad prompts; the queue bound rejects with
    QueueFullError."""
    sched = _sched(trained, ladder=[1, 2], max_queue=3)
    rs = np.random.RandomState(7)
    prompt = rs.randint(0, V, 3).tolist()
    ref = _ref_greedy(trained, prompt, 4)

    seen = []
    h = sched.submit(prompt, max_new_tokens=4)
    h.add_token_callback(lambda hh, tok, i: seen.append((i, tok)))
    sched.pump()
    assert [t for _, t in sorted(seen)] == list(h.result()) == ref
    assert h.finish_reason == "length" and h.latency is not None
    late = []
    h.add_token_callback(lambda hh, tok, i: late.append(tok))
    assert late == ref                         # replay on registration

    # EOS: use the first greedy token as the eos id -> zero emitted
    h2 = sched.submit(prompt, max_new_tokens=8, eos_id=ref[0])
    sched.pump()
    assert list(h2.result()) == [] and h2.finish_reason == "eos"

    with pytest.raises(mx.base.MXNetError, match="empty"):
        sched.submit([])
    with pytest.raises(mx.base.MXNetError, match="capacity"):
        sched.submit(list(range(T + 1)))
    with pytest.raises(mx.base.MXNetError, match="max_new_tokens"):
        sched.submit(prompt, max_new_tokens=0)

    for _ in range(3):
        sched.submit(prompt, max_new_tokens=2)
    with pytest.raises(QueueFullError):
        sched.submit(prompt, max_new_tokens=2)
    sched.pump()


def test_scheduler_deadline_retires_partial(trained):
    """A deadline passing mid-decode retires the sequence with its
    partial output and finish_reason='deadline' (the iteration-level
    analog of the server's deadline flush)."""
    clock = FakeClock()
    sched = _sched(trained, ladder=[1], clock=clock)
    prompt = [1, 2]
    h = sched.submit(prompt, max_new_tokens=50, deadline_ms=100)
    sched.pump(max_iterations=4)               # 3 emitted (2 prefill-1)
    emitted = len(h.tokens)
    assert emitted >= 1 and not h.done()
    clock.advance(0.2)                         # past the deadline
    sched.pump()
    assert h.done() and h.finish_reason == "deadline"
    assert list(h.result()) == h.tokens and len(h.tokens) == emitted
    assert h.missed_deadline()
    # a queued request past its deadline completes empty, never runs
    h2 = sched.submit(prompt, max_new_tokens=4, deadline_ms=1)
    clock.advance(1.0)
    sched.pump()
    assert h2.done() and h2.finish_reason == "deadline"
    assert list(h2.result()) == []


def test_scheduler_traces_and_telemetry(trained):
    """Per-sequence session traces survive batching: each sequence
    keeps its own tree under its root, iterations share ONE step span
    id across batchmates, and the occupancy/counter surface is live."""
    mx.telemetry.reset()
    from mxnet_tpu.telemetry import trace as _trace
    _trace.clear()
    _trace.configure(sample=1)
    try:
        sched = _sched(trained, ladder=[2])
        rs = np.random.RandomState(8)
        h1 = sched.submit(rs.randint(0, V, 2).tolist(), max_new_tokens=3)
        h2 = sched.submit(rs.randint(0, V, 2).tolist(), max_new_tokens=3)
        sched.pump()
        h1.result(timeout=5), h2.result(timeout=5)
        assert h1.trace_id and h2.trace_id
        assert h1.trace_id != h2.trace_id
        t1 = {s["name"]: s for s in _trace.spans(h1.trace_id)}
        assert "serve.decode.sequence" in t1
        s1 = [s for s in _trace.spans(h1.trace_id)
              if s["name"] == "serve.decode.step"]
        s2 = [s for s in _trace.spans(h2.trace_id)
              if s["name"] == "serve.decode.step"]
        shared = {s["span"] for s in s1} & {s["span"] for s in s2}
        assert shared, "batchmates share the iteration step span id"
        st = sched.stats()
        assert st["tokens"] == 6 and st["joins"] == 2
        g = mx.telemetry.get_metric("serve.decode.occupancy",
                                    model=sched.engine.name)
        assert g is not None
        kinds = [r.get("kind")
                 for r in mx.telemetry.flightrec.get_records()]
        assert "serve.decode.step" in kinds
    finally:
        _trace.configure(sample=_trace._env_sample(), reset_ids=False)


def test_slot_ladder_env(monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_DECODE_SLOTS", "2, 8,4")
    assert mx.serve.default_slot_ladder() == [2, 4, 8]
    monkeypatch.setenv("MXNET_SERVE_DECODE_SLOTS", "zero")
    with pytest.raises(mx.base.MXNetError):
        mx.serve.default_slot_ladder()
    monkeypatch.delenv("MXNET_SERVE_DECODE_SLOTS")
    assert mx.serve.default_slot_ladder() == [1, 4, 8]


def test_scheduler_thread_drive_mode(trained):
    """The real-clock dispatch thread serves submits end to end (the
    production drive mode bench.py's decode_batch row uses)."""
    sched = _sched(trained, ladder=[1, 2],
                   clock=mx.serve.MonotonicClock())
    rs = np.random.RandomState(9)
    prompts = [rs.randint(0, V, 2).tolist() for _ in range(3)]
    with sched:
        hs = [sched.submit(p, max_new_tokens=3) for p in prompts]
        outs = [list(h.result(timeout=60)) for h in hs]
    assert sched.stats()["compiles_since_warmup"] == 0
    for p, o in zip(prompts, outs):
        assert o == _ref_greedy(trained, p, 3)


def test_stop_without_drain_fails_pending(trained):
    sched = _sched(trained, ladder=[1])
    h = sched.submit([1, 2], max_new_tokens=4)
    sched.stop(drain=False)
    with pytest.raises(mx.base.MXNetError, match="stopped"):
        h.result(timeout=1)


# =========================================== export / memplan satellites
def test_slot_pooled_export_artifact(trained, tmp_path):
    """Satellite: a per-slot decode graph exports as a slot-pooled
    stateful artifact — the Predictor carries the pooled cache, matches
    the module driver step for step, and Predictor.reset_slot rewinds
    ONE slot without disturbing its batchmates."""
    slots = 3
    path = str(tmp_path / "lm_slots.mxp")
    mx.export_model(
        path,
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, capacity=T, per_slot=True,
                              max_seq_len=T),
        _args_nd(trained), {}, {"data": (slots, 1)},
        data_dtypes={"data": np.int32})
    p = mx.Predictor(path)
    assert p.stateful

    dec = _pooled_module(trained, slots)
    drv = tfm.BatchedKVCacheDecoder(dec, capacity=T)
    for s in range(slots):
        drv.join(s)
    rs = np.random.RandomState(10)
    toks = rs.randint(0, V, (slots, 6)).astype(np.int32)
    for t in range(4):
        ref = drv.step(toks[:, t:t + 1]).asnumpy()
        got = p.forward(data=toks[:, t:t + 1])[0].asnumpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=2e-6)

    # reset slot 1 only: slot 1 restarts from position 0 while slots
    # 0/2 keep their in-flight state — matched by the module driver
    p.reset_slot(1)
    drv.leave(1)
    drv.join(1)
    step5 = toks[:, 4:5].copy()
    ref = drv.step(step5).asnumpy()
    got = p.forward(data=step5)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=2e-6)


def test_memplan_slot_pool_kv_bytes_and_me801(trained):
    """Satellite: the planner charges the slot-pooled KV cache per
    rung under attention_decode — slots x layers x 2 caches + the
    (slots, 1) int32 cursor — and ME801 trips at a toy capacity x slot
    count."""
    from mxnet_tpu.analysis import memplan
    slots, cap = 8, 32
    sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                n_head=H, capacity=cap, per_slot=True,
                                max_seq_len=cap)
    plan = memplan.plan_symbol(sym, {"data": (slots, 1)}, policy="none",
                               for_training=False)
    expect = L * (2 * slots * H * cap * (D // H) * 4 + slots * 1 * 4)
    assert plan["kv_cache_bytes"] == expect
    assert plan["per_op_bytes"].get("attention_decode") == expect
    assert plan["aux_bytes"] >= expect
    # the pool scales linearly with the slot rung
    plan1 = memplan.plan_symbol(
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, capacity=cap, per_slot=True,
                              max_seq_len=cap),
        {"data": (1, 1)}, policy="none", for_training=False)
    assert plan["kv_cache_bytes"] == slots * plan1["kv_cache_bytes"]
    # ME801 at a toy capacity x slot count
    found = memplan.plan_findings(plan, capacity_bytes=expect // 2)
    assert any(d.rule == "ME801" for d in found)


def test_scalar_decode_unchanged(trained):
    """Regression: the scalar (single-session) decode path is
    untouched — same cursor shape, same outputs as ever."""
    sym = tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                                n_head=H, capacity=T)
    _args, _outs, auxs = sym.infer_shape(data=(2, 1))
    by_name = dict(zip(sym.list_auxiliary_states(), auxs))
    assert {s for n, s in by_name.items()
            if n.endswith("cache_pos")} == {(1,)}
    d = _scalar_decoder(trained)
    out = d.step(np.asarray([[1]], np.int32))
    assert out.shape == (1, 1, V)
