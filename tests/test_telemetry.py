"""Telemetry subsystem: spans, metrics registry, exporters, integration.

Covers the ISSUE 1 acceptance surface: span nesting/ordering, counter/
histogram math, chrome-trace JSON schema (traceEvents with ph/ts/dur/
pid/tid), Prometheus text round-trip, Speedometer/Monitor registry
integration, and the end-to-end snapshot after a dist-sync fit smoke run
(compile-cache hit/miss + KVStore byte counters nonzero).

ISSUE 2 diagnostics layer: flight-recorder ring (always-on, bounded,
crash dumps on exceptions escaping fit/executor), per-context device-
memory accounting (live/peak gauges, assert_no_leak), and the NaN/Inf
sentinel (warn/raise policies, executor-level and per-op attribution,
fused-path coverage).
"""
import gc
import json
import logging
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry as tm
from mxnet_tpu.telemetry import flightrec, memory as tmem


@pytest.fixture(autouse=True)
def _clean_telemetry():
    tm.disable()
    tm.reset()
    yield
    tm.disable()
    tm.reset()


# --------------------------------------------------------------- span core
def test_span_disabled_is_noop_singleton():
    assert not tm.enabled()
    s1 = tm.span("anything", k=1)
    s2 = tm.span("else")
    assert s1 is s2 is tm.null_span
    with s1:
        pass
    assert tm.get_spans() == []


def test_span_nesting_and_ordering():
    tm.enable()
    with tm.span("outer", phase=1):
        with tm.span("inner.a"):
            pass
        with tm.span("inner.b"):
            pass
    spans = tm.get_spans()
    # completion order: children close before the parent
    assert [s.name for s in spans] == ["inner.a", "inner.b", "outer"]
    by_name = {s.name: s for s in spans}
    assert by_name["inner.a"].parent == "outer"
    assert by_name["inner.b"].parent == "outer"
    assert by_name["outer"].parent is None
    assert by_name["inner.a"].depth == 1 and by_name["outer"].depth == 0
    # children are contained in the parent's interval
    o = by_name["outer"]
    for child in ("inner.a", "inner.b"):
        c = by_name[child]
        assert c.ts >= o.ts
        assert c.ts + c.dur <= o.ts + o.dur
    assert o.args == {"phase": 1}


def test_span_survives_exception_and_pops_stack():
    tm.enable()
    with pytest.raises(RuntimeError):
        with tm.span("failing"):
            raise RuntimeError("boom")
    with tm.span("after"):
        pass
    spans = {s.name: s for s in tm.get_spans()}
    assert set(spans) == {"failing", "after"}
    assert spans["after"].parent is None  # stack fully unwound


def test_span_feeds_histogram():
    tm.enable()
    with tm.span("timed", _hist="timed.seconds"):
        pass
    h = tm.get_metric("timed.seconds")
    assert h is not None and h.count == 1


# ----------------------------------------------------------------- metrics
def test_counter_math_and_labels():
    c = tm.counter("widgets")
    c.inc().inc(4)
    assert c.value == 5
    assert tm.counter("widgets") is c          # create-or-get
    c2 = tm.counter("widgets", kind="blue")
    assert c2 is not c and c2.value == 0
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c2.key == 'widgets{kind="blue"}'


def test_gauge_set_inc_dec():
    g = tm.gauge("depth")
    g.set(3.5)
    assert g.value == 3.5
    g.inc(2)
    g.dec()
    assert g.value == 4.5


def test_histogram_buckets_and_stats():
    h = tm.histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(55.55)
    assert h.min == 0.05 and h.max == 50.0
    assert h.mean == pytest.approx(55.55 / 4)
    # cumulative bucket counts: <=0.1 -> 1, <=1.0 -> 2, <=10.0 -> 3
    assert h.cumulative() == [(0.1, 1), (1.0, 2), (10.0, 3)]


def test_metric_type_collision_raises():
    tm.counter("clash")
    with pytest.raises(TypeError):
        tm.gauge("clash")


def test_snapshot_shape():
    tm.counter("a").inc(2)
    tm.gauge("b").set(7)
    tm.histogram("c").observe(0.5)
    snap = tm.snapshot()
    assert snap["counters"]["a"] == 2
    assert snap["gauges"]["b"] == 7.0
    assert snap["histograms"]["c"]["count"] == 1
    assert "spans" in snap and "events" in snap


# ----------------------------------------------------------- chrome trace
def _valid_trace_event(e):
    assert isinstance(e["name"], str) and e["name"]
    assert e["ph"] in ("X", "M", "i")
    assert isinstance(e["pid"], int)
    if e["ph"] == "X":
        assert isinstance(e["tid"], int)
        assert isinstance(e["ts"], int) and e["ts"] >= 0
        assert isinstance(e["dur"], int) and e["dur"] >= 0
        assert isinstance(e["args"], dict)


def test_chrome_trace_schema(tmp_path):
    tm.enable()
    with tm.span("parent"):
        with tm.span("child", op="FC"):
            pass
    tm.record_event("marker", epoch=0)
    path = tm.chrome_trace.dump(str(tmp_path / "trace.json"),
                                metadata={"mode": "test"})
    doc = json.load(open(path))
    assert set(doc) == {"traceEvents", "displayTimeUnit", "otherData"}
    assert doc["otherData"]["mode"] == "test"
    events = doc["traceEvents"]
    for e in events:
        _valid_trace_event(e)
    complete = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"parent", "child"}
    child = next(e for e in complete if e["name"] == "child")
    assert child["args"]["op"] == "FC"
    assert child["args"]["parent"] == "parent"
    assert [e["name"] for e in events if e["ph"] == "i"] == ["marker"]
    # lane metadata present for the emitting thread
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)


# ------------------------------------------------------------- prometheus
def test_prometheus_round_trip():
    tm.counter("kvstore.push.bytes").inc(1024)
    tm.counter("executor.op_dispatch", op="Convolution").inc(3)
    tm.gauge("speedometer.samples_per_sec").set(1234.5)
    h = tm.histogram("module.fit.batch.seconds", buckets=(0.01, 0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = tm.prometheus.render()
    parsed = tm.prometheus.parse(text)
    types = parsed.pop("__types__")
    assert types["mxnet_kvstore_push_bytes_total"] == "counter"
    assert types["mxnet_speedometer_samples_per_sec"] == "gauge"
    assert types["mxnet_module_fit_batch_seconds"] == "histogram"
    assert parsed["mxnet_kvstore_push_bytes_total"] == 1024
    assert parsed[
        'mxnet_executor_op_dispatch_total{op="Convolution"}'] == 3
    assert parsed["mxnet_speedometer_samples_per_sec"] == 1234.5
    assert parsed['mxnet_module_fit_batch_seconds_bucket{le="0.1"}'] == 1
    assert parsed['mxnet_module_fit_batch_seconds_bucket{le="+Inf"}'] == 2
    assert parsed["mxnet_module_fit_batch_seconds_count"] == 2
    assert parsed["mxnet_module_fit_batch_seconds_sum"] == \
        pytest.approx(0.55)


# ------------------------------------------------------------------ jsonl
def test_jsonl_event_log(tmp_path):
    tm.enable()
    tm.record_event("batch_end", epoch=0, nbatch=1, duration_us=2000,
                    batch_size=32)
    with tm.span("kvstore.push", bytes=64):
        pass
    tm.counter("io.batches", iter="NDArrayIter").inc(7)
    path = tm.jsonl.dump(str(tmp_path / "events.jsonl"))
    recs = [json.loads(ln) for ln in open(path) if ln.strip()]
    by_type = {}
    for r in recs:
        by_type.setdefault(r["type"], []).append(r)
    ev = by_type["event"][0]
    assert ev["kind"] == "batch_end" and ev["epoch"] == 0
    assert ev["batch_size"] == 32                # payload flattened
    sp = by_type["span"][0]
    assert sp["name"] == "kvstore.push" and sp["dur_us"] >= 0
    ctr = by_type["counter"][0]
    assert ctr["name"] == "io.batches" and ctr["value"] == 7
    assert ctr["labels"] == {"iter": "NDArrayIter"}


# ------------------------------------------------- monitor / speedometer
def test_monitor_records_into_registry_and_flush():
    tm.enable()
    mon = mx.Monitor(interval=1, pattern=".*fc.*")
    x = mx.sym.var("data")
    out = mx.sym.FullyConnected(x, num_hidden=4, name="monfc")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3))
    mon.install(exe)
    exe.arg_dict["data"][:] = np.ones((2, 3), "f")
    mon.tic()
    exe.forward(is_train=False)
    records = mon.toc()
    assert records, "monitor collected nothing"
    steps = {r[0] for r in records}
    assert steps == {0}, "all window records must share the tic step"
    # registry gauges exist for observed tensors
    names = [r[1] for r in records]
    g = tm.get_metric("monitor.stat", tensor=names[0])
    assert g is not None and g.value == pytest.approx(float(records[0][2]))
    # monitor events landed in the buffer
    kinds = [e["kind"] for e in tm.get_events()]
    assert "monitor" in kinds

    # flush drops queued entries so cycles don't leak
    mon.tic()
    exe.forward(is_train=False)
    mon.flush()
    assert mon.toc() == []          # window was discarded


def test_monitor_repeated_cycles_do_not_leak():
    mon = mx.Monitor(interval=1, pattern=".*fc.*")
    x = mx.sym.var("data")
    out = mx.sym.FullyConnected(x, num_hidden=4, name="leakfc")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3))
    mon.install(exe)
    exe.arg_dict["data"][:] = np.ones((2, 3), "f")
    sizes = []
    for _ in range(3):
        mon.tic()
        exe.forward(is_train=False)
        sizes.append(len(mon.toc()))
    assert sizes[0] == sizes[1] == sizes[2], sizes


def test_speedometer_records_into_registry():
    tm.enable()
    speedo = mx.callback.Speedometer(batch_size=32, frequent=2)
    metric = mx.metric.create("acc")
    from mxnet_tpu.model import BatchEndParam
    for nbatch in range(1, 5):
        speedo(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals=None))
    g = tm.get_metric("speedometer.samples_per_sec")
    assert g is not None and g.value > 0
    speeds = [e for e in tm.get_events() if e["kind"] == "speed"]
    assert speeds and speeds[-1]["payload"]["samples_per_sec"] == g.value


# ------------------------------------------------------- fit integration
def _fit_smoke(kvstore, num_epoch=1, batch_size=4, n=8):
    X = np.random.rand(n, 10).astype("f")
    Y = (np.random.rand(n) * 3).astype("f")
    it = mx.io.NDArrayIter(X, Y, batch_size=batch_size)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        logger=logging.getLogger("telemetry_smoke"))
    mod.fit(it, num_epoch=num_epoch, kvstore=kvstore,
            optimizer_params={"learning_rate": 0.1})
    return mod


def test_snapshot_after_dist_sync_fit():
    """ISSUE 1 acceptance: compile-cache hit/miss and KVStore byte
    counters are nonzero after a dist-sync fit smoke run."""
    tm.enable()
    _fit_smoke("dist_sync")
    snap = tm.snapshot()
    c = snap["counters"]
    assert c.get("executor.jit_cache.miss", 0) > 0
    assert c.get("executor.jit_cache.hit", 0) > 0
    assert c.get("kvstore.push.bytes", 0) > 0
    assert c.get("kvstore.pull.bytes", 0) > 0
    assert c.get("module.fit.batches", 0) == 2
    # per-op dispatch attribution from the registry
    assert any(k.startswith("executor.op_dispatch")
               for k in c), list(c)
    # span timeline covers the whole step
    names = {s.name for s in tm.get_spans()}
    for need in ("executor.compile", "kvstore.push", "kvstore.pull",
                 "io.next", "io.load_batch", "module.fit.batch",
                 "module.fit.epoch"):
        assert need in names, (need, sorted(names))
    assert any(n.startswith("op.") for n in names)
    # batch histograms populated
    h = snap["histograms"].get("module.fit.batch.seconds")
    assert h and h["count"] == 2
    # events for the jsonl log
    kinds = [e["kind"] for e in tm.get_events()]
    assert kinds.count("batch_end") == 2
    assert kinds.count("epoch_end") == 1


def test_fit_disabled_telemetry_records_nothing():
    _fit_smoke("local")
    assert tm.get_spans() == []
    assert tm.get_events() == []
    snap = tm.snapshot()
    assert snap["counters"] == {}


# --------------------------------------------------------- flight recorder
def test_flight_ring_bounded_and_always_on():
    """The ring records with the span tracer OFF and never exceeds its
    capacity (oldest entries fall off)."""
    flightrec.configure(capacity=8)
    try:
        flightrec.clear()
        assert not tm.enabled()
        for i in range(20):
            flightrec.note("tick", i=i)
        recs = flightrec.get_records()
        assert len(recs) == 8
        assert [r["i"] for r in recs] == list(range(12, 20))
        assert all(r["kind"] == "tick" and r["ts_us"] > 0 for r in recs)
    finally:
        flightrec.configure(capacity=512)


def test_flight_ring_records_fit_timeline_with_tracer_off():
    flightrec.clear()
    _fit_smoke("local")
    kinds = {r["kind"] for r in flightrec.get_records()}
    assert "module.fit.batch" in kinds, kinds
    assert "executor.compile" in kinds or "executor.run" in kinds, kinds
    assert "executor.bind" in kinds, kinds
    batches = [r for r in flightrec.get_records()
               if r["kind"] == "module.fit.batch"]
    assert all(r["dur_us"] > 0 for r in batches)


def test_flight_ring_mirrors_spans_and_events_when_enabled():
    tm.enable()
    flightrec.clear()
    with tm.span("mirrored.phase", step=1):
        pass
    tm.record_event("mirrored_marker", epoch=0)
    recs = flightrec.get_records()
    assert any(r["kind"] == "span" and r["name"] == "mirrored.phase"
               for r in recs)
    assert any(r["kind"] == "mirrored_marker" and r["epoch"] == 0
               for r in recs)


def test_crash_dump_on_fit_exception_and_diagnose(tmp_path):
    """ISSUE 2 acceptance: a Module.fit run killed by an injected
    mid-batch exception leaves a crash dump on disk (recent ring,
    memory watermarks, metrics snapshot) that tools/diagnose.py
    renders."""
    flightrec.configure(dump_dir=str(tmp_path))
    try:
        flightrec.clear()
        X = np.random.rand(16, 10).astype("f")
        Y = (np.random.rand(16) * 3).astype("f")
        it = mx.io.NDArrayIter(X, Y, batch_size=4)
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3),
            name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())

        class Boom(RuntimeError):
            pass

        def bomb(param):
            if param.nbatch == 1:
                raise Boom("injected mid-batch failure")

        with pytest.raises(Boom):
            mod.fit(it, num_epoch=1, batch_end_callback=bomb,
                    optimizer_params={"learning_rate": 0.1})

        dumps = sorted(tmp_path.glob("mxnet_crash_*.json"))
        assert len(dumps) == 1, dumps      # exactly one dump per crash
        rep = json.load(open(dumps[0]))
        assert rep["type"] == "crash_report"
        assert rep["where"] == "module.fit"
        assert rep["exception"]["type"] == "Boom"
        assert "injected mid-batch" in rep["exception"]["message"]
        # ring carries the recent timeline: batches ran before the crash
        kinds = [r["kind"] for r in rep["ring"]]
        assert "module.fit.batch" in kinds
        # memory watermarks and metrics snapshot present
        assert rep["memory"] and all(
            "live_bytes" in v and "peak_bytes" in v
            for v in rep["memory"].values())
        assert "counters" in rep["metrics"]
        assert rep["devices"], "jax device info missing"
        assert any(k.startswith("MXNET_") or k.startswith("JAX_")
                   for k in rep["env"])

        # tools/diagnose.py renders it human-readable
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            import diagnose
        finally:
            sys.path.pop(0)
        text = diagnose.render_file(str(dumps[0]))
        assert "CRASH REPORT" in text
        assert "Boom" in text
        assert "module.fit" in text
        assert "memory watermarks:" in text
        assert "module.fit.batch" in text          # timeline rendered
    finally:
        flightrec.configure(dump_dir=os.environ.get("MXNET_CRASH_DIR",
                                                    "."))


def test_crash_dump_deduped_across_nested_guards(tmp_path):
    """An exception escaping Executor.backward inside fit passes two
    crash guards — only the innermost writes a dump."""
    flightrec.configure(dump_dir=str(tmp_path))
    try:
        x = mx.sym.var("data")
        net = mx.sym.FullyConnected(x, num_hidden=4, name="dedupfc")
        exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
        with pytest.raises(mx.MXNetError):
            exe.backward()          # no prior forward: raises
        # user errors raised before dispatch carry no dump; now force a
        # dispatch-time failure via a sentinel raise
        sent = tm.NanSentinel(policy="raise")
        sent.install(exe)
        exe.arg_dict["data"][:] = np.full((2, 3), np.nan, "f")
        with pytest.raises(tm.AnomalyError):
            exe.forward(is_train=False)
        dumps = sorted(tmp_path.glob("mxnet_crash_*.json"))
        assert len(dumps) == 1
        assert json.load(open(dumps[0]))["where"] == "executor.forward"
    finally:
        flightrec.configure(dump_dir=os.environ.get("MXNET_CRASH_DIR",
                                                    "."))


# ------------------------------------------------------- memory accounting
def test_memory_accounting_bind_run_free_cycle():
    """ISSUE 2 acceptance: per-context live/peak gauges track a
    bind/run/free cycle and assert_no_leak() passes."""
    gc.collect()
    key = "cpu(0)"
    base = tmem.live_bytes(key)
    with tmem.assert_no_leak(ctx=key):
        x = mx.sym.var("data")
        net = mx.sym.FullyConnected(x, num_hidden=16, name="memfc")
        exe = net.simple_bind(ctx=mx.cpu(), data=(8, 4))
        # bind allocated visible bytes: data (8x4) + weight (16x4) +
        # bias (16), each f32, plus grads
        grown = tmem.live_bytes(key)
        assert grown >= base + (8 * 4 + 16 * 4 + 16) * 4
        # the executor reported its footprint at bind time
        fp = exe.memory_footprint
        assert fp["arg_bytes"] == (8 * 4 + 16 * 4 + 16) * 4
        assert fp["grad_bytes"] > 0
        assert fp["output_bytes"] == 8 * 16 * 4
        g = tm.get_metric("executor.memory.arg_bytes", ctx=key)
        assert g is not None and g.value == fp["arg_bytes"]
        exe.forward(is_train=False)
        _ = exe.outputs
        assert tmem.peak_bytes(key) >= tmem.live_bytes(key) > grown - 1
        del exe, _
    # after the cycle the ledger is back at (or below) baseline; the
    # registry gauges track the ledger
    gc.collect()
    assert tmem.live_bytes(key) <= base + 1
    snap = tm.snapshot()
    assert key in snap["memory"]
    assert snap["memory"][key]["live_bytes"] == tmem.live_bytes(key)
    g = tm.get_metric("memory.live_bytes", ctx=key)
    assert g is not None and g.value == tmem.live_bytes(key)


def test_assert_no_leak_catches_held_array():
    holder = []
    with pytest.raises(AssertionError, match="leak"):
        with tmem.assert_no_leak(ctx="cpu(0)"):
            holder.append(mx.nd.zeros((4096,)))
    holder.clear()


def test_memory_accounting_swap_adjusts_live():
    a = mx.nd.zeros((1024,))            # 4 KiB f32
    live0 = tmem.live_bytes("cpu(0)")
    a._set(a.asjax()[:256])             # shrink to 1 KiB
    assert tmem.live_bytes("cpu(0)") == live0 - 3 * 1024
    del a


# ---------------------------------------------------------------- sentinel
def _nan_executor(policy, per_op=False, train=False):
    x = mx.sym.var("data")
    net = mx.sym.FullyConnected(x, num_hidden=4, name="sentfc")
    if train:
        net = mx.sym.SoftmaxOutput(net, name="softmax")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
    sent = tm.NanSentinel(policy=policy)
    sent.install(exe, per_op=per_op)
    exe.arg_dict["data"][:] = np.full((2, 3), np.nan, "f")
    for nm in ("sentfc_weight",):
        exe.arg_dict[nm][:] = np.ones(exe.arg_dict[nm].shape, "f")
    return exe, sent


def test_sentinel_warn_flags_output_with_attribution(tmp_path):
    flightrec.configure(dump_dir=str(tmp_path))
    try:
        flightrec.clear()
        exe, sent = _nan_executor("warn")
        exe.forward(is_train=False)
        _ = exe.outputs
        assert sent.anomalies == [
            {"step": 0, "kind": "output", "array": "sentfc_output"}]
        # registry counter with op/array attribution
        c = tm.get_metric("sentinel.anomalies", kind="output",
                          array="sentfc_output")
        assert c is not None and c.value == 1
        # anomaly landed in the flight ring for the crash timeline
        assert any(r["kind"] == "anomaly"
                   and r["array"] == "sentfc_output"
                   for r in flightrec.get_records())
        # warn policy: training continues (no exception), second window
        # flags again
        exe.forward(is_train=False)
        _ = exe.outputs
        assert len(sent.anomalies) == 2 and c.value == 2
    finally:
        flightrec.configure(dump_dir=os.environ.get("MXNET_CRASH_DIR",
                                                    "."))


def test_sentinel_raise_policy_and_crash_dump(tmp_path):
    flightrec.configure(dump_dir=str(tmp_path))
    try:
        exe, sent = _nan_executor("raise")
        with pytest.raises(tm.AnomalyError, match="sentfc_output"):
            exe.forward(is_train=False)
        # the raise escaped the executor -> crash report with the
        # anomaly in its ring
        dumps = sorted(tmp_path.glob("mxnet_crash_*.json"))
        assert dumps
        rep = json.load(open(dumps[-1]))
        assert rep["exception"]["type"] == "AnomalyError"
        assert any(r["kind"] == "anomaly" for r in rep["ring"])
    finally:
        flightrec.configure(dump_dir=os.environ.get("MXNET_CRASH_DIR",
                                                    "."))


def test_sentinel_per_op_attribution():
    exe, sent = _nan_executor("warn", per_op=True)
    exe.forward(is_train=False)
    _ = exe.outputs
    kinds = {a["kind"] for a in sent.anomalies}
    assert "op_output" in kinds          # Monitor-tap install point fired
    assert any(a["array"] == "sentfc_output" for a in sent.anomalies
               if a["kind"] == "op_output")


def test_sentinel_flags_nan_gradients():
    exe, sent = _nan_executor("warn", train=True)
    exe.forward(is_train=True)
    exe.backward()
    grads = [a for a in sent.anomalies if a["kind"] == "gradient"]
    assert grads, sent.anomalies
    assert all(a["array"] in exe.arg_names for a in grads)


def test_sentinel_interval_windows():
    exe, sent = _nan_executor("warn")
    sent.interval = 2
    for _ in range(4):
        exe.forward(is_train=False)
        _ = exe.outputs
    # steps 0 and 2 checked; 1 and 3 skipped
    assert [a["step"] for a in sent.anomalies] == [0, 2]


def test_sentinel_module_fused_path_raise(tmp_path):
    """The sentinel trips inside the fused fwd+bwd+update step and the
    escaping AnomalyError leaves a crash dump."""
    flightrec.configure(dump_dir=str(tmp_path))
    try:
        X = np.random.rand(16, 10).astype("f")
        X[6, :] = np.nan                 # second batch poisons outputs
        Y = (np.random.rand(16) * 3).astype("f")
        it = mx.io.NDArrayIter(X, Y, batch_size=4)
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3),
            name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind([("data", (4, 10))], [("softmax_label", (4,))])
        mod.install_sentinel(tm.NanSentinel(policy="raise"))
        with pytest.raises(tm.AnomalyError):
            mod.fit(it, num_epoch=1,
                    optimizer_params={"learning_rate": 0.1})
        assert mod._fused_armed          # tripped on the fused path
        dumps = sorted(tmp_path.glob("mxnet_crash_*.json"))
        assert dumps
        rep = json.load(open(dumps[-1]))
        assert any(r["kind"] == "anomaly" for r in rep["ring"])
    finally:
        flightrec.configure(dump_dir=os.environ.get("MXNET_CRASH_DIR",
                                                    "."))


def test_histogram_quantile_estimation():
    """Histogram.quantile: bucket-interpolated percentile estimates
    (the serving p50/p99 SLO readout) — exact at bucket bounds, clamped
    to the recorded max above the last bound, None while empty."""
    from mxnet_tpu.telemetry.metrics import Histogram
    h = Histogram("t.q", (), buckets=(0.01, 0.1, 1.0))
    assert h.quantile(0.5) is None
    for v in (0.005, 0.005, 0.05, 0.05, 0.5, 0.5, 2.0, 3.0):
        h.observe(v)
    # 8 observations: ranks 1-2 in <=0.01, 3-4 in <=0.1, 5-6 in <=1.0,
    # 7-8 above the last bound
    assert h.quantile(0.25) == pytest.approx(0.01)
    assert h.quantile(0.5) == pytest.approx(0.1)
    assert h.quantile(1.0) == pytest.approx(3.0)    # clamps to max
    q99 = h.quantile(0.99)
    assert q99 == pytest.approx(3.0)                # beyond last bucket
    assert 0.01 <= h.quantile(0.4) <= 0.1           # interpolated
