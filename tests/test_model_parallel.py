"""Model parallelism: ctx_group + group2ctx lowered onto mesh shardings.

reference behavior: tests/python/unittest/test_model_parallel.py and
example/model-parallel-lstm/lstm.py:48-112 — a symbol whose layers are
tagged into groups, bound with group2ctx over several devices, must
compute the same values as the single-device binding.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _two_group_net():
    with mx.AttrScope(ctx_group="stage0"):
        data = sym.var("data")
        fc0 = sym.FullyConnected(data, num_hidden=16, name="fc0")
        act0 = sym.Activation(fc0, act_type="relu", name="act0")
    with mx.AttrScope(ctx_group="stage1"):
        fc1 = sym.FullyConnected(act0, num_hidden=8, name="fc1")
        out = sym.SoftmaxOutput(fc1, name="softmax")
    return out


def _bind_and_run(net, group2ctx=None, batch=4):
    shapes = {"data": (batch, 12), "softmax_label": (batch,)}
    exe = net.simple_bind(mx.cpu(), grad_req="write", group2ctx=group2ctx,
                          **shapes)
    rng = np.random.RandomState(3)
    exe.arg_dict["data"]._set(
        rng.rand(*shapes["data"]).astype(np.float32))
    exe.arg_dict["softmax_label"]._set(
        (rng.randint(0, 8, size=batch)).astype(np.float32))
    exe.arg_dict["fc0_weight"]._set(
        rng.normal(0, 0.1, (16, 12)).astype(np.float32))
    exe.arg_dict["fc0_bias"]._set(np.zeros(16, np.float32))
    exe.arg_dict["fc1_weight"]._set(
        rng.normal(0, 0.1, (8, 16)).astype(np.float32))
    exe.arg_dict["fc1_bias"]._set(np.zeros(8, np.float32))
    exe.forward(is_train=True)
    out = exe.outputs[0].asnumpy()
    exe.backward()
    grads = {nm: g.asnumpy() for nm, g in exe.grad_dict.items()
             if g is not None}
    return out, grads


def test_group2ctx_matches_single_device():
    import jax
    devs = jax.devices("cpu")
    net = _two_group_net()
    ref_out, ref_grads = _bind_and_run(net)
    g2c = {"stage0": mx.Context("cpu", 0), "stage1": mx.Context("cpu", 1)}
    mp_out, mp_grads = _bind_and_run(net, group2ctx=g2c)
    np.testing.assert_allclose(mp_out, ref_out, rtol=1e-5, atol=1e-6)
    for nm in ref_grads:
        np.testing.assert_allclose(mp_grads[nm], ref_grads[nm],
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"grad mismatch for {nm}")


def test_group2ctx_actually_shards_params():
    net = _two_group_net()
    g2c = {"stage0": mx.Context("cpu", 0), "stage1": mx.Context("cpu", 1)}
    shapes = {"data": (4, 12), "softmax_label": (4,)}
    exe = net.simple_bind(mx.cpu(), grad_req="write", group2ctx=g2c,
                          **shapes)
    w = exe.arg_dict["fc0_weight"].asjax()
    # 16x12 weight over a 2-device model axis: sharded on dim 0
    assert len(w.sharding.device_set) == 2, (
        "fc0_weight should live on both model-axis devices")
    assert not w.sharding.is_fully_replicated, (
        "fc0_weight should be sharded, not replicated")


def test_model_parallel_lstm_groups():
    """Reference example/model-parallel-lstm: each LSTM layer in its own
    group; grouped binding == ungrouped numerics."""
    from mxnet_tpu.rnn import LSTMCell

    def build():
        stacked = []
        with mx.AttrScope(ctx_group="layer0"):
            data = sym.var("data")
            cell0 = LSTMCell(8, prefix="l0_")
            out0, _ = cell0.unroll(5, inputs=data, layout="NTC",
                                   merge_outputs=True)
        with mx.AttrScope(ctx_group="layer1"):
            cell1 = LSTMCell(8, prefix="l1_")
            out1, _ = cell1.unroll(5, inputs=out0, layout="NTC",
                                   merge_outputs=True)
            flat = sym.Reshape(out1, shape=(-1, 8))
            fc = sym.FullyConnected(flat, num_hidden=4, name="fc")
            net = sym.SoftmaxOutput(fc, name="softmax")
        return net

    shapes = {"data": (2, 5, 3), "softmax_label": (10,)}
    rng = np.random.RandomState(0)
    feed = {}

    def run(group2ctx):
        net = build()
        exe = net.simple_bind(mx.cpu(), grad_req="write",
                              group2ctx=group2ctx, **shapes)
        for nm, arr in exe.arg_dict.items():
            if nm not in feed:
                feed[nm] = rng.normal(0, 0.1, arr.shape).astype(np.float32)
            arr._set(feed[nm])
        exe.forward(is_train=True)
        out = exe.outputs[0].asnumpy()
        exe.backward()
        gw = exe.grad_dict["l0_i2h_weight"].asnumpy()
        return out, gw

    ref_out, ref_gw = run(None)
    mp_out, mp_gw = run({"layer0": mx.Context("cpu", 0),
                         "layer1": mx.Context("cpu", 1)})
    np.testing.assert_allclose(mp_out, ref_out, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(mp_gw, ref_gw, rtol=1e-4, atol=1e-5)


def test_shard_spec_consumer_aware():
    """Weights shard on their OUTPUT dim (never a contraction dim);
    unknown 2-D+ consumers replicate; 1-D per-channel vectors shard."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.placement import _shard_spec

    # FullyConnected weight (num_hidden, in_dim): shard axis 0 even when
    # the contraction dim is larger (the old largest-dim rule got this
    # wrong and paid a partial-sum per matmul)
    assert _shard_spec((4, 1024), 2, ("FullyConnected", "weight"))[0] == \
        P("model", None)
    assert _shard_spec((8, 3, 3, 3), 2, ("Convolution", "weight"))[0] == \
        P("model", None, None, None)
    assert _shard_spec((1024, 8), 2, ("Embedding", "weight"))[0] == \
        P(None, "model")
    # unknown consumer, 2-D: replicate rather than guess (the reason —
    # the second return — feeds the SH602 lint finding)
    spec, reason = _shard_spec((1024, 512), 2, ("Correlation", "data1"))
    assert spec == P() and reason
    assert _shard_spec((1024, 512), 2, None)[0] == P()
    # per-channel vector: elementwise-safe
    assert _shard_spec((64,), 2, None)[0] == P("model")
    # indivisible: replicate
    spec, reason = _shard_spec((7, 6), 2, ("FullyConnected", "weight"))
    assert spec == P() and "divisible" in reason
