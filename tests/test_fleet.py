"""Fleet observability plane (ISSUE 16): rank identity, snapshot/merge,
the live ops endpoint, fleet forensics, and the perfwatch fleet series.

Tier-1 coverage for the cross-rank layer:

* ``telemetry.fleet`` — rank resolution precedence, versioned
  ``snapshot()``, lossless ``merge()`` (counters sum exactly, gauges
  keep per-rank + min/max/mean, histograms merge bucket-wise so fleet
  quantiles stay within one bucket width of the pooled stream);
* ``telemetry.prometheus.render(fleet=...)`` — one exposition text
  with ``rank`` labels on every sample;
* ``telemetry.opsd`` — /metrics (OpenMetrics negotiation), /healthz
  (200/503), /varz, /tracez, /fleetz, scraped during a live fit loop;
* ``tools/fleetstat.py`` — the fast chaos-shaped path: synthesized
  3-rank dumps with a straggler, a diverging rank, and a dead rank
  must produce the same report shape the @slow chaos test asserts on
  real per-rank dumps (tests/test_chaos.py), byte-deterministically;
* ``tools/perfwatch.py --fleet`` — the fleet-health series regresses
  and recovers like any bench series;
* ``tools/diagnose.py`` — the decode-engine section renders in BOTH
  the crash-report and the jsonl path.
"""
import json
import os
import random
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.telemetry import fleet, metrics, opsd, prometheus

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")

_FLEET_ENV = ("MXNET_FLEET_RANK", "DMLC_WORKER_ID", "DMLC_NUM_WORKER",
              "MXNET_RECOVERY_GENERATION", "MXNET_OPS_PORT")


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _clean_fleet(monkeypatch):
    """Every test starts untagged with an empty registry and no live
    endpoint, and leaves nothing behind for the rest of the suite."""
    for var in _FLEET_ENV:
        monkeypatch.delenv(var, raising=False)
    fleet.configure()
    mx.telemetry.reset()
    yield
    opsd.stop_ops()
    fleet.configure()
    mx.telemetry.reset()
    mx.telemetry.disable()


# --------------------------------------------------------- rank identity
def test_rank_resolution_precedence(monkeypatch):
    """configure() > MXNET_FLEET_RANK > DMLC_WORKER_ID > 0; tagged()
    flips exactly when a source is active."""
    assert fleet.rank() == 0
    assert not fleet.tagged()

    monkeypatch.setenv("DMLC_WORKER_ID", "2")
    assert fleet.rank() == 2 and fleet.tagged()

    monkeypatch.setenv("MXNET_FLEET_RANK", "3")
    assert fleet.rank() == 3          # explicit env beats the launcher's

    fleet.configure(rank=5)
    assert fleet.rank() == 5          # programmatic override beats env
    fleet.configure()
    assert fleet.rank() == 3          # cleared back to env resolution

    monkeypatch.setenv("MXNET_FLEET_RANK", "junk")
    assert fleet.rank() == 2          # malformed env falls through


def test_num_workers_and_generation(monkeypatch):
    assert fleet.num_workers() == 1
    assert fleet.generation() == 0
    monkeypatch.setenv("DMLC_NUM_WORKER", "4")
    assert fleet.num_workers() == 4
    fleet.configure(num_workers=7)
    assert fleet.num_workers() == 7
    monkeypatch.setenv("MXNET_RECOVERY_GENERATION", "2")
    assert fleet.generation() == 2


# -------------------------------------------------------------- snapshot
def test_snapshot_schema_and_determinism(monkeypatch):
    monkeypatch.setenv("MXNET_FLEET_RANK", "1")
    metrics.counter("t.fleet.items", shard="a").inc(3)
    metrics.gauge("t.fleet.depth").set(2.5)
    metrics.histogram("t.fleet.seconds",
                      buckets=(0.1, 1.0)).observe(0.05, exemplar="tr01")

    snap = fleet.snapshot()
    assert snap["schema"] == fleet.SCHEMA_VERSION
    assert snap["rank"] == 1 and snap["pid"] == os.getpid()
    assert snap["generation"] == 0

    [ctr] = [c for c in snap["counters"] if c["name"] == "t.fleet.items"]
    assert ctr == {"name": "t.fleet.items", "labels": {"shard": "a"},
                   "value": 3}
    [h] = [h for h in snap["histograms"]
           if h["name"] == "t.fleet.seconds"]
    assert h["buckets"] == [0.1, 1.0]
    assert h["bucket_counts"] == [1, 1]      # cumulative
    assert h["count"] == 1 and h["min"] == h["max"] == 0.05
    assert h["exemplars"] == {"0": ["tr01", 0.05]}

    # JSON-pure and deterministic: two snapshots of the same registry
    # state serialize byte-identically
    assert json.dumps(snap) == json.dumps(fleet.snapshot())
    json.loads(json.dumps(snap))


# ----------------------------------------------------------------- merge
def _snap(rank, counters=(), gauges=(), hists=(), gen=0, nw=3):
    return {"schema": fleet.SCHEMA_VERSION, "rank": rank,
            "host": f"h{rank}", "pid": 100 + rank, "num_workers": nw,
            "generation": gen,
            "counters": [{"name": n, "labels": dict(l), "value": v}
                         for n, l, v in counters],
            "gauges": [{"name": n, "labels": dict(l), "value": v}
                       for n, l, v in gauges],
            "histograms": list(hists)}


def _hist_record(h):
    """A registry Histogram as its schema-v1 snapshot record."""
    return {"buckets": list(h.buckets),
            "bucket_counts": list(h.bucket_counts),
            "count": h.count, "sum": h.sum, "min": h.min, "max": h.max,
            "exemplars": {str(i): [ex[0], ex[1]]
                          for i, ex in sorted(h.exemplars.items())}}


def test_merge_counters_sum_gauges_spread():
    snaps = [
        _snap(0, counters=[("io.batches", {}, 10)],
              gauges=[("q.depth", {}, 1.0)]),
        _snap(1, counters=[("io.batches", {}, 32)],
              gauges=[("q.depth", {}, 4.0)], gen=1),
        _snap(2, counters=[("io.batches", {}, 8),
                           ("only.rank2", {}, 5)],
              gauges=[("q.depth", {}, 1.0)]),
    ]
    out = fleet.merge(snaps)
    assert out["ranks"] == [0, 1, 2]
    assert out["hosts"] == {"0": "h0", "1": "h1", "2": "h2"}
    assert out["generations"] == {"0": 0, "1": 1, "2": 0}

    ctr = out["counters"]["io.batches"]
    assert ctr["by_rank"] == {"0": 10, "1": 32, "2": 8}
    assert ctr["total"] == 50                  # exact sum, nothing lost
    assert out["counters"]["only.rank2"]["total"] == 5

    g = out["gauges"]["q.depth"]
    assert g["min"] == 1.0 and g["max"] == 4.0 and g["mean"] == 2.0

    # deterministic regardless of input order
    assert json.dumps(out) == json.dumps(fleet.merge(reversed(snaps)))

    # two dumps from the same rank merge rank-wise: counters sum
    twice = fleet.merge([snaps[0], snaps[0]])
    assert twice["counters"]["io.batches"]["by_rank"] == {"0": 20}

    with pytest.raises(ValueError):
        fleet.merge([dict(snaps[0], schema=99)])


def test_histogram_merge_identical_bounds_is_lossless():
    """Satellite: merging per-rank records with the same bounds equals
    observing the pooled stream into one histogram — counts, sum and
    every quantile — and the estimate sits within one bucket width of
    the true pooled-stream quantile."""
    bounds = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0)
    rng = random.Random(7)
    stream1 = [rng.uniform(0.001, 1.2) for _ in range(400)]
    stream2 = [rng.uniform(0.02, 4.0) for _ in range(300)]

    h1 = metrics.Histogram("t.merge.seconds", (), buckets=bounds)
    h2 = metrics.Histogram("t.merge.seconds", (), buckets=bounds)
    pooled = metrics.Histogram("t.merge.seconds", (), buckets=bounds)
    for v in stream1:
        h1.observe(v)
        pooled.observe(v)
    for v in stream2:
        h2.observe(v)
        pooled.observe(v)

    merged = fleet.merge_histogram_records([_hist_record(h1),
                                            _hist_record(h2)])
    assert merged["buckets"] == list(bounds)
    assert merged["bucket_counts"] == list(pooled.bucket_counts)
    assert merged["count"] == 700
    assert merged["sum"] == pytest.approx(sum(stream1) + sum(stream2))
    assert merged["min"] == min(stream1 + stream2)
    assert merged["max"] == max(stream1 + stream2)

    observations = sorted(stream1 + stream2)
    edges = [0.0] + list(bounds)
    for q in (0.1, 0.5, 0.9, 0.99):
        est = fleet.hist_quantile(merged, q)
        assert est == pooled.quantile(q)       # merge loses nothing
        true_q = observations[int(q * (len(observations) - 1))]
        # within one bucket width of the pooled stream's quantile
        import bisect
        i = min(bisect.bisect_left(bounds, true_q), len(bounds) - 1)
        width = edges[i + 1] - edges[i]
        assert abs(est - true_q) <= width, (q, est, true_q, width)


def test_histogram_merge_mismatched_bounds_conservative():
    r1 = {"buckets": [0.1, 1.0], "bucket_counts": [3, 10], "count": 10,
          "sum": 4.0, "min": 0.02, "max": 0.9, "exemplars": {}}
    r2 = {"buckets": [0.5, 2.0], "bucket_counts": [4, 6], "count": 6,
          "sum": 3.0, "min": 0.3, "max": 1.8, "exemplars": {}}
    merged = fleet.merge_histogram_records([r1, r2])
    assert merged["buckets"] == [0.1, 0.5, 1.0, 2.0]   # union of bounds
    assert merged["count"] == 16
    assert merged["min"] == 0.02 and merged["max"] == 1.8
    # cumulative counts stay monotone and end at the full population
    counts = merged["bucket_counts"]
    assert counts == sorted(counts)
    assert counts[-1] == 16
    q99 = fleet.hist_quantile(merged, 0.99)
    assert 0.1 <= q99 <= 2.0


def test_histogram_merge_exemplars_highest_wins():
    base = {"buckets": [0.1, 1.0], "count": 2, "sum": 1.0,
            "min": 0.05, "max": 0.9}
    r1 = dict(base, bucket_counts=[1, 2],
              exemplars={"1": ["trace-a", 0.40]})
    r2 = dict(base, bucket_counts=[1, 2],
              exemplars={"1": ["trace-b", 0.45], "0": ["trace-c", 0.05]})
    merged = fleet.merge_histogram_records([r1, r2])
    # per-bucket collision: the slowest exemplar survives
    assert merged["exemplars"]["1"] == ["trace-b", 0.45]
    assert merged["exemplars"]["0"] == ["trace-c", 0.05]
    assert fleet.hist_exemplar(merged, 0.99) == "trace-b"
    assert fleet.hist_exemplar(merged, 0.01) == "trace-c"


# ----------------------------------------------------- prometheus render
def test_prometheus_fleet_render_rank_labels():
    hist = {"buckets": [0.1, 1.0], "bucket_counts": [2, 5], "count": 5,
            "sum": 1.5, "min": 0.01, "max": 0.9,
            "exemplars": {"1": ["tr99", 0.7]}}
    merged = fleet.merge([
        _snap(0, counters=[("io.batches", {"shard": "a"}, 10)],
              gauges=[("q.depth", {}, 1.0)], hists=[
                  dict(hist, name="step.seconds", labels={})]),
        _snap(1, counters=[("io.batches", {"shard": "a"}, 32)],
              gauges=[("q.depth", {}, 4.0)]),
    ])
    text = prometheus.render(fleet=merged)
    for line in text.splitlines():
        if not line.startswith("#"):
            assert 'rank="' in line, line      # every sample is ranked
    parsed = prometheus.parse(text)
    assert parsed['mxnet_io_batches_total{rank="0",shard="a"}'] == 10
    assert parsed['mxnet_io_batches_total{rank="1",shard="a"}'] == 32
    assert parsed['mxnet_q_depth{rank="0"}'] == 1.0
    assert parsed['mxnet_step_seconds_count{rank="0"}'] == 5
    assert parsed['mxnet_step_seconds_bucket{le="+Inf",rank="0"}'] == 5
    assert parsed["__types__"]["mxnet_io_batches_total"] == "counter"
    assert parsed["__types__"]["mxnet_step_seconds"] == "histogram"

    # default text carries no exemplars; OpenMetrics opts in
    assert "tr99" not in text
    om = prometheus.render(fleet=merged, openmetrics=True)
    assert '# {trace_id="tr99"} 0.7' in om


# ----------------------------------------------------------- ops endpoint
def _get(url, accept=None):
    req = urllib.request.Request(url)
    if accept:
        req.add_header("Accept", accept)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.headers.get("Content-Type", ""), \
                resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), \
            e.read().decode()


def test_opsd_routes(monkeypatch):
    monkeypatch.setenv("MXNET_FLEET_RANK", "4")
    metrics.counter("t.opsd.requests").inc(2)
    srv = mx.telemetry.serve_ops(port=0)
    assert srv.port > 0 and opsd.active() is srv
    assert mx.telemetry.serve_ops(port=0) is srv     # idempotent

    status, ct, body = _get(srv.url + "/metrics")
    assert status == 200 and ct.startswith("text/plain")
    assert prometheus.parse(body)["mxnet_t_opsd_requests_total"] == 2

    status, ct, _body = _get(srv.url + "/metrics",
                             accept="application/openmetrics-text")
    assert status == 200 and ct.startswith("application/openmetrics-text")

    status, _ct, body = _get(srv.url + "/healthz")
    doc = json.loads(body)
    assert status == 200 and doc["ok"] is True
    assert doc["rank"] == 4 and doc["pid"] == os.getpid()
    assert doc["kvstore"] == {"attached": False, "dead_nodes": []}

    status, _ct, body = _get(srv.url + "/varz")
    doc = json.loads(body)
    assert status == 200
    assert doc["env"]["MXNET_FLEET_RANK"] == "4"
    assert not any(k in doc["env"] for k in ("HOME", "PATH"))
    assert doc["telemetry"]["enabled"] in (True, False)
    assert "mesh" in doc

    status, _ct, body = _get(srv.url + "/tracez")
    doc = json.loads(body)
    assert status == 200
    assert isinstance(doc["slowest"], list)
    assert isinstance(doc["traces_buffered"], int)

    status, _ct, body = _get(srv.url + "/fleetz")
    doc = json.loads(body)
    assert status == 200 and doc["schema"] == fleet.SCHEMA_VERSION
    assert doc["rank"] == 4
    assert any(c["name"] == "t.opsd.requests" for c in doc["counters"])

    status, _ct, body = _get(srv.url + "/")
    assert status == 200 and "/fleetz" in json.loads(body)["routes"]
    status, _ct, _body = _get(srv.url + "/nope")
    assert status == 404

    opsd.stop_ops()
    assert opsd.active() is None


def test_opsd_healthz_degrades_on_open_breaker():
    g = metrics.gauge("t.breaker.opsd.state")
    g.set(2)                                   # OPEN
    srv = mx.telemetry.serve_ops(port=0)
    status, _ct, body = _get(srv.url + "/healthz")
    doc = json.loads(body)
    assert status == 503 and doc["ok"] is False
    assert doc["breakers"]["t.breaker.opsd.state"]["name"] == "open"

    g.set(0)                                   # closed again
    status, _ct, body = _get(srv.url + "/healthz")
    assert status == 200 and json.loads(body)["ok"] is True


def test_opsd_env_arming(monkeypatch):
    assert opsd.maybe_serve_from_env() is None         # unset: no-op
    monkeypatch.setenv("MXNET_OPS_PORT", "not-a-port")
    assert opsd.maybe_serve_from_env() is None         # malformed: warn
    assert opsd.active() is None
    monkeypatch.setenv("MXNET_OPS_PORT", "0")
    srv = opsd.maybe_serve_from_env()
    assert srv is not None and srv.port > 0


def test_opsd_scrape_during_live_fit_loop():
    """The acceptance shape in miniature: /metrics and /healthz answer
    correctly while a training loop is dispatching (the <2% overhead
    and zero-recompile gates run in benchmarks/telemetry_overhead.py)."""
    mx.telemetry.enable()
    srv = mx.telemetry.serve_ops(port=0)
    scrapes = []

    def cb(p):
        if len(scrapes) < 2:
            scrapes.append(_get(srv.url + "/metrics"))
            scrapes.append(_get(srv.url + "/healthz"))

    rng = np.random.RandomState(3)
    X = rng.rand(64, 8).astype("f")
    y = (X[:, 1] > 0.5).astype("f")
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    mod = mx.mod.Module(mx.sym.SoftmaxOutput(fc, name="softmax"),
                        context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(X, y, batch_size=16), num_epoch=1,
            initializer=mx.initializer.Xavier(),
            batch_end_callback=cb)

    assert len(scrapes) == 2 * 1 or len(scrapes) == 2
    m_status, _ct, m_body = scrapes[0]
    assert m_status == 200
    parsed = prometheus.parse(m_body)
    assert any(k.startswith("mxnet_module_fit") for k in parsed)
    h_status, _ct, h_body = scrapes[1]
    assert h_status == 200 and json.loads(h_body)["ok"] is True

    # after the loop the endpoint sees the finished counters
    _st, _ct, body = _get(srv.url + "/metrics")
    assert prometheus.parse(body)["mxnet_module_fit_batches_total"] == 4


def test_opsd_scrape_during_live_decode_engine():
    """/metrics and /healthz stay correct while a continuous-decode
    engine iterates, and scraping compiles nothing: the engine's
    compile delta after warmup is 0 with the scraper active."""
    from mxnet_tpu.models import transformer as tfm
    from mxnet_tpu.serve import FakeClock

    V, D, L, H, T = 16, 8, 1, 2, 8
    warm = mx.mod.Module(
        tfm.get_symbol(vocab_size=V, d_model=D, n_layer=L, n_head=H,
                       seq_len=4, include_loss=False, max_seq_len=T),
        label_names=[])
    warm.bind([("data", (1, 4))], None, for_training=False)
    warm.init_params(mx.initializer.Xavier())
    args, _ = warm.get_params()

    mx.telemetry.enable()
    eng = mx.serve.DecodeEngine(
        "fleetdec",
        tfm.get_decode_symbol(vocab_size=V, d_model=D, n_layer=L,
                              n_head=H, capacity=T, per_slot=True,
                              max_seq_len=T),
        dict(args), capacity=T, ladder=[2])
    clock = FakeClock()
    sched = mx.serve.DecodeScheduler(eng, clock=clock)
    srv = mx.telemetry.serve_ops(port=0)

    handles = [sched.submit([1, 2], max_new_tokens=3),
               sched.submit([3], max_new_tokens=3)]
    sched.pump(max_iterations=1)

    # scrape mid-decode: the serve.decode.* series are live and ranked 0
    status, _ct, body = _get(srv.url + "/metrics")
    assert status == 200
    parsed = prometheus.parse(body)
    assert parsed['mxnet_serve_decode_requests_total{model="fleetdec"}'] \
        == 2
    status, _ct, body = _get(srv.url + "/healthz")
    assert status == 200 and json.loads(body)["ok"] is True

    sched.pump()
    for h in handles:
        assert len(list(h.result(timeout=5))) == 3
    st = sched.stats()
    assert st["responses"] == 2 and st["errors"] == 0
    assert st["compiles_since_warmup"] == 0    # scraping compiled nothing

    _st, _ct, body = _get(srv.url + "/metrics")
    parsed = prometheus.parse(body)
    assert parsed['mxnet_serve_decode_responses_total{model="fleetdec"}'] \
        == 2
    assert parsed['mxnet_serve_decode_tokens_total{model="fleetdec"}'] == 6


# ------------------------------------------------------------- fleetstat
def _jsonl_rank(path, rank, gen, t, walls_us, phase_of, monitor,
                events=(), counters=()):
    """One synthesized per-rank dump shaped like the chaos run's."""
    lines = [{"type": "meta", "schema": fleet.SCHEMA_VERSION,
              "rank": rank, "host": f"h{rank}", "pid": 100 + rank,
              "num_workers": 3, "generation": gen, "time_unix": t}]
    for wall in walls_us:
        lines.append({"type": "step", "wall_us": wall,
                      "phases_us": dict(phase_of(wall))})
    lines.append({"type": "gauge", "name": "monitor.stat",
                  "labels": {"stat": "loss"}, "value": monitor})
    for ev in events:
        lines.append(dict({"type": "event"}, **ev))
    for name, value in counters:
        lines.append({"type": "counter", "name": name, "labels": {},
                      "value": value})
    with open(path, "w") as f:
        f.write("\n".join(json.dumps(rec) for rec in lines) + "\n")
    return str(path)


def _chaos_shaped_dumps(tmp_path):
    """3 ranks: rank 1 straggles on data_wait, rank 2 is dead (stale
    dump, frozen at generation 0, reported by rank 0) and diverging."""
    def lean(wall):
        return {"data_wait": 2000, "dispatch": wall - 2000}

    def starved(wall):
        return {"data_wait": wall - 8000, "dispatch": 8000}

    f0 = _jsonl_rank(tmp_path / "r0.jsonl", 0, 1, 1000.0,
                     [10000] * 5, lean, monitor=0.52,
                     events=[{"kind": "dead_node", "ranks": [2]}],
                     counters=[("recovery.reexec", 1)])
    f1 = _jsonl_rank(tmp_path / "r1.jsonl", 1, 1, 1000.5,
                     [20000] * 4 + [40000], starved, monitor=0.48,
                     counters=[("recovery.reexec", 1)])
    f2 = _jsonl_rank(tmp_path / "r2.jsonl", 2, 0, 900.0,
                     [10000] * 5, lean, monitor=5.0)
    return [f0, f1, f2]


def test_fleetstat_chaos_shaped_report(tmp_path):
    """The fast tier-1 twin of the @slow chaos assertions: straggler
    attribution, divergence flag, dead-rank timeline and byte-stable
    rendering over synthesized dumps."""
    fleetstat = _tool("fleetstat")
    files = _chaos_shaped_dumps(tmp_path)
    ranks = [fleetstat.load_file(p) for p in files]
    doc = fleetstat.build(ranks)

    assert doc["ranks"] == [0, 1, 2]
    assert doc["generations"] == {"0": 1, "1": 1, "2": 0}

    # straggler: rank 1's mean wall is +140% over the fleet median and
    # the excess sits in data_wait (input starvation, not compute)
    st = doc["step"]["straggler"]
    assert st["rank"] == "1" and st["phase"] == "data_wait"
    assert st["excess_pct"] > 100
    assert doc["step"]["per_rank"]["0"]["p99_over_p50"] == 1.0
    assert doc["step"]["spread_rank"] == "1"
    assert doc["series"]["step.wall.p99_over_p50"] == pytest.approx(2.0)

    # divergence: only rank 2's loss is flagged (leave-one-out z)
    assert len(doc["divergence"]) == 1
    flag = doc["divergence"][0]
    assert flag["rank"] == "2" and flag["z"] > 3
    assert flag["series"].startswith("monitor.stat")

    # dead-rank timeline: stale dump + survivor report + generations
    assert doc["dead"]["stale_ranks"] == ["2"]
    assert doc["dead"]["reported_dead"] == ["2"]
    assert doc["dead"]["lag_seconds"]["2"] == pytest.approx(100.5)
    assert doc["dead"]["recovery"] == {"0": {"reexec": 1},
                                       "1": {"reexec": 1}}

    # byte-determinism: permuted input order, same report text
    text = fleetstat.render(doc)
    doc2 = fleetstat.build([fleetstat.load_file(p)
                            for p in reversed(files)])
    assert fleetstat.render(doc2) == text
    assert "STRAGGLER: rank 1" in text
    assert "RANK 2 DIVERGING" in text
    assert "STALE" in text


def test_fleetstat_loads_snapshot_and_crash_formats(tmp_path):
    fleetstat = _tool("fleetstat")
    metrics.counter("t.fleetstat.items").inc(7)
    fleet.configure(rank=1)
    snap_path = tmp_path / "snap.json"
    snap_path.write_text(json.dumps(fleet.snapshot()))
    rec = fleetstat.load_file(str(snap_path))
    assert rec["rank"] == 1 and rec["had_meta"]
    assert any(c["name"] == "t.fleetstat.items" and c["value"] == 7
               for c in rec["counters"])

    crash = {"type": "crash_report", "rank": 2, "host": "h2",
             "time_unix": 500.0,
             "env": {"MXNET_RECOVERY_GENERATION": "1"},
             "ring": [{"kind": "dead_node", "ts_us": 1, "ranks": [0]},
                      {"kind": "span", "name": "op.X", "ts_us": 2}],
             "metrics": {"counters": {"io.batches": 4}, "gauges": {},
                         "histograms": {}}}
    crash_path = tmp_path / "crash.json"
    crash_path.write_text(json.dumps(crash))
    rec = fleetstat.load_file(str(crash_path))
    assert rec["rank"] == 2 and rec["generation"] == 1
    assert [e["kind"] for e in rec["events"]] == ["dead_node"]
    assert rec["counters"] == [{"name": "io.batches", "labels": {},
                                "value": 4}]


def test_fleetstat_cli(tmp_path, capsys):
    fleetstat = _tool("fleetstat")
    files = _chaos_shaped_dumps(tmp_path)
    assert fleetstat.main(files) == 0
    out = capsys.readouterr().out
    assert "FLEET REPORT — 3 rank(s)" in out

    assert fleetstat.main(files + ["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "merged" not in doc                 # slim machine document
    assert doc["series"]["step.wall.p99_over_p50"] == pytest.approx(2.0)

    assert fleetstat.main([str(tmp_path / "missing.jsonl")]) == 2


def test_fleetstat_scrapes_live_endpoint():
    fleetstat = _tool("fleetstat")
    metrics.counter("t.scrape.items").inc(1)
    fleet.configure(rank=2)
    srv = mx.telemetry.serve_ops(port=0)
    rec = fleetstat.scrape(srv.url)
    assert rec["rank"] == 2 and rec["had_meta"]
    assert rec["health"]["ok"] is True
    assert any(c["name"] == "t.scrape.items" for c in rec["counters"])
    doc = fleetstat.build([rec])
    assert doc["ranks"] == [2]

    with pytest.raises(OSError):
        fleetstat.scrape("http://127.0.0.1:9")     # discard port


# ------------------------------------------------------ perfwatch --fleet
def _fleet_report(path, spread):
    path.write_text(json.dumps(
        {"schema": 1, "ranks": [0, 1],
         "series": {"step.wall.p99_over_p50": spread,
                    "not.a.number": "skip-me"}}))
    return str(path)


def test_perfwatch_fleet_series_regression(tmp_path):
    perfwatch = _tool("perfwatch")
    hist = tmp_path / "hist"
    hist.mkdir()
    good = _fleet_report(tmp_path / "fleet_a.json", 1.2)
    bad = _fleet_report(tmp_path / "fleet_b.json", 2.0)

    runs = perfwatch.load_fleet_reports([good, bad])
    assert [tag for tag, _s in runs] == ["fleet_a.json", "fleet_b.json"]
    assert runs[0][1] == {"fleet.step.wall.p99_over_p50": (1.2, "down")}

    # widening p99/p50 spread across sessions is a regression
    regressions, n_series, n_runs = perfwatch.run(
        history_dir=str(hist), results_dir=str(hist),
        check_gates=False, fleet_reports=[good, bad])
    assert n_runs == 2 and n_series == 1
    assert [r["series"] for r in regressions] == \
        ["fleet.step.wall.p99_over_p50"]

    # an improving spread passes
    regressions, _n, _r = perfwatch.run(
        history_dir=str(hist), results_dir=str(hist),
        check_gates=False, fleet_reports=[bad, good])
    assert regressions == []

    # not a fleetstat --json report -> a loud error, not silence
    junk = tmp_path / "junk.json"
    junk.write_text("{}")
    with pytest.raises(ValueError):
        perfwatch.load_fleet_reports([str(junk)])
    junk.write_text("not json")
    with pytest.raises(ValueError):
        perfwatch.load_fleet_reports([str(junk)])


# ------------------------------------------- diagnose decode sections
_DECODE_COUNTERS = {'serve.decode.requests{model="m"}': 10,
                    'serve.decode.responses{model="m"}': 9,
                    'serve.decode.iterations{model="m"}': 50,
                    'serve.decode.tokens{model="m"}': 200,
                    'serve.decode.joins{model="m"}': 10,
                    'serve.decode.leaves{model="m"}': 9,
                    'serve.decode.migrations{model="m"}': 1}
_DECODE_GAUGES = {'serve.decode.slots{model="m"}': 8,
                  'serve.decode.active{model="m"}': 6,
                  'serve.decode.occupancy{model="m"}': 0.75,
                  'serve.decode.queue.depth{model="m"}': 2}
_DECODE_HIST = {"count": 50, "sum": 1.0, "min": 0.01, "max": 0.09,
                "buckets": {"0.05": 30, "0.1": 50}}


def _assert_decode_section(out):
    assert "decode engine (continuous batching):" in out
    assert "model m: 6/8 slots active (75% occupancy), queue depth 2" \
        in out
    assert "sessions: 10 admitted, 9 completed" in out
    assert "iterations: 50 (200 tokens, 4.00 tokens/iteration)" in out
    assert "churn: 10 joins, 9 leaves, 1 rung migration(s)" in out
    assert "step time: p50" in out


def test_diagnose_decode_section_crash_path():
    diagnose = _tool("diagnose")
    report = {"type": "crash_report", "pid": 1, "where": "serve.decode",
              "exception": {"type": "RuntimeError", "message": "x"},
              "ring": [],
              "metrics": {
                  "counters": dict(_DECODE_COUNTERS),
                  "gauges": dict(_DECODE_GAUGES),
                  "histograms": {
                      'serve.decode.step.seconds{model="m"}':
                          dict(_DECODE_HIST)}}}
    _assert_decode_section(diagnose.render_crash(report))


def test_diagnose_decode_section_jsonl_path():
    diagnose = _tool("diagnose")

    def split(series):
        name, _, rest = series.partition("{")
        return name, {"model": rest.rstrip("}").split('"')[1]}

    lines = []
    for series, v in _DECODE_COUNTERS.items():
        name, labels = split(series)
        lines.append(json.dumps({"type": "counter", "name": name,
                                 "labels": labels, "value": v}))
    for series, v in _DECODE_GAUGES.items():
        name, labels = split(series)
        lines.append(json.dumps({"type": "gauge", "name": name,
                                 "labels": labels, "value": v}))
    lines.append(json.dumps(
        {"type": "histogram", "name": "serve.decode.step.seconds",
         "labels": {"model": "m"}, **_DECODE_HIST}))
    _assert_decode_section(diagnose.render_jsonl(lines))


# ----------------------------------------------------- jsonl meta line
def test_jsonl_meta_line_carries_identity(monkeypatch):
    monkeypatch.setenv("MXNET_FLEET_RANK", "6")
    monkeypatch.setenv("MXNET_RECOVERY_GENERATION", "1")
    first = json.loads(mx.telemetry.jsonl.render().splitlines()[0])
    assert first["type"] == "meta"
    assert first["schema"] == fleet.SCHEMA_VERSION
    assert first["rank"] == 6 and first["generation"] == 1
    assert first["time_unix"] > 1.7e9          # wall clock, not perf ctr
