"""KVStore tests (mirrors reference tests/python/unittest/test_kvstore.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def init_kv(kind="local"):
    kv = mx.kv.create(kind)
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


def check_diff_to_scalar(A, x):
    assert (A.asnumpy() == x).all(), A.asnumpy()


def test_single_kv_pair():
    kv = init_kv()
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)


def test_list_kv_pair():
    kv = init_kv()
    kv.push(KEYS, [mx.nd.ones(SHAPE) * 4] * len(KEYS))
    val = [mx.nd.empty(SHAPE)] * len(KEYS)
    kv.pull(KEYS, out=val)
    for v in val:
        check_diff_to_scalar(v, 4)


def test_aggregator():
    """Push a list of per-device values -> reduced sum. reference:
    test_kvstore.py test_aggregator (4 'devices')."""
    kv = init_kv("device")
    num_devs = 4
    devs = [mx.cpu(0)] * num_devs
    vals = [mx.nd.ones(SHAPE, d) for d in devs]
    kv.push(3, vals)
    out = [mx.nd.empty(SHAPE, d) for d in devs]
    kv.pull(3, out=out)
    for v in out:
        check_diff_to_scalar(v, num_devs)
    # list of keys, 4 devices each
    kv.push(KEYS, [[mx.nd.ones(SHAPE) * 2.0] * num_devs] * len(KEYS))
    outs = [[mx.nd.empty(SHAPE) for _ in range(num_devs)]
            for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for olist in outs:
        for o in olist:
            check_diff_to_scalar(o, num_devs * 2.0)


def test_updater():
    """reference: test_kvstore.py test_updater — custom updater does +=."""
    kv = init_kv()

    def updater(key, recv, local):
        local += recv

    kv._set_updater(updater)
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)
    num_push = 4
    for _ in range(num_push):
        kv.push(3, mx.nd.ones(SHAPE))
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1 + num_push)


def test_set_optimizer_semantics():
    """The dist_sync arithmetic invariant (reference:
    tests/nightly/dist_sync_kvstore.py:30-45): with the Test optimizer
    (w += rescale*g), after nrepeat pushes of ones the pulled value is
    nrepeat * rate + init."""
    kv = mx.kv.create("local")
    kv.init(9, mx.nd.ones(SHAPE))
    opt = mx.optimizer.Test(rescale_grad=0.5)
    kv.set_optimizer(opt)
    nrepeat = 3
    for _ in range(nrepeat):
        kv.push(9, mx.nd.ones(SHAPE) * 2)
    val = mx.nd.empty(SHAPE)
    kv.pull(9, out=val)
    check_diff_to_scalar(val, 1 + nrepeat * 0.5 * 2)


def test_str_keys():
    kv = mx.kv.create("local")
    kv.init("w0", mx.nd.zeros(SHAPE))
    kv.push("w0", mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull("w0", out=val)
    check_diff_to_scalar(val, 1)


def test_dist_async_unsupported():
    import pytest
    with pytest.raises(mx.MXNetError):
        mx.kv.create("dist_async")


def test_dist_sync_single_process():
    kv = mx.kv.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)


# ---------------------------------------------------------------------------
# bucket scheduler (ready-order overlapped all-reduce, kvstore_sched.py)
# ---------------------------------------------------------------------------

def _dist_kv(keys_shapes, dtype=np.float32):
    kv = mx.kv.create("dist_sync")
    for k, s in keys_shapes.items():
        kv.init(k, mx.nd.zeros(s, dtype=dtype))
    return kv


def test_bucket_straddle_boundary(monkeypatch):
    """An array bigger than MXNET_KVSTORE_BUCKET_BYTES must get its own
    bucket (and survive the size-class padding round trip) while its
    neighbors pack separately — values must come back exact."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", str(1 << 12))  # 4 KiB
    shapes = {0: (8, 8), 1: (40, 40), 2: (7, 3)}     # 1 straddles: 6.4 KB
    kv = _dist_kv(shapes)
    rs = np.random.RandomState(0)
    vals = {k: rs.randn(*s).astype(np.float32) for k, s in shapes.items()}
    kv.push(list(shapes), [mx.nd.array(vals[k]) for k in shapes])
    outs = {k: mx.nd.empty(s) for k, s in shapes.items()}
    kv.pull(list(shapes), out=[outs[k] for k in shapes])
    for k in shapes:
        np.testing.assert_allclose(outs[k].asnumpy(), vals[k], rtol=1e-6)
    # the big key went alone; >= 2 buckets total for the call
    logs = list(kv._sched.bucket_log)
    assert len(logs) >= 2, logs
    big = [b for b in logs if 1 in b["key_ids"]]
    assert len(big) == 1 and big[0]["key_ids"] == [1], logs


def test_mixed_dtype_push():
    """fp32 + bf16 keys in ONE push call reduce through separate
    same-dtype buckets and keep their dtypes."""
    import jax.numpy as jnp
    kv = mx.kv.create("dist_sync")
    kv.init(0, mx.nd.zeros((4, 4)))
    kv.init(1, mx.nd.NDArray(jnp.zeros((6, 2), jnp.bfloat16)))
    v32 = mx.nd.ones((4, 4)) * 3
    v16 = mx.nd.NDArray(jnp.full((6, 2), 2.0, jnp.bfloat16))
    kv.push([0, 1], [v32, v16])
    o32, o16 = mx.nd.empty((4, 4)), \
        mx.nd.NDArray(jnp.zeros((6, 2), jnp.bfloat16))
    kv.pull([0, 1], out=[o32, o16])
    assert (o32.asnumpy() == 3).all()
    assert o16.asjax().dtype == jnp.bfloat16
    assert (np.asarray(o16.asjax(), np.float32) == 2).all()
    # one bucket per dtype
    logs = list(kv._sched.bucket_log)
    assert len(logs) == 2, logs


def test_bucketed_equals_unbucketed(monkeypatch):
    """Reduced values through the bucket scheduler must match the
    unbucketed per-array collective (the equivalence oracle)."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", str(1 << 10))
    shapes = {5: (17,), 6: (31, 3), 7: (257,), 8: (2, 2, 2)}
    kv = _dist_kv(shapes)
    rs = np.random.RandomState(1)
    vals = {k: rs.randn(*s).astype(np.float32) for k, s in shapes.items()}
    kv.push(list(shapes), [mx.nd.array(vals[k]) for k in shapes])
    outs = {k: mx.nd.empty(s) for k, s in shapes.items()}
    kv.pull(list(shapes), out=[outs[k] for k in shapes])
    for k, s in shapes.items():
        direct = np.asarray(
            kv._allreduce([mx.nd.array(vals[k])])[0]).reshape(s)
        np.testing.assert_array_equal(outs[k].asnumpy(), direct, err_msg=k)


def test_size_class_jit_accounting(monkeypatch):
    """Odd/tiny flat lengths must collapse onto power-of-two size
    classes: many distinct gradient lengths -> a handful of `_sum_jit`
    shapes (one trace per class), not one per length."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", str(1 << 30))
    lengths = [3, 5, 7, 9, 11, 33, 65, 127, 129, 255, 257, 511, 513]
    shapes = {i: (n,) for i, n in enumerate(lengths)}
    kv = _dist_kv(shapes)
    # separate pushes -> one bucket (and one collective) per length
    for i, n in enumerate(lengths):
        kv.push(i, mx.nd.ones((n,)))
        out = mx.nd.empty((n,))
        kv.pull(i, out=out)
        check_diff_to_scalar(out, 1)
    # 13 distinct lengths collapse onto the log-spaced class ladder
    # (8, 16, 64, 128, 256, 512, 1024 for L=8 local devices)
    n_classes = len(kv._sum_jit_shapes)
    assert n_classes <= 7, kv._sum_jit_shapes
    # every class is (dtype, L * 2^k)
    for _, padded in kv._sum_jit_shapes:
        chunk = padded // kv._local
        assert chunk * kv._local == padded
        assert chunk & (chunk - 1) == 0, padded
    snap = mx.telemetry.metrics.snapshot()
    assert snap["gauges"].get("kvstore.allreduce.size_classes") == n_classes


def test_push_priority_orders_dispatch(monkeypatch):
    """push(priority=...) orders bucket dispatch: higher-priority keys
    go on the wire first regardless of call order. Cap of 20 bytes fits
    exactly one 16-byte key per bucket but never fills a bucket at
    stage time, so the whole call stays pending and the flush cuts
    buckets in priority order."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "20")
    shapes = {0: (4,), 1: (4,), 2: (4,)}
    kv = _dist_kv(shapes)
    kv.push([0, 1, 2],
            [mx.nd.ones((4,)), mx.nd.ones((4,)), mx.nd.ones((4,))],
            priority=[0, 5, 2])
    kv._flush_pending()
    order = [b["key_ids"][0] for b in kv._sched.bucket_log]
    assert order == [1, 2, 0], order


def test_overlap_disabled_is_synchronous(monkeypatch):
    """MXNET_KVSTORE_OVERLAP=0 applies every push inside the call."""
    monkeypatch.setenv("MXNET_KVSTORE_OVERLAP", "0")
    kv = _dist_kv({0: (4, 4)})
    kv.push(0, mx.nd.ones((4, 4)))
    assert kv._sched.in_flight() == 0
    assert len(kv._sched.bucket_log) == 1
    val = mx.nd.empty((4, 4))
    kv.pull(0, out=val)
    check_diff_to_scalar(val, 1)


def test_repush_before_pull_flushes():
    """Two pushes of one key without an intervening pull are two
    logical reductions (the updater runs once per push)."""
    kv = _dist_kv({0: (4, 4)})

    seen = []

    def updater(key, recv, local):
        seen.append(np.array(recv.asnumpy()))
        local += recv

    kv._set_updater(updater)
    kv.push(0, mx.nd.ones((4, 4)))
    kv.push(0, mx.nd.ones((4, 4)) * 2)
    val = mx.nd.empty((4, 4))
    kv.pull(0, out=val)
    check_diff_to_scalar(val, 3)
    assert len(seen) == 2
