"""KVStore tests (mirrors reference tests/python/unittest/test_kvstore.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def init_kv(kind="local"):
    kv = mx.kv.create(kind)
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


def check_diff_to_scalar(A, x):
    assert (A.asnumpy() == x).all(), A.asnumpy()


def test_single_kv_pair():
    kv = init_kv()
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)


def test_list_kv_pair():
    kv = init_kv()
    kv.push(KEYS, [mx.nd.ones(SHAPE) * 4] * len(KEYS))
    val = [mx.nd.empty(SHAPE)] * len(KEYS)
    kv.pull(KEYS, out=val)
    for v in val:
        check_diff_to_scalar(v, 4)


def test_aggregator():
    """Push a list of per-device values -> reduced sum. reference:
    test_kvstore.py test_aggregator (4 'devices')."""
    kv = init_kv("device")
    num_devs = 4
    devs = [mx.cpu(0)] * num_devs
    vals = [mx.nd.ones(SHAPE, d) for d in devs]
    kv.push(3, vals)
    out = [mx.nd.empty(SHAPE, d) for d in devs]
    kv.pull(3, out=out)
    for v in out:
        check_diff_to_scalar(v, num_devs)
    # list of keys, 4 devices each
    kv.push(KEYS, [[mx.nd.ones(SHAPE) * 2.0] * num_devs] * len(KEYS))
    outs = [[mx.nd.empty(SHAPE) for _ in range(num_devs)]
            for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for olist in outs:
        for o in olist:
            check_diff_to_scalar(o, num_devs * 2.0)


def test_updater():
    """reference: test_kvstore.py test_updater — custom updater does +=."""
    kv = init_kv()

    def updater(key, recv, local):
        local += recv

    kv._set_updater(updater)
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)
    num_push = 4
    for _ in range(num_push):
        kv.push(3, mx.nd.ones(SHAPE))
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1 + num_push)


def test_set_optimizer_semantics():
    """The dist_sync arithmetic invariant (reference:
    tests/nightly/dist_sync_kvstore.py:30-45): with the Test optimizer
    (w += rescale*g), after nrepeat pushes of ones the pulled value is
    nrepeat * rate + init."""
    kv = mx.kv.create("local")
    kv.init(9, mx.nd.ones(SHAPE))
    opt = mx.optimizer.Test(rescale_grad=0.5)
    kv.set_optimizer(opt)
    nrepeat = 3
    for _ in range(nrepeat):
        kv.push(9, mx.nd.ones(SHAPE) * 2)
    val = mx.nd.empty(SHAPE)
    kv.pull(9, out=val)
    check_diff_to_scalar(val, 1 + nrepeat * 0.5 * 2)


def test_str_keys():
    kv = mx.kv.create("local")
    kv.init("w0", mx.nd.zeros(SHAPE))
    kv.push("w0", mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull("w0", out=val)
    check_diff_to_scalar(val, 1)


def test_dist_async_unsupported():
    import pytest
    with pytest.raises(mx.MXNetError):
        mx.kv.create("dist_async")


def test_dist_sync_single_process():
    kv = mx.kv.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.push(3, mx.nd.ones(SHAPE))
    val = mx.nd.empty(SHAPE)
    kv.pull(3, out=val)
    check_diff_to_scalar(val, 1)
