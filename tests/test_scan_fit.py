"""K-step scan-fused training (Module.fit steps_per_dispatch).

One jitted ``lax.scan`` program advances K batches per device dispatch
(ISSUE 3 tentpole): params/optimizer-state/rng ride the donated carry,
per-step outputs + metric counts come back stacked, partial tail
windows fall back to single fused steps. These tests pin (a) numerical
equivalence against K single fused steps — including a mid-run
``mx.random.seed()`` and a partial tail — and (b) the dispatch-count
contract counted via ``telemetry.wrap_dispatch``.
"""
import numpy as np

import mxnet_tpu as mx


def _dropout_mlp():
    # dropout makes the rng chain part of the numerics, so key handling
    # differences between the scan carry and per-step splits would show
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu")
    drop = mx.sym.Dropout(act, p=0.3)
    fc2 = mx.sym.FullyConnected(drop, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _init_args(rs):
    return {
        "fc1_weight": mx.nd.array(rs.randn(8, 6).astype(np.float32) * 0.1),
        "fc1_bias": mx.nd.array(np.zeros(8, np.float32)),
        "fc2_weight": mx.nd.array(rs.randn(3, 8).astype(np.float32) * 0.1),
        "fc2_bias": mx.nd.array(np.zeros(3, np.float32)),
    }


def _fit(K, n_batches=10, batch=4, reseed_at=3, prefetch=False):
    """Fit one epoch at the given steps_per_dispatch; returns params,
    fused optimizer states, and the per-batch metric trajectory."""
    rs = np.random.RandomState(0)
    X = rs.rand(n_batches * batch, 6).astype(np.float32)
    y = rs.randint(0, 3, (n_batches * batch,)).astype(np.float32)
    init = _init_args(np.random.RandomState(1))

    mx.random.seed(7)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    if prefetch:
        it = mx.io.PrefetchingIter(it)
    mod = mx.mod.Module(_dropout_mlp(), context=mx.cpu())
    accs = []

    def cb(param):
        if param.nbatch == reseed_at:
            # mid-run re-seed at a step boundary: both arrangements must
            # re-draw the device rng chain at the next dispatch
            mx.random.seed(1234)
        accs.append(param.eval_metric.get()[1])

    mod.fit(it, num_epoch=1, steps_per_dispatch=K, batch_end_callback=cb,
            arg_params={k: v.copy() for k, v in init.items()},
            optimizer_params=(("learning_rate", 0.1), ("momentum", 0.9)))
    args, _ = mod.get_params()
    states = {k: np.asarray(v)
              for k, v in mod._exec_group._fused_states.items()}
    return ({k: v.asnumpy() for k, v in args.items()}, states, accs)


def test_scan_k4_matches_single_steps():
    """K=4 over 10 batches = two scan windows + a 2-batch tail (single
    fused steps), with a reseed after batch 3: params, optimizer state
    and per-batch metric values must match K=1 to fp tolerance."""
    p1, s1, a1 = _fit(1)
    p4, s4, a4 = _fit(4)
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    for k in s1:
        np.testing.assert_allclose(s1[k], s4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a1, a4, rtol=1e-12)


def test_scan_stacked_prefetch_matches_single_steps():
    """The PrefetchingIter.stack_windows path (producer-stacked windows
    landed via the prefetch thread) must reproduce the same numerics."""
    p1, s1, a1 = _fit(1)
    p4, s4, a4 = _fit(4, prefetch=True)
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
    np.testing.assert_allclose(a1, a4, rtol=1e-12)


def test_scan_k8_dispatch_count():
    """The dispatch-amortization gate: at K=8, a 32-batch fit must issue
    <= 2 dispatches per 8 batches (it issues exactly 1: 4 total), vs 32
    at K=1 — counted via telemetry.wrap_dispatch's executor.dispatch."""
    rs = np.random.RandomState(0)
    n_batches, batch = 32, 4
    X = rs.rand(n_batches * batch, 6).astype(np.float32)
    y = rs.randint(0, 3, (n_batches * batch,)).astype(np.float32)

    def dispatches(K):
        it = mx.io.NDArrayIter(X, y, batch_size=batch)
        mod = mx.mod.Module(_dropout_mlp(), context=mx.cpu())
        mod.bind(it.provide_data, it.provide_label)
        mod.init_params(arg_params=_init_args(np.random.RandomState(1)))
        mod.init_optimizer(
            optimizer_params=(("learning_rate", 0.1), ("momentum", 0.9)))
        mx.telemetry.reset()
        mx.telemetry.enable()
        try:
            mod.fit(it, num_epoch=1, steps_per_dispatch=K,
                    optimizer_params=(("learning_rate", 0.1),
                                      ("momentum", 0.9)))
        finally:
            mx.telemetry.disable()
        snap = mx.telemetry.snapshot()
        return snap["counters"].get("executor.dispatch", 0)

    d8 = dispatches(8)
    assert d8 * 8 <= 2 * n_batches, f"{d8} dispatches for {n_batches}"
    assert d8 <= 8, d8                     # acceptance bound
    d1 = dispatches(1)
    assert d1 >= n_batches, d1             # one per batch without scan


def test_scan_env_var_default(monkeypatch):
    """MXNET_STEPS_PER_DISPATCH drives fit's default window size."""
    monkeypatch.setenv("MXNET_STEPS_PER_DISPATCH", "4")
    rs = np.random.RandomState(0)
    X = rs.rand(32, 6).astype(np.float32)
    y = rs.randint(0, 3, (32,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=4)
    mod = mx.mod.Module(_dropout_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=1,
            optimizer_params=(("learning_rate", 0.1),))
    assert mod._steps_per_dispatch == 4
    assert mod._exec_group._scan_K == 4


def test_stacked_databatch_split_roundtrip():
    """split() recovers the per-step batches a window was stacked from
    (the partial-tail fallback path)."""
    rs = np.random.RandomState(3)
    batches = [mx.io.DataBatch([mx.nd.array(rs.rand(4, 6))],
                               [mx.nd.array(rs.rand(4))], pad=p)
               for p in (0, 0, 2)]
    import jax.numpy as jnp
    stacked = mx.io.StackedDataBatch(
        [mx.nd.NDArray(jnp.stack([b.data[0].asjax() for b in batches]))],
        [mx.nd.NDArray(jnp.stack([b.label[0].asjax() for b in batches]))],
        pads=[b.pad for b in batches])
    assert stacked.steps == 3
    parts = stacked.split()
    assert [p.pad for p in parts] == [0, 0, 2]
    for orig, part in zip(batches, parts):
        np.testing.assert_array_equal(orig.data[0].asnumpy(),
                                      part.data[0].asnumpy())
        np.testing.assert_array_equal(orig.label[0].asnumpy(),
                                      part.label[0].asnumpy())


def test_prefetch_stack_windows_shapes():
    """stack_windows(K) yields (K, batch, ...) windows plus a partial
    tail window, covering the dataset exactly once."""
    rs = np.random.RandomState(0)
    X = rs.rand(40, 6).astype(np.float32)     # 10 batches of 4
    y = rs.randint(0, 3, (40,)).astype(np.float32)
    it = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, y, batch_size=4))
    it.stack_windows(4)
    seen = []
    for w in it:
        assert isinstance(w, mx.io.StackedDataBatch)
        assert w.data[0].shape[1:] == (4, 6)
        seen.append(w.steps)
    assert seen == [4, 4, 2]
    it.reset()                                # epoch 2 identical
    assert [w.steps for w in it] == [4, 4, 2]
    it.stack_windows(1)                       # back to per-batch mode
    batches = list(it)
    assert len(batches) == 10
    assert not isinstance(batches[0], mx.io.StackedDataBatch)
