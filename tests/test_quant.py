"""Int8/fp8 PTQ tiers: per-channel quantization, graph rewrite,
kernel-tier gates, export round-trip, quantized serving.

Everything on the CPU mesh (Pallas interpret mode); the tolerance class
is quant.INT8_TOL (int8-vs-float) / quant.FP8_TOL (fp8-vs-float) and
the standard tier tolerances for pallas-vs-xla of the SAME quantized
op.
"""
import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kernel_tier, program_cache
from mxnet_tpu.ops import quant
from mxnet_tpu.ops.registry import get_op


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("MXNET_KERNEL_TIER", raising=False)
    monkeypatch.delenv("MXNET_SERVE_QUANTIZE", raising=False)
    kernel_tier.clear()
    yield
    kernel_tier.clear()


def _mlp_symbol():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=32, name="f1")
    act = mx.sym.Activation(fc, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="f2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _convnet_symbol():
    data = mx.sym.var("data")
    c = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                           pad=(1, 1), name="c1")
    a = mx.sym.Activation(c, act_type="relu")
    f = mx.sym.FullyConnected(a, num_hidden=10, name="f1")
    return mx.sym.SoftmaxOutput(f, name="softmax")


def _bound(sym, data_shape):
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind([("data", data_shape)], [("softmax_label",
                                       (data_shape[0],))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    return mod


# ------------------------------------------------------------ numerics
def test_quantize_per_channel_roundtrip():
    rng = np.random.RandomState(0)
    w = rng.randn(16, 64).astype(np.float32) * np.linspace(
        0.01, 3.0, 16)[:, None]          # per-channel dynamic range
    q, s = quant.quantize_per_channel(w)
    assert q.dtype == np.int8 and s.shape == (16,)
    back = np.asarray(quant.dequantize(jnp.asarray(q), jnp.asarray(s)))
    # per-channel error bound: half an lsb of each channel's scale
    assert np.all(np.abs(back - w) <= 0.5 * s[:, None] + 1e-7)
    # a global (per-tensor) scale would be ~100x worse on channel 0
    zero = np.zeros((4, 8), np.float32)
    qz, sz = quant.quantize_per_channel(zero)
    assert np.all(qz == 0) and np.all(sz == 1.0)


# -------------------------------------------------------- graph rewrite
def test_quantize_symbol_structure():
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    ap, _ = mod.get_params()
    assert quant.quantizable_weights(sym, ap) == ["f1_weight",
                                                  "f2_weight"]
    qsym, qargs = quant.quantize_symbol(sym, ap)
    ops = {n.op for n in qsym._topo_nodes() if not n.is_variable}
    assert "FullyConnected" not in ops
    assert "QuantizedFullyConnected" in ops
    assert {"f1_weight_q", "f1_weight_scale", "f2_weight_q",
            "f2_weight_scale", "f1_bias", "f2_bias"} <= set(qargs)
    assert "f1_weight" not in qargs
    assert qargs["f1_weight_q"].dtype == np.int8
    # node/output names unchanged — downstream wiring intact
    assert qsym.list_outputs() == sym.list_outputs()


def test_quantize_symbol_rejects_unquantizable():
    data = mx.sym.var("data")
    out = mx.sym.Activation(data, act_type="relu")
    with pytest.raises(mx.base.MXNetError):
        quant.quantize_symbol(mx.sym.SoftmaxOutput(out), {})


def test_quantized_outputs_within_tolerance():
    for sym_fn, shape in ((_mlp_symbol, (4, 16)),
                          (_convnet_symbol, (4, 3, 8, 8))):
        sym = sym_fn()
        mod = _bound(sym, shape)
        ap, xp = mod.get_params()
        qsym, qargs = quant.quantize_symbol(sym, ap)
        qmod = mx.mod.Module(qsym, context=mx.cpu())
        qmod.bind([("data", shape)], [("softmax_label", (shape[0],))],
                  for_training=False)
        qmod.init_params(initializer=None, arg_params=qargs,
                         aux_params=xp)
        # the int8 weights bind int8 CELLS (no silent f32 upcast)
        wq = qmod._exec_group.executor.arg_dict
        qnames = [n for n in wq if n.endswith("_q")]
        assert qnames and all(wq[n].dtype == np.int8 for n in qnames)
        x = np.random.RandomState(1).rand(*shape).astype(np.float32)
        batch = mx.io.DataBatch([mx.nd.array(x)], [])
        mod.forward(batch, is_train=False)
        ref = mod.get_outputs()[0].asnumpy()
        qmod.forward(batch, is_train=False)
        got = qmod.get_outputs()[0].asnumpy()
        assert np.allclose(ref, got, **quant.INT8_TOL)


# ------------------------------------------------------- kernel tier
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_quantized_fc_pallas_gate(dtype):
    qfc = get_op("QuantizedFullyConnected")
    attrs = qfc.normalize_attrs({"num_hidden": 32})
    ok, err = kernel_tier.numerics_gate(
        qfc, attrs, [(8, 64), (32, 64), (32,), (32,)],
        [dtype, "int8", "float32", "float32"])
    assert ok, f"max_abs_err={err}"


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_quantized_conv_pallas_gate(dtype):
    qcv = get_op("QuantizedConvolution")
    attrs = qcv.normalize_attrs({"kernel": (3, 3), "num_filter": 8,
                                 "pad": (1, 1)})
    ok, err = kernel_tier.numerics_gate(
        qcv, attrs, [(2, 4, 8, 8), (8, 4, 3, 3), (8,), (8,)],
        [dtype, "int8", "float32", "float32"])
    assert ok, f"max_abs_err={err}"


def test_quantized_pallas_never_selected_when_slower(monkeypatch):
    """The quantized kernels ride the same scripted-timer autotune: a
    slower measurement can never select them."""
    qfc = get_op("QuantizedFullyConnected")
    attrs = qfc.normalize_attrs({"num_hidden": 32})
    shapes = [(8, 64), (32, 64), (32,), (32,)]
    dtypes = ["float32", "int8", "float32", "float32"]
    times = iter([1.0, 3.0])                   # xla 1ms, pallas 3ms
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times) / 1e3)
    assert kernel_tier.resolve(qfc, attrs, shapes, dtypes,
                               False) == "xla"
    assert "slower" in kernel_tier.decisions()[-1]["reason"]


# ------------------------------------------------------------- export
def test_export_quantize_roundtrip(tmp_path):
    from mxnet_tpu.predict import export_model, Predictor
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    ap, xp = mod.get_params()
    pf = export_model(str(tmp_path / "f.mxp"), sym, ap, xp,
                      {"data": (4, 16)})
    pq = export_model(str(tmp_path / "q.mxp"), sym, ap, xp,
                      {"data": (4, 16)}, quantize="int8")
    # the int8 artifact ships smaller weights
    assert os.path.getsize(pq) < os.path.getsize(pf)
    predf, predq = Predictor(pf), Predictor(pq)
    assert predf.quantize is None
    assert predq.quantize == "int8"
    assert predq._manifest["quantized_weights"] == ["f1_weight",
                                                    "f2_weight"]
    x = np.random.RandomState(2).rand(4, 16).astype(np.float32)
    of = predf.forward(data=x)[0].asnumpy()
    oq = predq.forward(data=x)[0].asnumpy()
    assert np.allclose(of, oq, **quant.INT8_TOL)
    assert not np.array_equal(of, oq)       # it IS quantized


def test_export_quantize_rejects_unknown_dtype(tmp_path):
    from mxnet_tpu.predict import export_model
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    ap, xp = mod.get_params()
    with pytest.raises(mx.base.MXNetError):
        export_model(str(tmp_path / "x.mxp"), sym, ap, xp,
                     {"data": (4, 16)}, quantize="int4")


# ------------------------------------------------------------- serving
def test_int8_serve_zero_compiles_and_tolerance():
    """The acceptance gate: compile_count() delta == 0 after warmup on
    the int8 ladder, outputs within the tolerance class of the float
    ladder, stats report the quantized tier."""
    sym = _mlp_symbol()
    mod = _bound(sym, (8, 16))
    ap, xp = mod.get_params()
    server = mx.serve.serve(mod, name="q8", ladder=[1, 2, 4, 8],
                            compute_dtype="int8", start=False)
    try:
        eng = server._registry.entries()[0].engine
        assert eng.quantized == "int8"
        assert eng._compute_dtype is None       # rewrite consumed it
        assert eng.warmup_compiles > 0
        mark = program_cache.compile_count()
        x = np.random.RandomState(3).rand(4, 16).astype(np.float32)
        out = eng.forward(4, {"data": x})[0].asnumpy()
        assert program_cache.compile_count() - mark == 0
        assert eng.compiles_since_warmup() == 0
        assert server.stats()["models"]["q8"]["quantized"] == "int8"
        # float reference through the original module
        batch = mx.io.DataBatch([mx.nd.array(x)], [])
        fmod = mx.mod.Module(sym, context=mx.cpu())
        fmod.bind([("data", (4, 16))], [("softmax_label", (4,))],
                  for_training=False)
        fmod.init_params(initializer=None, arg_params=ap,
                         aux_params=xp)
        fmod.forward(batch, is_train=False)
        ref = fmod.get_outputs()[0].asnumpy()
        assert np.allclose(ref, out, **quant.INT8_TOL)
    finally:
        server.stop()


def test_serve_quantize_env_default(monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_QUANTIZE", "int8")
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    server = mx.serve.serve(mod, name="envq", ladder=[1, 4],
                            start=False)
    try:
        eng = server._registry.entries()[0].engine
        assert eng.quantized == "int8"
    finally:
        server.stop()


# ----------------------------------------------------------- fp8 tier
def test_fp8_quantize_per_channel_roundtrip():
    rng = np.random.RandomState(0)
    w = rng.randn(16, 64).astype(np.float32) * np.linspace(
        0.01, 3.0, 16)[:, None]
    q, s = quant.quantize_per_channel(w, dtype="fp8")
    assert q.dtype == np.dtype("float8_e4m3fn") and s.shape == (16,)
    back = np.asarray(quant.dequantize(jnp.asarray(q), jnp.asarray(s)))
    # e4m3 keeps 3 mantissa bits: relative error <= 2^-4 per element
    rel = np.abs(back - w) / (np.abs(w) + 1e-9)
    assert float(rel.max()) <= 2 ** -4
    zero = np.zeros((4, 8), np.float32)
    qz, sz = quant.quantize_per_channel(zero, dtype="fp8")
    assert np.all(np.asarray(qz, np.float32) == 0) and np.all(sz == 1.0)


def test_quantize_symbol_fp8_structure():
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    ap, _ = mod.get_params()
    qsym, qargs = quant.quantize_symbol(sym, ap, dtype="fp8")
    ops = {n.op for n in qsym._topo_nodes() if not n.is_variable}
    assert "QuantizedFullyConnected" in ops
    assert qargs["f1_weight_q"].dtype == np.dtype("float8_e4m3fn")
    assert qsym.list_outputs() == sym.list_outputs()


def test_quantize_symbol_rejects_unknown_dtype():
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    ap, _ = mod.get_params()
    with pytest.raises(mx.base.MXNetError, match="int8 or fp8"):
        quant.quantize_symbol(sym, ap, dtype="int4")


def test_fp8_quantized_outputs_within_tolerance():
    for sym_fn, shape in ((_mlp_symbol, (4, 16)),
                          (_convnet_symbol, (4, 3, 8, 8))):
        sym = sym_fn()
        mod = _bound(sym, shape)
        ap, xp = mod.get_params()
        qsym, qargs = quant.quantize_symbol(sym, ap, dtype="fp8")
        qmod = mx.mod.Module(qsym, context=mx.cpu())
        qmod.bind([("data", shape)], [("softmax_label", (shape[0],))],
                  for_training=False)
        qmod.init_params(initializer=None, arg_params=qargs,
                         aux_params=xp)
        # the fp8 weights bind fp8 CELLS (no silent f32 upcast)
        wq = qmod._exec_group.executor.arg_dict
        qnames = [n for n in wq if n.endswith("_q")]
        assert qnames and all(
            wq[n].dtype == np.dtype("float8_e4m3fn") for n in qnames)
        x = np.random.RandomState(1).rand(*shape).astype(np.float32)
        batch = mx.io.DataBatch([mx.nd.array(x)], [])
        mod.forward(batch, is_train=False)
        ref = mod.get_outputs()[0].asnumpy()
        qmod.forward(batch, is_train=False)
        got = qmod.get_outputs()[0].asnumpy()
        assert np.allclose(ref, got, **quant.FP8_TOL)


def test_export_quantize_fp8_roundtrip(tmp_path):
    from mxnet_tpu.predict import export_model, Predictor
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    ap, xp = mod.get_params()
    pf = export_model(str(tmp_path / "f.mxp"), sym, ap, xp,
                      {"data": (4, 16)})
    pq = export_model(str(tmp_path / "q8.mxp"), sym, ap, xp,
                      {"data": (4, 16)}, quantize="fp8")
    assert os.path.getsize(pq) < os.path.getsize(pf)
    predf, predq = Predictor(pf), Predictor(pq)
    assert predq.quantize == "fp8"
    x = np.random.RandomState(2).rand(4, 16).astype(np.float32)
    of = predf.forward(data=x)[0].asnumpy()
    oq = predq.forward(data=x)[0].asnumpy()
    assert np.allclose(of, oq, **quant.FP8_TOL)
    assert not np.array_equal(of, oq)       # it IS quantized


def test_fp8_serve_zero_compiles_and_tolerance():
    """The fp8 acceptance gate: compile_count() delta == 0 after warmup
    on the fp8 ladder, outputs within FP8_TOL of the float ladder."""
    sym = _mlp_symbol()
    mod = _bound(sym, (8, 16))
    ap, xp = mod.get_params()
    server = mx.serve.serve(mod, name="q8f", ladder=[1, 2, 4, 8],
                            compute_dtype="fp8", start=False)
    try:
        eng = server._registry.entries()[0].engine
        assert eng.quantized == "fp8"
        assert eng.warmup_compiles > 0
        x = np.random.RandomState(3).rand(8, 16).astype(np.float32)
        mark = program_cache.compile_count()
        outs = []
        for n in (1, 2, 4, 8):          # every rung stays pinned
            outs.append(eng.forward(n, {"data": x[:n]})[0].asnumpy())
        assert program_cache.compile_count() - mark == 0
        assert eng.compiles_since_warmup() == 0
        assert server.stats()["models"]["q8f"]["quantized"] == "fp8"
        batch = mx.io.DataBatch([mx.nd.array(x[:8])], [])
        fmod = mx.mod.Module(sym, context=mx.cpu())
        fmod.bind([("data", (8, 16))], [("softmax_label", (8,))],
                  for_training=False)
        fmod.init_params(initializer=None, arg_params=ap,
                         aux_params=xp)
        fmod.forward(batch, is_train=False)
        ref = fmod.get_outputs()[0].asnumpy()
        assert np.allclose(ref, outs[-1], **quant.FP8_TOL)
    finally:
        server.stop()


def test_serve_quantize_env_fp8(monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_QUANTIZE", "fp8")
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    server = mx.serve.serve(mod, name="envq8", ladder=[1, 4],
                            start=False)
    try:
        eng = server._registry.entries()[0].engine
        assert eng.quantized == "fp8"
    finally:
        server.stop()


def test_int8_serve_warm_payload_persists_quantized():
    """The warm-restart payload carries the ALREADY-quantized symbol +
    int8 params (restore re-binds without re-quantizing)."""
    from mxnet_tpu.serve.warm import server_payload
    sym = _mlp_symbol()
    mod = _bound(sym, (4, 16))
    server = mx.serve.serve(mod, name="wq", ladder=[1, 2],
                            compute_dtype="int8", start=False)
    try:
        payload = server_payload(server)
        rec = payload["models"]["wq"]
        assert rec["quantized"] == "int8"
        assert rec["compute_dtype"] is None
        assert rec["arg_params"]["f1_weight_q"].dtype == np.int8
        qsym = mx.sym.load_json(rec["symbol"])
        ops = {n.op for n in qsym._topo_nodes() if not n.is_variable}
        assert "QuantizedFullyConnected" in ops
    finally:
        server.stop()
