"""Imperative autograd: grad_and_loss + the mark_variables/backward tape.

reference behavior: python/mxnet/contrib/autograd.py + autograd.cc
(MarkVariables/RecordImperativeFCompute/ComputeGradient) and
tests/python/unittest/test_contrib_autograd.py.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag


def test_grad_and_loss():
    def f(x):
        return mx.nd.sum(x * x)

    x = mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    grads, loss = ag.grad_and_loss(f)(x)
    np.testing.assert_allclose(loss.asnumpy(), 14.0, rtol=1e-6)
    np.testing.assert_allclose(grads[0].asnumpy(), [2.0, 4.0, 6.0],
                               rtol=1e-6)


def test_marked_backward_arithmetic():
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    gx = mx.nd.zeros((2,))
    ag.mark_variables(x, gx)
    with ag.train_section():
        y = x * x + 3.0 * x
    ag.backward(y)
    np.testing.assert_allclose(gx.asnumpy(), 2 * x.asnumpy() + 3.0,
                               rtol=1e-6)


def test_marked_backward_registry_ops():
    x = mx.nd.array(np.random.RandomState(0).rand(3, 4).astype(np.float32))
    gx = mx.nd.zeros((3, 4))
    ag.mark_variables(x, gx)
    with ag.train_section():
        y = mx.nd.relu(x - 0.5)
        z = mx.nd.sum(y)
    ag.backward(z)
    expect = (x.asnumpy() - 0.5 > 0).astype(np.float32)
    np.testing.assert_allclose(gx.asnumpy(), expect, rtol=1e-6)


def test_backward_grad_req_add():
    x = mx.nd.array(np.array([2.0], np.float32))
    gx = mx.nd.array(np.array([10.0], np.float32))
    ag.mark_variables(x, gx, grad_reqs="add")
    with ag.train_section():
        y = x * x
    ag.backward(y)
    np.testing.assert_allclose(gx.asnumpy(), [14.0], rtol=1e-6)


def test_backward_with_head_grads():
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    gx = mx.nd.zeros((2,))
    ag.mark_variables(x, gx)
    with ag.train_section():
        y = x * 2.0
    ag.backward(y, out_grads=mx.nd.array(np.array([3.0, 5.0], np.float32)))
    np.testing.assert_allclose(gx.asnumpy(), [6.0, 10.0], rtol=1e-6)
