"""Imperative autograd: grad_and_loss + the mark_variables/backward tape.

reference behavior: python/mxnet/contrib/autograd.py + autograd.cc
(MarkVariables/RecordImperativeFCompute/ComputeGradient) and
tests/python/unittest/test_contrib_autograd.py.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag


def test_grad_and_loss():
    def f(x):
        return mx.nd.sum(x * x)

    x = mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    grads, loss = ag.grad_and_loss(f)(x)
    np.testing.assert_allclose(loss.asnumpy(), 14.0, rtol=1e-6)
    np.testing.assert_allclose(grads[0].asnumpy(), [2.0, 4.0, 6.0],
                               rtol=1e-6)


def test_marked_backward_arithmetic():
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    gx = mx.nd.zeros((2,))
    ag.mark_variables(x, gx)
    with ag.train_section():
        y = x * x + 3.0 * x
    ag.backward(y)
    np.testing.assert_allclose(gx.asnumpy(), 2 * x.asnumpy() + 3.0,
                               rtol=1e-6)


def test_marked_backward_registry_ops():
    x = mx.nd.array(np.random.RandomState(0).rand(3, 4).astype(np.float32))
    gx = mx.nd.zeros((3, 4))
    ag.mark_variables(x, gx)
    with ag.train_section():
        y = mx.nd.relu(x - 0.5)
        z = mx.nd.sum(y)
    ag.backward(z)
    expect = (x.asnumpy() - 0.5 > 0).astype(np.float32)
    np.testing.assert_allclose(gx.asnumpy(), expect, rtol=1e-6)


def test_backward_grad_req_add():
    x = mx.nd.array(np.array([2.0], np.float32))
    gx = mx.nd.array(np.array([10.0], np.float32))
    ag.mark_variables(x, gx, grad_reqs="add")
    with ag.train_section():
        y = x * x
    ag.backward(y)
    np.testing.assert_allclose(gx.asnumpy(), [14.0], rtol=1e-6)


def test_backward_with_head_grads():
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    gx = mx.nd.zeros((2,))
    ag.mark_variables(x, gx)
    with ag.train_section():
        y = x * 2.0
    ag.backward(y, out_grads=mx.nd.array(np.array([3.0, 5.0], np.float32)))
    np.testing.assert_allclose(gx.asnumpy(), [6.0, 10.0], rtol=1e-6)


def test_tape_holds_refs_id_reuse_safe():
    """ADVICE r2 (high): a temporary freed mid-section must not have its
    id reused by a later constant — the tape holds strong refs."""
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    gx = mx.nd.zeros((2,))
    ag.mark_variables(x, gx)
    with ag.train_section():
        t = x * 2.0          # recorded; output handle t
        del t                # without tape refs, t's id is free for reuse
        c = mx.nd.array(np.array([7.0, 7.0], np.float32))  # may reuse id
        y = c * x
    ag.backward(y)
    np.testing.assert_allclose(gx.asnumpy(), [7.0, 7.0], rtol=1e-6)


def test_backward_does_not_clobber_unrelated_marked_grads():
    """ADVICE r2 (medium): backward writes only grads of variables the
    current tape consumed — earlier models' buffers stay untouched."""
    a = mx.nd.array(np.array([3.0], np.float32))
    ga = mx.nd.zeros((1,))
    ag.mark_variables(a, ga)
    with ag.train_section():
        ya = a * a
    ag.backward(ya)
    np.testing.assert_allclose(ga.asnumpy(), [6.0], rtol=1e-6)

    b = mx.nd.array(np.array([5.0], np.float32))
    gb = mx.nd.zeros((1,))
    ag.mark_variables(b, gb)
    with ag.train_section():
        yb = b * 3.0
    ag.backward(yb)
    np.testing.assert_allclose(gb.asnumpy(), [3.0], rtol=1e-6)
    # ga must NOT have been zeroed by the second backward
    np.testing.assert_allclose(ga.asnumpy(), [6.0], rtol=1e-6)


def test_backward_prunes_unrelated_branches():
    """Only the sub-graph feeding the requested outputs replays: ops on
    unrelated branches are skipped entirely (reference builds the
    backward graph from the requested heads only, autograd.cc:132-188)."""
    from mxnet_tpu import autograd as ag

    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    z = mx.nd.array(np.array([3.0, 4.0], np.float32))
    gx = mx.nd.zeros((2,))
    gz = mx.nd.zeros((2,))
    ag.mark_variables([x, z], [gx, gz])
    calls = {"side": 0}

    with ag.train_section():
        y = x * x                     # wanted branch
        side_in = z * 2.0             # unrelated branch (its own leaf)

        def side_replay(vals):
            calls["side"] += 1
            return [vals[0] * 10.0]

        side_out = mx.nd.empty((2,))
        ag._record_fn(side_replay, [side_in], [side_in.asjax()],
                      [side_out])
        ag.backward(y)

    np.testing.assert_allclose(gx.asnumpy(), [2.0, 4.0])
    # the unrelated branch was never replayed and its leaf grad untouched
    assert calls["side"] == 0
    np.testing.assert_allclose(gz.asnumpy(), [0.0, 0.0])
