"""Continuous-batching inference server (mxnet_tpu/serve/).

Gates, per ISSUE 8 acceptance:

* every served response is bitwise-equal to a direct
  ``Module.predict``/``Predictor`` forward of the same input (the
  pad/slice batcher is bit-transparent — row-independent inference ops
  plus the SAME bucket program via the process-wide program cache);
* zero XLA compiles after warmup (``program_cache.compile_count``
  deltas + the ``serve.program_cache.compiles_since_warmup`` gauge);
* p99 latency + queue-depth series present in the telemetry registry
  and the Prometheus export;
* deadline-aware flush proven on a deterministic FakeClock: a request
  is dispatched AT its flush instant in a smaller bucket rather than
  kept waiting for a larger one past its deadline.
"""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.serve import (BucketLadder, FakeClock, QueueFullError,
                             bucket_for, pad_rows, run_scripted,
                             slice_rows)


def _mlp(prefix="fc", hidden=8, classes=3):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=hidden,
                               name=f"{prefix}1")
    act = mx.sym.Activation(fc, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes,
                                name=f"{prefix}2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _bound_module(sym, feat=6, batch=4):
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind([("data", (batch, feat))], [("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    return mod


def _direct_predict(sym, mod, x, batch):
    """The oracle the acceptance names: Module.predict of the same
    input through an independent module at the serving bucket size
    (same program via the process-wide cache). Rows beyond a bucket
    multiple ride as NDArrayIter pad rows, which iter_predict drops —
    row-independent inference ops make the valid rows bit-identical
    regardless of pad content."""
    ref = mx.mod.Module(sym, context=mx.cpu())
    ref.bind([("data", (batch,) + x.shape[1:])], for_training=False,
             label_shapes=None)
    arg_params, aux_params = mod.get_params()
    ref.init_params(initializer=None, arg_params=arg_params,
                    aux_params=aux_params)
    n = x.shape[0]
    if n % batch:               # NDArrayIter needs >= one full batch
        x = np.concatenate(
            [x, np.zeros((batch - n % batch,) + x.shape[1:], x.dtype)])
    out = ref.predict(mx.io.NDArrayIter(x, None, batch))
    return out.asnumpy()[:n]


# --------------------------------------------------------------- helpers
def test_pad_slice_roundtrip_and_ladder():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    p = pad_rows(x, 8)
    assert p.shape == (8, 4) and np.array_equal(p[:3], x)
    assert not p[3:].any()
    assert np.array_equal(pad_rows(x, 3), x)          # no-op at the rung
    back = slice_rows([p], 1, 2)[0].asnumpy()
    assert np.array_equal(back, x[1:3])

    lad = BucketLadder([8, 2, 4, 2])
    assert lad.sizes == [2, 4, 8] and lad.max == 8
    assert lad.bucket_for(1) == 2 and lad.bucket_for(5) == 8
    assert lad.bucket_for(9) is None
    assert bucket_for(3, [2, 4]) == 4
    with pytest.raises(mx.base.MXNetError):
        pad_rows(x, 2)                                 # rows > bucket


def test_ladder_env_default(monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_BUCKETS", "4, 1,16")
    assert BucketLadder().sizes == [1, 4, 16]
    monkeypatch.setenv("MXNET_SERVE_BUCKETS", "zero")
    with pytest.raises(mx.base.MXNetError):
        BucketLadder()


# ------------------------------------------------- deterministic scheduler
def test_deadline_flush_fake_clock():
    """A lone request must flush at deadline - exec_estimate (0 on the
    fake clock) in the SMALLEST covering bucket — never held past its
    deadline waiting for a fuller batch."""
    clock = FakeClock()
    sym = _mlp("dl")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2, 4],
                            start=False, clock=clock,
                            default_deadline_ms=50)
    x = np.random.RandomState(0).rand(1, 6).astype(np.float32)
    h = server.submit({"data": x})
    assert server.pump() == 0, "no flush before the deadline instant"
    clock.advance(0.049)
    assert server.pump() == 0
    clock.advance(0.001)                    # exactly t = deadline
    assert server.pump() == 1
    assert h.done() and h.bucket == 1, \
        "the smallest covering bucket serves the deadline flush"
    assert h.latency == pytest.approx(0.050)
    assert not h.missed_deadline()
    stats = server.stats()["models"]["default"]
    assert stats["deadline_misses"] == 0
    assert stats["dispatches"] == 1


def test_full_bucket_flushes_immediately():
    """rows_pending == max bucket leaves no batching benefit in
    waiting: dispatch fires with zero clock advance."""
    clock = FakeClock()
    sym = _mlp("fb")
    server = mx.serve.serve(_bound_module(sym), ladder=[2, 4],
                            start=False, clock=clock,
                            default_deadline_ms=1000)
    rs = np.random.RandomState(1)
    hs = [server.submit({"data": rs.rand(2, 6).astype(np.float32)})
          for _ in range(2)]                # 4 rows == max bucket
    assert server.pump() == 1
    assert all(h.done() for h in hs)
    assert {h.bucket for h in hs} == {4}
    assert all(h.latency == 0.0 for h in hs)


def test_coalesced_batch_slices_per_request():
    """Two queued requests coalesce into one padded bucket; each handle
    gets exactly its own rows back."""
    mx.telemetry.reset()
    clock = FakeClock()
    sym = _mlp("co")
    mod = _bound_module(sym)
    server = mx.serve.serve(mod, ladder=[1, 2, 4], start=False,
                            clock=clock, default_deadline_ms=10)
    rs = np.random.RandomState(2)
    x1 = rs.rand(2, 6).astype(np.float32)
    x2 = rs.rand(1, 6).astype(np.float32)
    h1 = server.submit({"data": x1})
    h2 = server.submit({"data": x2})
    clock.advance(0.010)
    assert server.pump() == 1
    assert h1.bucket == h2.bucket == 4      # 3 rows -> rung 4
    ref = _direct_predict(sym, mod, np.concatenate([x1, x2]), 4)
    assert np.array_equal(h1.result()[0].asnumpy(), ref[:2])
    assert np.array_equal(h2.result()[0].asnumpy(), ref[2:3])
    stats = server.stats()["models"]["default"]
    assert stats["batch_occupancy"] == pytest.approx(0.75)
    assert stats["padding_waste_pct"] == pytest.approx(25.0)


def test_fair_scheduling_round_robin():
    """Two saturated tenants alternate dispatches (least-recently-
    dispatched wins among ready models)."""
    clock = FakeClock()
    server = mx.serve.InferenceServer(clock=clock)
    sym_a, sym_b = _mlp("fa"), _mlp("fb2", hidden=5)
    server.register("a", model=_bound_module(sym_a), ladder=[2])
    server.register("b", model=_bound_module(sym_b), ladder=[2])
    order = []
    rs = np.random.RandomState(3)

    def sub(name):
        h = server.submit({"data": rs.rand(2, 6).astype(np.float32)},
                          model=name)
        h.add_done_callback(lambda _h: order.append(name))
        return h

    for _ in range(2):
        sub("a")
    for _ in range(2):
        sub("b")
    assert server.pump() == 4
    assert order == ["a", "b", "a", "b"], order


def test_queue_full_rejection():
    mx.telemetry.reset()
    clock = FakeClock()
    sym = _mlp("qf")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 4],
                            start=False, clock=clock, max_queue=2,
                            default_deadline_ms=1000)
    x = np.zeros((1, 6), np.float32)
    server.submit({"data": x})
    server.submit({"data": x})
    with pytest.raises(QueueFullError):
        server.submit({"data": x})
    assert server.stats()["models"]["default"]["rejected"] == 1


def test_submit_validation_errors():
    clock = FakeClock()
    sym = _mlp("va")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2],
                            start=False, clock=clock)
    with pytest.raises(mx.base.MXNetError):
        server.submit({"data": np.zeros((1, 7), np.float32)})  # bad feat
    with pytest.raises(mx.base.MXNetError):
        server.submit({"data": np.zeros((3, 6), np.float32)})  # > max
    with pytest.raises(mx.base.MXNetError):
        server.submit({"wrong": np.zeros((1, 6), np.float32)})
    with pytest.raises(mx.base.MXNetError):
        server.submit({"data": np.zeros((1, 6), np.float32)},
                      model="ghost")


def test_dispatch_error_fails_batch_not_server():
    mx.telemetry.reset()
    clock = FakeClock()
    sym = _mlp("er")
    server = mx.serve.serve(_bound_module(sym), ladder=[1],
                            start=False, clock=clock,
                            default_deadline_ms=5)
    engine = server.engine()
    real_forward = engine.forward
    engine.forward = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected"))
    h_bad = server.submit({"data": np.zeros((1, 6), np.float32)})
    clock.advance(0.005)
    server.pump()
    with pytest.raises(RuntimeError, match="injected"):
        h_bad.result(timeout=1)
    engine.forward = real_forward           # server keeps serving
    h_ok = server.submit({"data": np.zeros((1, 6), np.float32)})
    clock.advance(0.005)
    server.pump()
    assert h_ok.result(timeout=1)[0].shape == (1, 3)
    assert server.stats()["models"]["default"]["errors"] == 1


def test_stop_without_drain_fails_pending():
    sym = _mlp("sp")
    server = mx.serve.serve(_bound_module(sym), ladder=[4], start=False,
                            clock=FakeClock(), default_deadline_ms=1000)
    h = server.submit({"data": np.zeros((1, 6), np.float32)})
    server.stop(drain=False)
    with pytest.raises(mx.base.MXNetError):
        h.result(timeout=1)


# ----------------------------------------------------- scripted load path
def test_scripted_arrivals_deterministic():
    """The fast tier-1 loadgen path: scripted arrivals on a FakeClock —
    exact flush instants, no wall-clock sleeps."""
    clock = FakeClock()
    sym = _mlp("sc")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2, 4],
                            start=False, clock=clock,
                            default_deadline_ms=20)
    arrivals = [0.000, 0.004, 0.008, 0.030, 0.031]
    out = run_scripted(
        server, arrivals,
        lambda i, rng: {"data": rng.rand(1, 6).astype(np.float32)},
        slo_ms=25)
    assert out["offered"] == out["completed"] == 5
    assert out["errors"] == 0 and out["deadline_misses"] == 0
    # first three coalesce at the first request's flush instant
    # (t=0.020), so their latencies are exactly 20/16/12 ms
    assert out["latency_ms"]["p99"] == pytest.approx(20.0)
    assert out["p99_within_slo"] is True
    # rerun is bit-identical (fresh server, same script)
    server2 = mx.serve.serve(_bound_module(_mlp("sc2")),
                             ladder=[1, 2, 4], start=False,
                             clock=FakeClock(), default_deadline_ms=20)
    out2 = run_scripted(
        server2, arrivals,
        lambda i, rng: {"data": rng.rand(1, 6).astype(np.float32)},
        slo_ms=25)
    assert out2["latency_ms"] == out["latency_ms"]


# ------------------------------------------------------------ end to end
def test_e2e_two_model_registry_concurrent():
    """The acceptance scenario: concurrent clients, mixed row counts,
    two tenants — bitwise-correct responses, zero compiles after
    warmup, latency/queue metrics in the registry and the Prometheus
    export."""
    mx.program_cache.clear()
    mx.telemetry.reset()
    sym_a, sym_b = _mlp("ea", hidden=8), _mlp("eb", hidden=5, classes=2)
    mod_a = _bound_module(sym_a, feat=6)
    mod_b = _bound_module(sym_b, feat=6)
    server = mx.serve.InferenceServer(default_deadline_ms=200)
    server.register("a", model=mod_a, ladder=[1, 2, 4])
    server.register("b", model=mod_b, ladder=[1, 2, 4])
    compiles_before = mx.program_cache.compile_count()

    results = []
    res_lock = threading.Lock()

    def client(cid):
        rs = np.random.RandomState(100 + cid)
        for j in range(3):
            name = "a" if (cid + j) % 2 == 0 else "b"
            rows = 1 + (cid + j) % 3
            x = rs.rand(rows, 6).astype(np.float32)
            h = server.submit({"data": x}, model=name)
            out = h.result(timeout=30)[0].asnumpy()
            with res_lock:
                results.append((name, x, out, h.bucket))

    with server:
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    assert len(results) == 12
    for name, x, out, bucket in results:
        sym, mod = (sym_a, mod_a) if name == "a" else (sym_b, mod_b)
        ref = _direct_predict(sym, mod, x, bucket)
        assert np.array_equal(out, ref), \
            f"served response differs from direct predict ({name})"

    # zero compiles after warmup — the program-cache counters, the
    # engine-level delta, and the published gauge all agree
    assert mx.program_cache.compile_count() == compiles_before
    stats = server.stats()
    assert stats["compiles_since_warmup"] == 0
    for name in ("a", "b"):
        # (compiles_since_warmup is process-global — model b's warmup
        # counts against a's engine-level mark; the server-level delta
        # above is the steady-state gate)
        assert server.engine(name).programs_resident()
        assert stats["models"][name]["latency_ms"]["p99"] is not None
        assert stats["models"][name]["responses"] == 6

    # latency histogram + queue-depth gauge live in the registry...
    assert mx.telemetry.get_metric("serve.request.latency.seconds",
                                   model="a").count > 0
    assert mx.telemetry.get_metric("serve.queue.depth",
                                   model="b") is not None
    # ...and in the Prometheus exposition
    prom = mx.telemetry.prometheus.render()
    assert "mxnet_serve_request_latency_seconds_bucket" in prom
    assert "mxnet_serve_queue_depth" in prom
    assert "mxnet_serve_batch_occupancy" in prom
    # flight ring carries per-dispatch records
    kinds = [r.get("kind") for r in mx.telemetry.flightrec.get_records()]
    assert "serve.dispatch" in kinds


def test_exact_bucket_request_matches_module_predict_bitwise():
    """A request whose rows equal a rung pads nothing: its response is
    the bucket program's output verbatim, bitwise-equal to
    Module.predict at that batch size."""
    sym = _mlp("bw")
    mod = _bound_module(sym)
    server = mx.serve.serve(mod, ladder=[4], start=False,
                            clock=FakeClock(), default_deadline_ms=10)
    x = np.random.RandomState(7).rand(4, 6).astype(np.float32)
    h = server.submit({"data": x})
    server.pump()                           # full bucket -> immediate
    assert np.array_equal(h.result()[0].asnumpy(),
                          _direct_predict(sym, mod, x, 4))


def test_predictor_engine_serves_mxp(tmp_path):
    """predict.py artifacts served directly: the .mxp's exported batch
    is the single ladder rung and responses match Predictor.forward."""
    sym = _mlp("px")
    mod = _bound_module(sym)
    arg_params, aux_params = mod.get_params()
    path = str(tmp_path / "mlp.mxp")
    mx.export_model(path, sym, arg_params, aux_params, {"data": (4, 6)})

    clock = FakeClock()
    server = mx.serve.serve(path, start=False, clock=clock,
                            default_deadline_ms=10)
    assert server.engine().ladder.sizes == [4]
    x = np.random.RandomState(9).rand(2, 6).astype(np.float32)
    h = server.submit({"data": x})
    clock.advance(0.010)
    assert server.pump() == 1
    ref = mx.Predictor(path).forward(data=pad_rows(x, 4))[0].asnumpy()
    assert np.array_equal(h.result()[0].asnumpy(), ref[:2])


@pytest.mark.slow
def test_poisson_soak_open_loop():
    """Real-clock soak: open-loop Poisson arrivals against a started
    server; everything completes, p99 is finite, metrics accumulate."""
    sym = _mlp("so")
    server = mx.serve.serve(_bound_module(sym), ladder=[1, 2, 4, 8],
                            default_deadline_ms=100)
    gen = mx.serve.PoissonLoadGen(
        server,
        lambda i, rng: {"data": rng.rand(1 + i % 3, 6)
                        .astype(np.float32)},
        rate=200.0, n_requests=300, seed=4)
    try:
        out = gen.run(slo_ms=100)
    finally:
        server.stop()
    assert out["completed"] == 300 and out["errors"] == 0
    assert out["latency_ms"]["p99"] is not None
    assert server.stats()["compiles_since_warmup"] == 0
    stats = server.stats()["models"]["default"]
    assert stats["dispatches"] >= 1
    assert stats["batch_occupancy"] is not None
