"""Distributed Module.fit worker (the dist_lenet analog, launched N-way).

reference: tests/nightly/dist_lenet.py — data-parallel training across
processes through the dist_sync kvstore; the gate is that every worker
ends with bit-identical parameters (the all-reduce + shared updater must
keep replicas in lockstep) and that training actually learned.
"""
import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import mxnet_tpu as mx  # noqa: E402


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers

    # each worker sees its own shard of the planted-signal task
    rng = np.random.RandomState(100 + rank)
    n = 256
    X = rng.rand(n, 16).astype("f")
    y = (X[:, 3] > 0.5).astype("f")
    X[:, 0] = y * 3.0
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)

    data = mx.sym.var("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=16,
                                                name="fc1"),
                          act_type="relu")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=2,
                                                     name="fc2"),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=4, kvstore=kv,
            initializer=mx.initializer.Xavier(rnd_type="uniform",
                                              magnitude=2),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})

    args, _ = mod.get_params()
    digest = hashlib.sha1()
    for nm in sorted(args):
        digest.update(np.ascontiguousarray(
            np.round(args[nm].asnumpy().astype(np.float64), 5)).tobytes())
    acc = mod.score(it, "acc")[0][1]
    kv.close()                  # stop/join the heartbeat thread
    print(f"DIST_FIT_OK rank={rank} nworker={nworker} "
          f"params={digest.hexdigest()[:16]} acc={acc:.3f}", flush=True)
    assert acc > 0.8, f"rank {rank} failed to learn: {acc}"


if __name__ == "__main__":
    main()
