"""Registry-wide operator verification sweep.

Every op in ``OP_REGISTRY`` is either exercised here (dtype-parity via
``check_consistency`` f32-vs-f16 and, where differentiable, finite-difference
gradients via ``check_numeric_gradient``) or listed in ``SKIPS`` with the
reason and the test file that covers it instead. ``test_registry_coverage``
enforces that invariant and prints the per-op coverage report.

Mirrors the reference's two harnesses in one place: the per-op numeric
checks of tests/python/unittest/test_operator.py (3159 LoC) and the
cross-config parity sweep of tests/python/gpu/test_operator_gpu.py built on
check_consistency (reference python/mxnet/test_utils.py:676).
"""
import zlib

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import OP_REGISTRY
from mxnet_tpu.test_utils import (check_consistency, check_numeric_gradient,
                                  assert_almost_equal)

pytestmark = pytest.mark.slow

F32, F16 = np.float32, np.float16


# ---------------------------------------------------------------------------
# input generators (domain-safe values so f16 parity and finite differences
# stay well-conditioned: away from kinks, branch cuts and integer boundaries)
# ---------------------------------------------------------------------------
def U(lo, hi):
    return lambda shape, rng: rng.uniform(lo, hi, shape).astype(F32)


def signed_away_from_zero(lo=0.3, hi=1.0):
    def gen(shape, rng):
        mag = rng.uniform(lo, hi, shape)
        sgn = np.where(rng.rand(*shape) < 0.5, -1.0, 1.0)
        return (mag * sgn).astype(F32)
    return gen


def well_separated(lo=-2.0, hi=2.0):
    """Values with pairwise gaps (safe FD through max/min/sort kinks)."""
    def gen(shape, rng):
        n = int(np.prod(shape))
        vals = np.linspace(lo, hi, n) + rng.uniform(-0.1, 0.1, n) * (
            (hi - lo) / (4 * n))
        rng.shuffle(vals)
        return vals.reshape(shape).astype(F32)
    return gen


def int_valued(high):
    return lambda shape, rng: rng.randint(0, high, shape).astype(F32)


DEFAULT_GEN = U(-1.0, 1.0)


class Case:
    """One sweep configuration of an op.

    shapes   : input name -> shape (simple_bind kwargs; weights inferred)
    attrs    : op kwargs
    gen      : input name -> generator(shape, rng)
    grad     : run check_numeric_gradient
    grad_nodes : restrict FD to these args (bounds cost on layer ops)
    grad_req : consistency backward mode ("null" = forward-only parity)
    builder  : optional fn(vars_dict, attrs) -> Symbol for nonstandard
               composition (variadic/optional-input ops)
    """

    def __init__(self, shapes, attrs=None, gen=None, grad=True,
                 grad_nodes=None, grad_req="write", eps=1e-2, grad_rtol=0.06,
                 tol=None, builder=None, aux=None, consistency=True):
        self.shapes = shapes
        self.attrs = attrs or {}
        self.gen = gen or {}
        self.grad = grad
        self.grad_nodes = grad_nodes
        self.grad_req = grad_req
        self.eps = eps
        self.grad_rtol = grad_rtol
        self.tol = tol
        self.builder = builder
        self.aux = aux or {}
        self.consistency = consistency


def _build(name, case):
    if case.builder is not None:
        vars_ = {k: mx.sym.var(k) for k in case.shapes}
        return case.builder(vars_, dict(case.attrs))
    op = getattr(mx.sym, name)
    kwargs = {k: mx.sym.var(k) for k in case.shapes}
    return op(name="t", **kwargs, **case.attrs)


def _arrays(case, rng):
    out = {}
    for k, shape in case.shapes.items():
        gen = case.gen.get(k, DEFAULT_GEN)
        out[k] = gen(shape, rng)
    return out


def run_case(name, case):
    rng = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
    sym = _build(name, case)
    data = _arrays(case, rng)
    aux = {k: v.copy() for k, v in case.aux.items()} or None

    if case.consistency:
        args = sym.list_arguments()
        ctx_f32 = {"ctx": mx.cpu(), "type_dict": {a: F32 for a in args},
                   **case.shapes}
        ctx_f16 = {"ctx": mx.cpu(), "type_dict": {a: F16 for a in args},
                   **case.shapes}
        check_consistency(sym, [ctx_f32, ctx_f16], grad_req=case.grad_req,
                          arg_params=data, aux_params=aux, tol=case.tol)

    if case.grad:
        # fill remaining args (auto-created weights) with small random values
        loc = dict(data)
        shapes_known = {k: v.shape for k, v in loc.items()}
        arg_shapes, _, aux_shapes = sym.infer_shape_partial(**shapes_known)
        for nm, shp in zip(sym.list_arguments(), arg_shapes):
            if nm not in loc:
                loc[nm] = rng.uniform(-0.5, 0.5, shp).astype(F32)
        aux_states = None
        if sym.list_auxiliary_states():
            aux_states = {nm: case.aux.get(
                nm, rng.uniform(0.5, 1.0, shp).astype(F32))
                for nm, shp in zip(sym.list_auxiliary_states(), aux_shapes)}
        check_numeric_gradient(sym, loc, aux_states=aux_states,
                               numeric_eps=case.eps, rtol=case.grad_rtol,
                               grad_nodes=case.grad_nodes)


# ---------------------------------------------------------------------------
# the case table
# ---------------------------------------------------------------------------
CASES = {}


def add(name, *cases):
    CASES[name] = list(cases)


S23 = {"data": (2, 3)}

# ---- unary math family (domain-restricted generators) ----
_unary = {
    "abs": signed_away_from_zero(),
    "arccos": U(-0.8, 0.8), "arcsin": U(-0.8, 0.8),
    "arccosh": U(1.3, 3.0), "arcsinh": U(-2.0, 2.0),
    "arctan": U(-2.0, 2.0), "arctanh": U(-0.7, 0.7),
    "cos": U(-1.2, 1.2), "sin": U(-1.2, 1.2), "tan": U(-0.9, 0.9),
    "cosh": U(-1.5, 1.5), "sinh": U(-1.5, 1.5), "tanh": U(-2.0, 2.0),
    "degrees": U(-2.0, 2.0), "radians": U(-90.0, 90.0),
    "exp": U(-1.5, 1.5), "expm1": U(-1.5, 1.5),
    "gamma": U(0.6, 2.8), "gammaln": U(0.6, 2.8),
    "log": U(0.4, 2.5), "log10": U(0.4, 2.5), "log2": U(0.4, 2.5),
    "log1p": U(-0.6, 2.0),
    "negative": U(-2.0, 2.0),
    "relu": signed_away_from_zero(),
    "rsqrt": U(0.4, 2.5), "sqrt": U(0.4, 2.5),
    "sigmoid": U(-2.5, 2.5), "square": U(-2.0, 2.0),
}
for _n, _g in _unary.items():
    add(_n, Case(S23, gen={"data": _g}))

# rounding / sign ops: piecewise-constant (zero gradient a.e.) — FD across
# the jumps is meaningless, so forward parity only with inputs away from
# boundaries
_round_gen = lambda shape, rng: (  # noqa: E731
    rng.randint(-3, 4, shape) + rng.uniform(0.15, 0.35, shape)).astype(F32)
for _n in ["ceil", "floor", "fix", "rint", "round"]:
    add(_n, Case(S23, gen={"data": _round_gen}, grad=False))
add("sign", Case(S23, gen={"data": signed_away_from_zero()}, grad=False))

add("smooth_l1", Case(S23, attrs={"scalar": 1.0},
                      gen={"data": well_separated(-2.5, 2.5)}))
add("identity", Case(S23))
# stop_gradient is identity in forward, so FD sees a nonzero slope while
# the symbolic grad is (correctly) zero — forward parity only
add("stop_gradient", Case(S23, grad=False))
add("make_loss", Case(S23, grad=False, grad_req="null"))
add("ones_like", Case(S23, grad=False))
add("zeros_like", Case(S23, grad=False))
add("argmax_channel", Case({"data": (3, 4)}, grad=False, grad_req="null",
                           gen={"data": well_separated()}))

# ---- binary elemwise family ----
LHS_RHS = {"lhs": (2, 3), "rhs": (2, 3)}
POS = {"lhs": U(0.4, 2.0), "rhs": U(0.4, 2.0)}
add("elemwise_add", Case(LHS_RHS))
add("elemwise_sub", Case(LHS_RHS))
add("elemwise_mul", Case(LHS_RHS))
add("elemwise_div", Case(LHS_RHS, gen={"rhs": signed_away_from_zero(0.5)}))
add("_power", Case(LHS_RHS, gen=POS))
add("_hypot", Case(LHS_RHS, gen={"lhs": signed_away_from_zero(),
                                 "rhs": signed_away_from_zero()}))
add("_maximum", Case(LHS_RHS, gen={"lhs": well_separated(-2, 2),
                                   "rhs": well_separated(-1.9, 2.1)}))
add("_minimum", Case(LHS_RHS, gen={"lhs": well_separated(-2, 2),
                                   "rhs": well_separated(-1.9, 2.1)}))
add("_mod", Case(LHS_RHS, gen={"lhs": U(0.55, 0.95), "rhs": U(1.1, 2.0)},
                 grad=False))

# comparisons: boolean outputs, forward parity only
for _n in ["_equal", "_not_equal", "_greater", "_greater_equal", "_lesser",
           "_lesser_equal"]:
    add(_n, Case(LHS_RHS, grad=False, grad_req="null",
                 gen={"lhs": int_valued(3), "rhs": int_valued(3)}))

# scalar variants
SC = {"scalar": 1.5}
add("_plus_scalar", Case(S23, attrs=SC))
add("_minus_scalar", Case(S23, attrs=SC))
add("_rminus_scalar", Case(S23, attrs=SC))
add("_mul_scalar", Case(S23, attrs=SC))
add("_div_scalar", Case(S23, attrs=SC))
add("_rdiv_scalar", Case(S23, attrs=SC,
                         gen={"data": signed_away_from_zero(0.5)}))
add("_mod_scalar", Case(S23, attrs=SC, gen={"data": U(0.2, 1.2)},
                        grad=False))
add("_rmod_scalar", Case(S23, attrs=SC,
                         gen={"data": U(1.7, 2.8)}, grad=False))
add("_power_scalar", Case(S23, attrs={"scalar": 2.5},
                          gen={"data": U(0.4, 2.0)}))
add("_rpower_scalar", Case(S23, attrs={"scalar": 1.5},
                           gen={"data": U(-1.5, 1.5)}))
add("_hypot_scalar", Case(S23, attrs=SC,
                          gen={"data": signed_away_from_zero()}))
add("_maximum_scalar", Case(S23, attrs={"scalar": 0.1},
                            gen={"data": well_separated(-2, 2)}))
add("_minimum_scalar", Case(S23, attrs={"scalar": 0.1},
                            gen={"data": well_separated(-2, 2)}))
for _n in ["_equal_scalar", "_not_equal_scalar", "_greater_scalar",
           "_greater_equal_scalar", "_lesser_scalar",
           "_lesser_equal_scalar"]:
    add(_n, Case(S23, attrs={"scalar": 1.0}, grad=False, grad_req="null",
                 gen={"data": int_valued(3)}))

# ---- broadcast family ----
BC = {"lhs": (2, 1, 3), "rhs": (1, 4, 3)}
add("broadcast_add", Case(BC))
add("broadcast_sub", Case(BC))
add("broadcast_mul", Case(BC))
add("broadcast_div", Case(BC, gen={"rhs": signed_away_from_zero(0.5)}))
add("broadcast_power", Case(BC, gen=POS))
add("broadcast_hypot", Case(BC, gen={"lhs": signed_away_from_zero(),
                                     "rhs": signed_away_from_zero()}))
add("broadcast_maximum", Case(BC, gen={"lhs": well_separated(-2, 2),
                                       "rhs": well_separated(-1.9, 2.1)}))
add("broadcast_minimum", Case(BC, gen={"lhs": well_separated(-2, 2),
                                       "rhs": well_separated(-1.9, 2.1)}))
add("broadcast_mod", Case(BC, gen={"lhs": U(0.55, 0.95),
                                   "rhs": U(1.1, 2.0)}, grad=False))
for _n in ["broadcast_equal", "broadcast_not_equal", "broadcast_greater",
           "broadcast_greater_equal", "broadcast_lesser",
           "broadcast_lesser_equal"]:
    add(_n, Case(BC, grad=False, grad_req="null",
                 gen={"lhs": int_valued(3), "rhs": int_valued(3)}))
add("broadcast_axis", Case({"data": (2, 1, 3)}, attrs={"axis": 1, "size": 4}))
add("broadcast_to", Case({"data": (2, 1, 3)}, attrs={"shape": (2, 4, 3)}))

# ---- reductions ----
R_SHAPE = {"data": (2, 3, 4)}
add("sum", Case(R_SHAPE, attrs={"axis": 1}),
    Case(R_SHAPE, attrs={"axis": (0, 2), "keepdims": True}))
add("mean", Case(R_SHAPE, attrs={"axis": 2}))
add("prod", Case(R_SHAPE, attrs={"axis": 1},
                 gen={"data": signed_away_from_zero(0.5, 1.5)}))
add("nansum", Case(R_SHAPE, attrs={"axis": 1}, grad=False))
add("nanprod", Case(R_SHAPE, attrs={"axis": 1}, grad=False,
                    gen={"data": signed_away_from_zero(0.5, 1.5)}))
add("max", Case(R_SHAPE, attrs={"axis": 1}, grad=False,
                gen={"data": well_separated()}))
add("min", Case(R_SHAPE, attrs={"axis": 1}, grad=False,
                gen={"data": well_separated()}))
add("norm", Case({"data": (3, 4)}, gen={"data": signed_away_from_zero()}))
add("argmax", Case(R_SHAPE, attrs={"axis": 1}, grad=False, grad_req="null",
                   gen={"data": well_separated()}))
add("argmin", Case(R_SHAPE, attrs={"axis": 1}, grad=False, grad_req="null",
                   gen={"data": well_separated()}))

# ---- ordering ----
add("sort", Case({"data": (3, 4)}, attrs={"axis": 1}, grad=False,
                 gen={"data": well_separated()}))
add("argsort", Case({"data": (3, 4)}, attrs={"axis": 1}, grad=False,
                    grad_req="null", gen={"data": well_separated()}))
add("topk", Case({"data": (3, 5)}, attrs={"axis": 1, "k": 2}, grad=False,
                 grad_req="null", gen={"data": well_separated()}))

# ---- indexing ----
add("Embedding",
    Case({"data": (4,), "weight": (5, 3)}, attrs={"input_dim": 5,
                                                  "output_dim": 3},
         gen={"data": int_valued(5)}, grad_nodes=["weight"]))
add("take", Case({"a": (5, 3), "indices": (4,)},
                 gen={"indices": int_valued(5)}, grad_nodes=["a"]))
add("batch_take", Case({"a": (4, 3), "indices": (4,)},
                       gen={"indices": int_valued(3)}, grad_nodes=["a"]))
add("one_hot", Case({"indices": (5,)}, attrs={"depth": 4}, grad=False,
                    grad_req="null", gen={"indices": int_valued(4)}))
add("pick", Case({"data": (4, 3), "index": (4,)},
                 gen={"index": int_valued(3)}, grad_nodes=["data"]))

# ---- shape manipulation ----
add("Reshape", Case({"data": (2, 6)}, attrs={"shape": (3, 4)}))
add("Flatten", Case({"data": (2, 3, 2)}))
add("expand_dims", Case(S23, attrs={"axis": 1}))
add("slice", Case({"data": (4, 5)}, attrs={"begin": (1, 0), "end": (3, 4)}))
add("slice_axis", Case({"data": (4, 5)},
                       attrs={"axis": 1, "begin": 1, "end": 4}))
add("flip", Case(R_SHAPE, attrs={"axis": 1}))
add("repeat", Case(S23, attrs={"repeats": 2, "axis": 1}))
add("tile", Case(S23, attrs={"reps": (2, 1)}))
add("transpose", Case(R_SHAPE, attrs={"axes": (2, 0, 1)}))
add("SwapAxis", Case(R_SHAPE, attrs={"dim1": 0, "dim2": 2}))
add("clip", Case({"data": (3, 4)}, attrs={"a_min": -0.8, "a_max": 0.8},
                 gen={"data": well_separated(-1.5, 1.5)}))
add("where", Case({"condition": (2, 3), "x": (2, 3), "y": (2, 3)},
                  gen={"condition": int_valued(2)}, grad_nodes=["x", "y"]))
add("Pad", Case({"data": (1, 2, 3, 3)},
                attrs={"mode": "constant", "constant_value": 0.5,
                       "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    Case({"data": (1, 2, 3, 3)},
         attrs={"mode": "edge", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    Case({"data": (1, 2, 4, 4)},
         attrs={"mode": "reflect", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}))
add("Cast", Case(S23, attrs={"dtype": "float32"}, grad=False,
                 grad_req="null"))

# ---- matrix ----
add("dot", Case({"lhs": (2, 3), "rhs": (3, 4)}),
    Case({"lhs": (3, 2), "rhs": (3, 4)}, attrs={"transpose_a": True}))
add("batch_dot", Case({"lhs": (2, 2, 3), "rhs": (2, 3, 2)}))

# ---- variadic ----
add("Concat",
    Case({"a": (2, 2), "b": (2, 3)},
         builder=lambda v, a: mx.sym.Concat(v["a"], v["b"], dim=1,
                                            num_args=2)))
add("SliceChannel",
    Case({"data": (2, 6)},
         builder=lambda v, a: mx.sym.SliceChannel(v["data"], num_outputs=2,
                                                  axis=1)[0],
         grad=False))
add("ElementWiseSum",
    Case({"a": (2, 3), "b": (2, 3), "c": (2, 3)},
         builder=lambda v, a: mx.sym.ElementWiseSum(v["a"], v["b"], v["c"],
                                                    num_args=3)))
add("UpSampling",
    Case({"data": (1, 2, 3, 3)},
         builder=lambda v, a: mx.sym.UpSampling(v["data"], scale=2,
                                                sample_type="nearest",
                                                num_args=1)))
add("Crop",
    Case({"data": (1, 2, 5, 5)},
         builder=lambda v, a: mx.sym.Crop(v["data"], num_args=1,
                                          offset=(1, 1), h_w=(3, 3))))

# ---- nn layer ops ----
add("Activation",
    Case({"data": (2, 4)}, attrs={"act_type": "relu"},
         gen={"data": signed_away_from_zero()}),
    Case({"data": (2, 4)}, attrs={"act_type": "sigmoid"}),
    Case({"data": (2, 4)}, attrs={"act_type": "tanh"}),
    Case({"data": (2, 4)}, attrs={"act_type": "softrelu"}))
add("FullyConnected",
    Case({"data": (3, 4)}, attrs={"num_hidden": 3}))
add("Convolution",
    Case({"data": (1, 2, 5, 5)},
         attrs={"kernel": (3, 3), "num_filter": 2}, grad_nodes=["data"]),
    Case({"data": (1, 2, 4, 4)},
         attrs={"kernel": (2, 2), "num_filter": 2, "stride": (2, 2),
                "num_group": 2, "no_bias": True}, grad_nodes=["data"]))
add("Deconvolution",
    Case({"data": (1, 2, 3, 3)},
         attrs={"kernel": (2, 2), "num_filter": 2, "stride": (2, 2),
                "no_bias": True}, grad_nodes=["data"]))
add("Pooling",
    Case({"data": (1, 2, 4, 4)},
         attrs={"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"},
         gen={"data": well_separated()}, grad=False),
    Case({"data": (1, 2, 4, 4)},
         attrs={"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"},
         grad_nodes=["data"]),
    Case({"data": (1, 2, 4, 4)},
         attrs={"global_pool": True, "kernel": (2, 2), "pool_type": "max"},
         gen={"data": well_separated()}, grad=False))
add("BatchNorm",
    Case({"data": (3, 2)}, attrs={"fix_gamma": False},
         grad_nodes=["data", "t_gamma", "t_beta"],
         aux={"t_moving_mean": np.zeros(2, F32),
              "t_moving_var": np.ones(2, F32)}))
add("InstanceNorm",
    Case({"data": (2, 2, 4)}, grad_nodes=["data"], grad_rtol=0.08))
add("L2Normalization",
    Case({"data": (2, 3)}, attrs={"mode": "instance"},
         gen={"data": signed_away_from_zero()}),
    Case({"data": (2, 3, 4)}, attrs={"mode": "channel"},
         gen={"data": signed_away_from_zero()}, grad=False),
    Case({"data": (2, 3, 4)}, attrs={"mode": "spatial"},
         gen={"data": signed_away_from_zero()}, grad=False))
add("LRN", Case({"data": (1, 4, 3, 3)}, attrs={"nsize": 3},
                grad_nodes=["data"], grad_rtol=0.08))
add("LeakyReLU",
    Case({"data": (2, 4)}, attrs={"act_type": "leaky", "slope": 0.3},
         gen={"data": signed_away_from_zero()}),
    Case({"data": (2, 4)}, attrs={"act_type": "elu", "slope": 0.3},
         gen={"data": signed_away_from_zero()}))
add("Dropout", Case({"data": (2, 4)}, attrs={"p": 0.5}, grad=False,
                    grad_req="null"))
add("SoftmaxActivation",
    Case({"data": (3, 4)}),
    Case({"data": (2, 3, 2, 2)}, attrs={"mode": "channel"}))
add("softmax", Case({"data": (3, 4)}, attrs={"axis": 1}))
add("log_softmax", Case({"data": (3, 4)}, attrs={"axis": 1}))

# ---- sequence ops (length input is optional; exercised with it on) ----
add("SequenceLast",
    Case({"data": (3, 2, 4)},
         builder=lambda v, a: mx.sym.SequenceLast(v["data"]),
         grad_nodes=["data"]))
add("SequenceMask",
    Case({"data": (3, 2, 4), "length": (2,)},
         builder=lambda v, a: mx.sym.SequenceMask(
             v["data"], v["length"], use_sequence_length=True, value=0.0),
         gen={"length": lambda s, r: np.array([2, 3], F32)},
         grad_nodes=["data"]))
add("SequenceReverse",
    Case({"data": (3, 2, 4)},
         builder=lambda v, a: mx.sym.SequenceReverse(v["data"]),
         grad_nodes=["data"]))

# ---- spatial / vision ops ----
add("ROIPooling",
    Case({"data": (1, 2, 6, 6), "rois": (2, 5)},
         attrs={"pooled_size": (2, 2), "spatial_scale": 1.0},
         gen={"data": well_separated(),
              "rois": lambda s, r: np.array([[0, 0, 0, 3, 3],
                                             [0, 1, 1, 5, 5]], F32)},
         grad=False))
# grid gradient has kinks wherever a sample point crosses a pixel-cell
# boundary — FD across those is unreliable, so FD covers data only (grid
# still exercised by the f32/f16 consistency backward)
add("BilinearSampler",
    Case({"data": (1, 2, 4, 4), "grid": (1, 2, 3, 3)},
         gen={"grid": U(-0.7, 0.7)}, grad_nodes=["data"],
         grad_rtol=0.08))
add("GridGenerator",
    Case({"data": (1, 6)}, attrs={"transform_type": "affine",
                                  "target_shape": (4, 4)},
         gen={"data": lambda s, r: np.array(
             [[1.1, 0.1, 0.05, -0.1, 0.9, 0.02]], F32)}),
    Case({"data": (1, 2, 4, 4)}, attrs={"transform_type": "warp"},
         gen={"data": U(-0.3, 0.3)}, grad=False))
add("SpatialTransformer",
    Case({"data": (1, 2, 4, 4), "loc": (1, 6)},
         attrs={"transform_type": "affine", "sampler_type": "bilinear",
                "target_shape": (3, 3)},
         gen={"loc": lambda s, r: np.array(
             [[0.9, 0.05, 0.02, -0.05, 0.85, -0.02]], F32)},
         grad_nodes=["data", "loc"], grad_rtol=0.09))
add("Correlation",
    Case({"data1": (1, 2, 4, 4), "data2": (1, 2, 4, 4)},
         attrs={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                "stride2": 1, "pad_size": 1},
         grad=False))

# ---- loss heads (forward parity; backward semantics covered by
# test_operator.py / executor loss-seeding tests) ----
LBL = {"label": int_valued(3)}
add("SoftmaxOutput", Case({"data": (4, 3), "label": (4,)}, gen=LBL,
                          grad=False, grad_req="null"))
add("LinearRegressionOutput", Case({"data": (4, 3), "label": (4, 3)},
                                   grad=False, grad_req="null"))
add("LogisticRegressionOutput", Case({"data": (4, 3), "label": (4, 3)},
                                     grad=False, grad_req="null"))
add("MAERegressionOutput", Case({"data": (4, 3), "label": (4, 3)},
                                grad=False, grad_req="null"))
add("SVMOutput", Case({"data": (4, 3), "label": (4,)}, gen=LBL,
                      grad=False, grad_req="null"))
add("MakeLoss", Case({"data": (3, 4)}, gen={"data": U(0.1, 1.0)},
                     grad=False, grad_req="null"))
add("IdentityAttachKLSparseReg", Case({"data": (3, 4)},
                                      gen={"data": U(0.05, 0.95)},
                                      grad=False, grad_req="null"))
add("softmax_cross_entropy",
    Case({"data": (4, 3), "label": (4,)}, gen=LBL, grad=False,
         grad_req="null"))
add("BlockGrad", Case(S23, grad=False))

# ---- contrib ----
add("CTCLoss",
    Case({"data": (5, 2, 4), "label": (2, 3)},
         gen={"label": lambda s, r: np.array([[1, 2, 0], [2, 3, 1]], F32)},
         grad=False, grad_req="null", tol=2e-1))
add("fft", Case({"data": (2, 4)}, grad=False, grad_req="null"))
add("ifft", Case({"data": (2, 8)}, grad=False, grad_req="null"))
add("count_sketch",
    Case({"data": (2, 6), "h": (1, 6), "s": (1, 6)},
         attrs={"out_dim": 4},
         gen={"h": int_valued(4),
              "s": lambda s, r: np.where(r.rand(*s) < 0.5, -1, 1).astype(
                  F32)},
         grad=False, grad_req="null"))
add("quantize",
    Case({"data": (2, 3), "min_range": (1,), "max_range": (1,)},
         gen={"data": U(-0.9, 0.9),
              "min_range": lambda s, r: np.array([-1.0], F32),
              "max_range": lambda s, r: np.array([1.0], F32)},
         grad=False, grad_req="null", consistency=False))
add("dequantize",
    Case({"data": (2, 3), "min_range": (1,), "max_range": (1,)},
         gen={"data": int_valued(255),
              "min_range": lambda s, r: np.array([-1.0], F32),
              "max_range": lambda s, r: np.array([1.0], F32)},
         grad=False, grad_req="null", consistency=False))
add("MultiBoxPrior",
    Case({"data": (1, 2, 4, 4)},
         attrs={"sizes": (0.4, 0.8), "ratios": (1.0, 2.0)},
         grad=False, grad_req="null"))
add("MultiBoxTarget",
    Case({"anchor": (1, 4, 4), "label": (1, 2, 5), "cls_pred": (1, 2, 4)},
         gen={"anchor": lambda s, r: np.array(
             [[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
               [0.0, 0.0, 0.2, 0.2], [0.6, 0.1, 0.9, 0.4]]], F32),
             "label": lambda s, r: np.array(
                 [[[0, 0.12, 0.12, 0.38, 0.42], [1, 0.55, 0.5, 0.88, 0.92]]],
                 F32),
             "cls_pred": lambda s, r: r.uniform(0, 1, s).astype(F32)},
         grad=False, grad_req="null"))
add("MultiBoxDetection",
    Case({"cls_prob": (1, 3, 4), "loc_pred": (1, 16), "anchor": (1, 4, 4)},
         gen={"cls_prob": lambda s, r: r.dirichlet(
             np.ones(3), (1, 4)).transpose(0, 2, 1).astype(F32),
             "loc_pred": U(-0.1, 0.1),
             "anchor": lambda s, r: np.array(
                 [[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                   [0.0, 0.0, 0.2, 0.2], [0.6, 0.1, 0.9, 0.4]]], F32)},
         grad=False, grad_req="null"))

# ---------------------------------------------------------------------------
# ops exercised outside the consistency/FD harness
# ---------------------------------------------------------------------------


def _check_creation_ops():
    a = mx.nd._arange(start=1, stop=7, step=2)
    assert_almost_equal(a, np.arange(1, 7, 2, dtype=np.float32))
    z = mx.nd._zeros(shape=(2, 3))
    assert_almost_equal(z, np.zeros((2, 3)))
    o = mx.nd._ones(shape=(2, 3))
    assert_almost_equal(o, np.ones((2, 3)))
    f = mx.nd._full(shape=(2, 2), value=3.5)
    assert_almost_equal(f, np.full((2, 2), 3.5, np.float32))


def _check_assign_ops():
    base = np.arange(12, dtype=np.float32).reshape(3, 4)
    lhs = mx.nd.array(base)
    rhs = mx.nd.array(np.full((2, 2), -1.0, np.float32))
    out = mx.nd._slice_assign(lhs, rhs, begin=(0, 1), end=(2, 3))
    exp = base.copy()
    exp[0:2, 1:3] = -1.0
    assert_almost_equal(out, exp)
    out2 = mx.nd._crop_assign_scalar(mx.nd.array(base), begin=(1, 0),
                                     end=(3, 2), scalar=9.0)
    exp2 = base.copy()
    exp2[1:3, 0:2] = 9.0
    assert_almost_equal(out2, exp2)
    like = mx.nd._identity_with_attr_like_rhs(
        mx.nd.array(np.ones((2, 2), np.float32)),
        mx.nd.array(np.zeros((2, 2), np.float32)))
    assert_almost_equal(like, np.ones((2, 2)))


def _check_sampler(name, attrs, mean, std, mean_tol, std_tol):
    fn = getattr(mx.nd, name)
    out = fn(shape=(20000,), **attrs)
    arr = out.asnumpy()
    assert arr.shape == (20000,)
    assert np.isfinite(arr).all()
    assert abs(arr.mean() - mean) < mean_tol, (name, arr.mean(), mean)
    assert abs(arr.std() - std) < std_tol, (name, arr.std(), std)


SAMPLERS = {
    "_random_uniform": ({"low": -1.0, "high": 1.0}, 0.0, 2 / np.sqrt(12),
                        0.05, 0.05),
    "_random_normal": ({"loc": 1.0, "scale": 2.0}, 1.0, 2.0, 0.08, 0.08),
    "_random_gamma": ({"alpha": 4.0, "beta": 0.5}, 2.0, 1.0, 0.08, 0.08),
    "_random_exponential": ({"lam": 2.0}, 0.5, 0.5, 0.04, 0.04),
    "_random_poisson": ({"lam": 3.0}, 3.0, np.sqrt(3.0), 0.1, 0.1),
    "_random_negative_binomial": ({"k": 3, "p": 0.5}, 3.0, np.sqrt(6.0),
                                  0.15, 0.15),
    "_random_generalized_negative_binomial":
        ({"mu": 2.0, "alpha": 0.5}, 2.0, np.sqrt(2 + 0.5 * 4), 0.15, 0.2),
}

def _check_multi_proposal():
    """MultiProposal vs a direct numpy re-derivation of the RPN recipe
    (reference: src/operator/contrib/multi_proposal.cc)."""
    rng = np.random.RandomState(7)
    stride, scales, ratios = 4, (2.0,), (1.0,)
    A, H, W = 1, 4, 4
    cls_prob = rng.uniform(0, 1, (1, 2 * A, H, W)).astype(F32)
    bbox_pred = rng.uniform(-0.2, 0.2, (1, 4 * A, H, W)).astype(F32)
    im_info = np.array([[16.0, 16.0, 1.0]], F32)
    post = 4
    out = mx.nd.MultiProposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        feature_stride=stride, scales=scales, ratios=ratios,
        rpn_pre_nms_top_n=8, rpn_post_nms_top_n=post, rpn_min_size=2,
        threshold=0.7).asnumpy()
    assert out.shape == (post, 5)
    assert (out[:, 0] == 0).all()                      # batch index
    x1, y1, x2, y2 = out[:, 1], out[:, 2], out[:, 3], out[:, 4]
    assert (x1 >= 0).all() and (y1 >= 0).all()
    assert (x2 <= 15).all() and (y2 <= 15).all()       # clipped to im_info
    assert (x2 - x1 + 1 >= 2).all() and (y2 - y1 + 1 >= 2).all()
    # numpy recompute of the decoded, clipped top-score box -> must be roi 0
    base = np.array([0, 0, stride - 1, stride - 1], np.float32)
    w0 = base[2] - base[0] + 1
    ws = np.round(np.sqrt(w0 * w0 / ratios[0]))
    hs = np.round(ws * ratios[0])
    cx0 = base[0] + 0.5 * (w0 - 1)
    cy0 = base[1] + 0.5 * (w0 - 1)
    anchors = []
    for yy in range(H):
        for xx in range(W):
            cx = cx0 + xx * stride
            cy = cy0 + yy * stride
            sw, sh = ws * scales[0], hs * scales[0]
            anchors.append([cx - 0.5 * (sw - 1), cy - 0.5 * (sh - 1),
                            cx + 0.5 * (sw - 1), cy + 0.5 * (sh - 1)])
    anchors = np.array(anchors, np.float32)
    score = cls_prob[0, A:].transpose(1, 2, 0).reshape(-1)
    deltas = bbox_pred[0].reshape(A, 4, H, W).transpose(
        2, 3, 0, 1).reshape(-1, 4)
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + 0.5 * (aw - 1)
    acy = anchors[:, 1] + 0.5 * (ah - 1)
    pcx = deltas[:, 0] * aw + acx
    pcy = deltas[:, 1] * ah + acy
    pw = np.exp(deltas[:, 2]) * aw
    ph = np.exp(deltas[:, 3]) * ah
    best = int(np.argmax(score))
    exp_box = np.array([
        np.clip(pcx[best] - 0.5 * (pw[best] - 1), 0, 15),
        np.clip(pcy[best] - 0.5 * (ph[best] - 1), 0, 15),
        np.clip(pcx[best] + 0.5 * (pw[best] - 1), 0, 15),
        np.clip(pcy[best] + 0.5 * (ph[best] - 1), 0, 15)], dtype=F32)
    assert_almost_equal(out[0, 1:], exp_box, rtol=1e-4, atol=1e-4)


FUNCTIONAL = {
    "_arange": _check_creation_ops, "_zeros": _check_creation_ops,
    "_ones": _check_creation_ops, "_full": _check_creation_ops,
    "_slice_assign": _check_assign_ops,
    "_crop_assign_scalar": _check_assign_ops,
    "_identity_with_attr_like_rhs": _check_assign_ops,
    "MultiProposal": _check_multi_proposal,
}

# ---------------------------------------------------------------------------
# explicit skips — every entry names the covering test or the reason
# ---------------------------------------------------------------------------
SKIPS = {
    "pallas_sgd_mom_update": "built-in Pallas kernel — numerics vs XLA "
                             "composition in tests/test_rtc.py",
    "pallas_flash_attention": "built-in Pallas kernel — fwd/grad vs XLA "
                              "attention in tests/test_rtc.py",
    "RNN": "fused RNN kernel — fused-vs-unfolded equivalence in "
           "tests/test_rnn.py",
    "Custom": "python CustomOp bridge — end-to-end in "
              "tests/test_custom_op.py",
    "sgd_update": "mutating optimizer kernel — fused-vs-staged numerics in "
                  "tests/test_optimizer.py / test_module.py",
    "sgd_mom_update": "see sgd_update",
    "adam_update": "see sgd_update",
    "rmsprop_update": "see sgd_update",
    "rmspropalex_update": "see sgd_update",
}


# snapshot at import: ops registered later (e.g. by test_rtc's
# register_pallas_op cases) are out of scope for the coverage gate
_REGISTRY_SNAPSHOT = sorted(OP_REGISTRY)


def _canonical():
    """name -> canonical name (first registered name of the same OpDef)."""
    by_id = {}
    for n in _REGISTRY_SNAPSHOT:
        by_id.setdefault(id(OP_REGISTRY[n]), []).append(n)
    canon = {}
    for names in by_id.values():
        covered = [n for n in names
                   if n in CASES or n in SKIPS or n in SAMPLERS
                   or n in FUNCTIONAL]
        root = covered[0] if covered else names[0]
        for n in names:
            canon[n] = root
    return canon


CANON = _canonical()


@pytest.mark.parametrize("name,idx", [(n, i) for n in sorted(CASES)
                                      for i in range(len(CASES[n]))])
def test_op_sweep(name, idx):
    run_case(name, CASES[name][idx])


@pytest.mark.parametrize("name", sorted(SAMPLERS))
def test_op_sweep_sampler(name):
    _check_sampler(name, *SAMPLERS[name])


@pytest.mark.parametrize("fn", sorted({f.__name__ for f in
                                       FUNCTIONAL.values()}))
def test_op_sweep_functional(fn):
    {f.__name__: f for f in FUNCTIONAL.values()}[fn]()


def test_registry_coverage():
    """Every registered op is swept here or skipped with a named reason."""
    report, missing = [], []
    for name in _REGISTRY_SNAPSHOT:
        root = CANON[name]
        alias = f" (alias of {root})" if root != name else ""
        if root in CASES:
            ncase = len(CASES[root])
            kinds = []
            if any(c.consistency for c in CASES[root]):
                kinds.append("consistency[f32/f16]")
            if any(c.grad for c in CASES[root]):
                kinds.append("numeric-grad")
            report.append(f"TESTED  {name}{alias}: {ncase} case(s): "
                          f"{'+'.join(kinds)}")
        elif root in SAMPLERS:
            report.append(f"TESTED  {name}{alias}: forward moments check")
        elif root in FUNCTIONAL:
            report.append(f"TESTED  {name}{alias}: functional check")
        elif root in SKIPS:
            report.append(f"SKIPPED {name}{alias}: {SKIPS[root]}")
        else:
            missing.append(name)
    print()
    print("\n".join(report))
    n_tested = sum(1 for r in report if r.startswith("TESTED"))
    n_skipped = sum(1 for r in report if r.startswith("SKIPPED"))
    print(f"== op sweep coverage: {n_tested} tested, {n_skipped} "
          f"skipped-with-reason, {len(missing)} uncovered of "
          f"{len(OP_REGISTRY)} registered ==")
    assert not missing, f"ops with no sweep coverage: {missing}"


def test_check_speed_harness():
    """check_speed (reference: test_utils.py:602) measures a bound
    executor's step time — exercise both modes so the harness stays
    alive."""
    from mxnet_tpu.test_utils import check_speed
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="spfc")
    t_whole = check_speed(net, ctx=mx.cpu(), N=3, typ="whole",
                          data=(4, 16))
    t_fwd = check_speed(net, ctx=mx.cpu(), N=3, typ="forward",
                        data=(4, 16))
    assert t_whole > 0 and t_fwd > 0
    with pytest.raises(ValueError):
        check_speed(net, ctx=mx.cpu(), N=1, typ="sideways", data=(4, 16))
