"""RecordIO tests (mirrors reference tests/python/unittest/test_recordio.py)."""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import recordio


def test_recordio_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "test.rec")
        w = recordio.MXRecordIO(path, "w")
        for i in range(5):
            w.write(f"record{i}".encode() * (i + 1))
        w.close()
        r = recordio.MXRecordIO(path, "r")
        for i in range(5):
            item = r.read()
            assert item == f"record{i}".encode() * (i + 1)
        assert r.read() is None
        r.reset()
        assert r.read() == b"record0"


def test_indexed_recordio():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "test.rec")
        idx_path = os.path.join(d, "test.idx")
        w = recordio.MXIndexedRecordIO(idx_path, path, "w")
        for i in range(5):
            w.write_idx(i, f"record{i}".encode())
        w.close()
        r = recordio.MXIndexedRecordIO(idx_path, path, "r")
        assert r.keys == list(range(5))
        assert r.read_idx(3) == b"record3"
        assert r.read_idx(0) == b"record0"


def test_irheader_pack_unpack():
    header = recordio.IRHeader(0, 5.0, 123, 0)
    packed = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(packed)
    assert h2.label == 5.0
    assert h2.id == 123
    assert payload == b"payload"
    # multi-label
    header = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0],
                                           dtype=np.float32), 7, 0)
    packed = recordio.pack(header, b"xyz")
    h3, payload3 = recordio.unpack(packed)
    np.testing.assert_allclose(h3.label, [1.0, 2.0, 3.0])
    assert payload3 == b"xyz"
