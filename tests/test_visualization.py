"""Visualization (reference: tests/python/unittest/test_viz.py +
print_summary contract)."""
import numpy as np

import mxnet_tpu as mx


def _small_net():
    data = mx.sym.var("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="c1")
    a = mx.sym.Activation(c, act_type="relu", name="a1")
    b = mx.sym.BatchNorm(a, name="bn1")
    f = mx.sym.Flatten(b, name="fl")
    fc = mx.sym.FullyConnected(f, num_hidden=5, name="fc1")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def test_print_summary_param_count_matches_executor(capsys):
    sym = _small_net()
    total = mx.viz.print_summary(sym, shape={"data": (2, 3, 8, 8)})
    out = capsys.readouterr().out
    assert "data" in out.splitlines()[3]   # input row leads the table
    assert "c1 (Convolution)" in out
    assert "fc1 (FullyConnected)" in out
    assert f"Total params: {total}" in out
    # ground truth: sum of learnable arg + aux element counts when bound
    exe = sym.simple_bind(ctx=mx.cpu(), data=(2, 3, 8, 8))
    expected = sum(int(np.prod(a.shape)) for nm, a in exe.arg_dict.items()
                   if nm not in ("data", "softmax_label"))
    expected += sum(int(np.prod(a.shape)) for a in exe.aux_dict.values())
    assert total == expected, (total, expected)


def test_print_summary_without_shapes():
    total = mx.viz.print_summary(_small_net())
    assert total == 0          # no shapes -> no param counting


def test_plot_network_gated_or_renders():
    sym = _small_net()
    try:
        import graphviz  # noqa: F401
        have = True
    except ImportError:
        have = False
    if not have:
        import pytest
        with pytest.raises(ImportError):
            mx.viz.plot_network(sym)
    else:
        dot = mx.viz.plot_network(sym, shape={"data": (2, 3, 8, 8)})
        src = dot.source
        assert "c1" in src and "fc1" in src
        assert "c1_weight" not in src      # hidden by default
        dot2 = mx.viz.plot_network(sym, hide_weights=False)
        assert "c1_weight" in dot2.source
