"""Executor tests (mirrors reference tests/python/unittest/test_executor.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_bind_forward():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = a + b
    a_np = np.random.rand(4, 4).astype(np.float32)
    b_np = np.random.rand(4, 4).astype(np.float32)
    ex = c.bind(mx.cpu(), args={"a": mx.nd.array(a_np),
                                "b": mx.nd.array(b_np)})
    out = ex.forward()
    assert_almost_equal(out[0], a_np + b_np)


def test_bind_backward():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = a * b
    a_np = np.random.rand(3, 3).astype(np.float32)
    b_np = np.random.rand(3, 3).astype(np.float32)
    ga = mx.nd.zeros((3, 3))
    gb = mx.nd.zeros((3, 3))
    ex = c.bind(mx.cpu(), args={"a": mx.nd.array(a_np),
                                "b": mx.nd.array(b_np)},
                args_grad={"a": ga, "b": gb})
    ex.forward(is_train=True)
    head = np.random.rand(3, 3).astype(np.float32)
    ex.backward([mx.nd.array(head)])
    assert_almost_equal(ga, head * b_np, rtol=1e-5)
    assert_almost_equal(gb, head * a_np, rtol=1e-5)


def test_grad_req_add():
    a = mx.sym.var("a")
    c = a * 2
    a_np = np.random.rand(3,).astype(np.float32)
    ga = mx.nd.ones((3,))
    ex = c.bind(mx.cpu(), args={"a": mx.nd.array(a_np)},
                args_grad={"a": ga}, grad_req="add")
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones((3,))])
    assert_almost_equal(ga, np.ones(3) + 2)  # 1 (initial) + 2 (grad)


def test_grad_req_null():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = a * b
    ex = c.bind(mx.cpu(), args={"a": mx.nd.ones((2,)),
                                "b": mx.nd.ones((2,))},
                args_grad={"a": mx.nd.zeros((2,))},
                grad_req={"a": "write", "b": "null"})
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones((2,))])
    assert ex.grad_dict["b"] is None
    assert_almost_equal(ex.grad_dict["a"], np.ones(2))


def test_simple_bind():
    net = mx.sym.FullyConnected(data=mx.sym.var("data"), num_hidden=4,
                                name="fc")
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 8))
    assert ex.arg_dict["fc_weight"].shape == (4, 8)
    assert ex.arg_dict["fc_bias"].shape == (4,)
    ex.arg_dict["data"][:] = 1
    ex.arg_dict["fc_weight"][:] = 1
    ex.arg_dict["fc_bias"][:] = 0
    out = ex.forward()
    assert_almost_equal(out[0], np.full((2, 4), 8.0))


def test_executor_arg_aliasing():
    """Param mutation through the shared NDArray cell must be visible to
    the executor (the aliasing property executor_group relies on)."""
    net = mx.sym.FullyConnected(data=mx.sym.var("data"), num_hidden=2,
                                name="fc", no_bias=True)
    w = mx.nd.ones((2, 3))
    ex = net.bind(mx.cpu(), args={"data": mx.nd.ones((1, 3)),
                                  "fc_weight": w})
    out1 = ex.forward()[0].asnumpy()
    w *= 2  # in-place through the alias
    out2 = ex.forward()[0].asnumpy()
    assert_almost_equal(out2, out1 * 2)


def test_loss_head_backward_no_outgrads():
    net = mx.sym.SoftmaxOutput(mx.sym.var("data"), name="softmax")
    data = np.random.rand(4, 5).astype(np.float32)
    label = np.array([0, 1, 2, 3], dtype=np.float32)
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 5))
    ex.arg_dict["data"][:] = data
    ex.arg_dict["softmax_label"][:] = label
    ex.forward(is_train=True)
    ex.backward()
    prob = ex.outputs[0].asnumpy()
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    assert_almost_equal(ex.grad_dict["data"], prob - onehot, rtol=1e-5)


def test_reshape_executor():
    net = mx.sym.FullyConnected(data=mx.sym.var("data"), num_hidden=4,
                                name="fc")
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 8))
    ex.arg_dict["fc_weight"][:] = 1
    ex2 = ex.reshape(data=(5, 8))
    assert ex2.arg_dict["data"].shape == (5, 8)
    # params carried over (same shape -> same cells)
    assert ex2.arg_dict["fc_weight"] is ex.arg_dict["fc_weight"]


def test_forward_override_kwargs():
    net = mx.sym.var("x") * 3
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="null", x=(2, 2))
    out = ex.forward(x=mx.nd.ones((2, 2)))
    assert_almost_equal(out[0], np.full((2, 2), 3.0))


def test_multi_output_executor():
    data = mx.sym.var("data")
    parts = mx.sym.SliceChannel(data, num_outputs=3, axis=1, name="slice")
    ex = parts.bind(mx.cpu(), args={"data": mx.nd.array(
        np.arange(12).reshape(2, 6).astype(np.float32))})
    outs = ex.forward()
    assert len(outs) == 3
    assert outs[0].shape == (2, 2)


def test_monitor_taps_per_op_during_training():
    """ADVICE r2 (low): fit-style forward(is_train=True)+backward must
    still fire the per-op monitor tap (reference ExecuteMonCallback)."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(fc, mx.sym.Variable("sm_label"), name="sm")
    exe = out.simple_bind(mx.cpu(), data=(2, 4), sm_label=(2,))
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=True,
                data=mx.nd.array(np.random.rand(2, 4).astype(np.float32)))
    exe.backward()
    assert any("fc" in n for n in seen), seen
    assert any("sm" in n for n in seen), seen
    # exactly once per op per step — no duplicate taps
    from collections import Counter
    assert all(c == 1 for c in Counter(seen).values()), Counter(seen)


def test_naive_engine_serial_replay(monkeypatch):
    """MXNET_ENGINE_TYPE=NaiveEngine routes executor programs through the
    un-jitted serial runner (reference: env_var.md:33-40, the documented
    deterministic-debug switch) and must match the jitted path bitwise-
    close on forward outputs and gradients."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(fc, mx.sym.Variable("sm_label"), name="sm")

    x = np.random.rand(4, 5).astype(np.float32)
    y = np.array([0, 1, 2, 0], dtype=np.float32)

    def run_step():
        mx.random.seed(7)
        exe = out.simple_bind(mx.cpu(), data=(4, 5), sm_label=(4,))
        for nm, arr in exe.arg_dict.items():
            if nm not in ("data", "sm_label"):
                arr[:] = 0.1
        exe.forward(is_train=True, data=mx.nd.array(x),
                    sm_label=mx.nd.array(y))
        exe.backward()
        return (exe.outputs[0].asnumpy(),
                exe.grad_dict["fc_weight"].asnumpy())

    ref_out, ref_grad = run_step()
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    naive_out, naive_grad = run_step()
    assert_almost_equal(naive_out, ref_out)
    assert_almost_equal(naive_grad, ref_grad)


def test_naive_engine_disables_fused_fit(monkeypatch):
    """Under NaiveEngine Module.fit must fall back to the imperative
    per-phase path (per-op serial replay), not the fused XLA step."""
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    n = 16
    x = np.random.rand(n, 4).astype(np.float32)
    y = (x.sum(axis=1) > 2).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=8, label_name="sm_label")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2, name="fc"),
        mx.sym.var("sm_label"), name="sm")
    mod = mx.mod.Module(net, label_names=("sm_label",))
    mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    assert not mod._fused_armed
