"""Initializer, metric, attribute-scope tests (mirrors reference
test_init.py, metric tests, test_attr.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


# ------------------------------------------------------------ initializers
def test_basic_initializers():
    for init, check in [
        (mx.initializer.Zero(), lambda a: (a == 0).all()),
        (mx.initializer.One(), lambda a: (a == 1).all()),
        (mx.initializer.Constant(3.0), lambda a: (a == 3).all()),
        (mx.initializer.Uniform(0.1), lambda a: (np.abs(a) <= 0.1).all()),
        (mx.initializer.Normal(0.1), lambda a: np.abs(a).std() < 0.5),
        (mx.initializer.Xavier(), lambda a: np.isfinite(a).all()),
        (mx.initializer.MSRAPrelu(), lambda a: np.isfinite(a).all()),
    ]:
        arr = mx.nd.zeros((20, 10))
        init("fc_weight", arr)
        assert check(arr.asnumpy()), type(init).__name__


def test_name_pattern_dispatch():
    init = mx.initializer.Uniform(0.1)
    bias = mx.nd.ones((5,))
    init("fc_bias", bias)
    assert (bias.asnumpy() == 0).all()
    gamma = mx.nd.zeros((5,))
    init("bn_gamma", gamma)
    assert (gamma.asnumpy() == 1).all()
    mean = mx.nd.ones((5,))
    init("bn_moving_mean", mean)
    assert (mean.asnumpy() == 0).all()
    var = mx.nd.zeros((5,))
    init("bn_moving_var", var)
    assert (var.asnumpy() == 1).all()


def test_orthogonal_init():
    init = mx.initializer.Orthogonal(scale=1.0)
    arr = mx.nd.zeros((10, 10))
    init("q_weight", arr)
    a = arr.asnumpy()
    np.testing.assert_allclose(a.dot(a.T), np.eye(10), atol=1e-4)


def test_lstm_bias_init():
    init = mx.initializer.LSTMBias(forget_bias=1.0)
    arr = mx.nd.ones((20,))  # 4 gates x 5 hidden
    init("lstm_i2h_bias", arr)
    a = arr.asnumpy()
    assert (a[5:10] == 1.0).all()  # forget gate
    assert (a[:5] == 0.0).all()


def test_mixed_initializer():
    # reference semantics: first matching pattern wins; name-suffix routing
    # still applies inside each initializer (bias -> _init_bias)
    init = mx.initializer.Mixed(
        [".*special_weight", ".*"],
        [mx.initializer.Constant(7), mx.initializer.Zero()])
    w = mx.nd.zeros((3,))
    init("fc_special_weight", w)
    assert (w.asnumpy() == 7).all()
    w2 = mx.nd.ones((3,))
    init("fc_weight", w2)
    assert (w2.asnumpy() == 0).all()


def test_load_initializer():
    params = {"arg:w": mx.nd.ones((2, 2)) * 5}
    init = mx.initializer.Load({"w": mx.nd.ones((2, 2)) * 5},
                               default_init=mx.initializer.Zero())
    w = mx.nd.zeros((2, 2))
    init("w", w)
    assert (w.asnumpy() == 5).all()
    other = mx.nd.ones((3,))
    init("other", other)
    assert (other.asnumpy() == 0).all()


# ----------------------------------------------------------------- metrics
def test_accuracy_metric():
    m = mx.metric.Accuracy()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk_metric():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.7, 0.2, 0.1]])
    label = mx.nd.array([2, 1])
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6  # both in top-2


def test_mse_mae_rmse():
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([0.0, 4.0])
    mse = mx.metric.MSE()
    mse.update([label], [pred])
    assert abs(mse.get()[1] - (1 + 4) / 2) < 1e-6
    mae = mx.metric.MAE()
    mae.update([label], [pred])
    assert abs(mae.get()[1] - 1.5) < 1e-6


def test_f1_crossentropy_perplexity():
    pred = mx.nd.array([[0.9, 0.1], [0.3, 0.7], [0.8, 0.2]])
    label = mx.nd.array([0, 1, 1])
    f1 = mx.metric.F1()
    f1.update([label], [pred])
    assert 0 < f1.get()[1] <= 1
    ce = mx.metric.CrossEntropy()
    ce.update([label], [pred])
    expect = -(np.log(0.9) + np.log(0.7) + np.log(0.2)) / 3
    assert abs(ce.get()[1] - expect) < 1e-4
    pp = mx.metric.Perplexity(ignore_label=None)
    pp.update([label], [pred])
    assert pp.get()[1] > 1


def test_custom_and_composite_metric():
    def feval(label, pred):
        return float(np.sum(label))
    m = mx.metric.CustomMetric(feval, name="mysum")
    m.update([mx.nd.array([1, 2, 3])], [mx.nd.array([0, 0, 0])])
    assert m.get()[1] == 6.0
    comp = mx.metric.create(["acc", "mse"])
    assert isinstance(comp, mx.metric.CompositeEvalMetric)
    names, _ = comp.get()
    assert len(names) == 2


def test_np_metric_wrapper():
    @mx.metric.np
    def custom_error(label, pred):
        return 0.5
    # decorator-less usage
    m = mx.metric.np(lambda l, p: 1.0, name="one")
    m.update([mx.nd.array([0])], [mx.nd.array([0])])
    assert m.get()[1] == 1.0


def test_metric_setter_discards_pending_device_batches():
    """ADVICE r5: poking sum_metric/num_inst must DISCARD queued
    device-side accumulations, not flush them into both accumulators
    before overwriting only one (the old half-applied state)."""
    m = mx.metric.Accuracy()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])            # device path: queues pending
    assert m._pending, "expected a queued device batch"
    m.sum_metric = 0
    # the queued batch is gone entirely: num_inst did NOT absorb it
    assert m.num_inst == 0
    assert m.sum_metric == 0
    # same discard through the num_inst setter
    m.update([label], [pred])
    assert m._pending
    m.num_inst = 0
    assert m.sum_metric == 0 and m.num_inst == 0
    # metric remains fully usable afterwards
    m.update([label], [pred])
    assert m.get()[1] == 1.0


# ------------------------------------------------------------ attr scoping
def test_attr_scope():
    with mx.AttrScope(group="4", data="great"):
        data = mx.sym.var("data", attr={"dtype": "data", "group": "1"})
        gdata = mx.sym.var("data2")
    assert gdata.attr("group") == "4"
    assert data.attr("group") == "1"


def test_attr_scope_nesting():
    with mx.AttrScope(x="1"):
        with mx.AttrScope(y="2"):
            v = mx.sym.var("v")
        v2 = mx.sym.var("v2")
    assert v.attr("x") == "1" and v.attr("y") == "2"
    assert v2.attr("x") == "1" and v2.attr("y") is None


def test_ctx_group_attr():
    with mx.AttrScope(ctx_group="dev1"):
        fc = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2,
                                   name="fc")
    assert fc.attr("ctx_group") == "dev1"
    # attr survives JSON round trip
    js = mx.sym.load_json(fc.tojson())
    assert js.attr_dict()["fc"]["ctx_group"] == "dev1"


def test_name_manager():
    with mx.NameManager():
        s1 = mx.sym.FullyConnected(mx.sym.var("d"), num_hidden=1)
        s2 = mx.sym.FullyConnected(mx.sym.var("d"), num_hidden=1)
    assert s1.name != s2.name
    with mx.Prefix("pre_"):
        s3 = mx.sym.FullyConnected(mx.sym.var("d"), num_hidden=1)
    assert s3.name.startswith("pre_")


def test_device_metric_paths_match_host():
    """Every metric with a device-side accumulate branch must agree
    exactly with the host-numpy branch on identical data (NDArray
    inputs take the device path; raw numpy takes the host path)."""
    rs = np.random.RandomState(12)
    prob = rs.rand(16, 5).astype(np.float32)
    prob /= prob.sum(axis=1, keepdims=True)
    lab = rs.randint(0, 5, (16,)).astype(np.float32)
    reg_pred = rs.randn(16, 1).astype(np.float32)
    reg_lab = rs.randn(16).astype(np.float32)
    cases = [
        (lambda: mx.metric.Accuracy(), lab, prob),
        (lambda: mx.metric.TopKAccuracy(top_k=3), lab, prob),
        # (N,1)-shaped labels (the softmax-label convention): must not
        # broadcast cross-sample, and top-k accuracy stays <= 1
        (lambda: mx.metric.TopKAccuracy(top_k=3), lab[:, None], prob),
        (lambda: mx.metric.CrossEntropy(), lab, prob),
        (lambda: mx.metric.Perplexity(ignore_label=None), lab, prob),
        (lambda: mx.metric.Perplexity(ignore_label=0), lab, prob),
        (lambda: mx.metric.MSE(), reg_lab, reg_pred),
        (lambda: mx.metric.MAE(), reg_lab, reg_pred),
        (lambda: mx.metric.RMSE(), reg_lab, reg_pred),
    ]
    for make, l, p in cases:
        dev, host = make(), make()
        dev.update([mx.nd.array(l)], [mx.nd.array(p)])
        host.update([l.copy()], [p.copy()])
        name, dv = dev.get()
        _, hv = host.get()
        np.testing.assert_allclose(dv, hv, rtol=1e-5, atol=1e-6,
                                   err_msg=name)
        if "accuracy" in name:
            assert 0.0 <= dv <= 1.0, (name, dv)


def test_perplexity_multi_batch_unbiased():
    """ADVICE r2 (medium): get() must be exp(total_nll/total_count), not
    the arithmetic mean of per-batch perplexities (biased high)."""
    import math
    m = mx.metric.Perplexity(ignore_label=None)
    rs = np.random.RandomState(7)
    total_nll, total_n = 0.0, 0
    for _ in range(3):
        lab = rs.randint(0, 4, size=(5,)).astype(np.float32)
        prob = rs.rand(5, 4).astype(np.float32)
        prob /= prob.sum(axis=1, keepdims=True)
        m.update([mx.nd.array(lab)], [mx.nd.array(prob)])
        total_nll -= np.log(np.maximum(
            prob[np.arange(5), lab.astype(int)], 1e-10)).sum()
        total_n += 5
    _, val = m.get()
    np.testing.assert_allclose(val, math.exp(total_nll / total_n),
                               rtol=1e-5)
