"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the driver's multi-chip dry-run environment: sharding/collective
tests exercise real SPMD partitioning over 8 XLA CPU devices (SURVEY.md §4:
"distributed tests = N local processes" -> here N virtual devices).
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
