"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the driver's multi-chip dry-run environment: sharding/collective
tests exercise real SPMD partitioning over 8 XLA CPU devices (SURVEY.md §4:
"distributed tests = N local processes" -> here N virtual devices).
"""
import os
import tempfile

# Tests that deliberately crash executors/fit would otherwise drop
# flight-recorder crash reports into the working tree; tests asserting
# on dumps point the recorder at their own tmp_path via configure().
os.environ.setdefault(
    "MXNET_CRASH_DIR",
    os.path.join(tempfile.gettempdir(), f"mxnet_crash_{os.getpid()}"))

# Bind-time graph validation in warn mode across the whole suite: every
# executor the tier-1 tests bind runs the static-analysis passes for
# free (findings log as warnings, never raise). Tests that assert on
# validation behavior set the env/kwargs themselves.
os.environ.setdefault("MXNET_GRAPH_VALIDATE", "warn")

# Force, don't setdefault: the outer environment may carry JAX_PLATFORMS=tpu
# (or another accelerator), and the suite's numerics are written for f32 CPU
# execution on the virtual 8-device mesh.
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# A site hook may have already registered an accelerator plugin and pinned
# jax_platforms via jax.config.update(), which takes precedence over the
# env var — override the config itself too.
import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", (
    "test suite must run on the virtual CPU mesh, got "
    f"{jax.devices()[0].platform}")
assert jax.device_count() >= 8, "expected 8 virtual CPU devices"

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import random
    random.seed(0)          # augmenters draw from stdlib random
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    # process-wide program cache: cleared per test so compile/hit/miss
    # counter assertions stay deterministic regardless of test order
    # (tests exercising cross-bind reuse re-populate it themselves)
    mx.program_cache.clear()
