"""Kernel tier: variant selection, one-shot autotune, numerics gates.

Everything runs on the CPU test mesh: Pallas executes in interpret mode
(rtc.py's gate), so parity is checkable everywhere, and the autotune
path is driven by monkeypatching the backend probe + timer — the
measured branch itself is exercised without TPU hardware.
"""
import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kernel_tier
from mxnet_tpu.ops.registry import get_op
from mxnet_tpu.telemetry import metrics


@pytest.fixture(autouse=True)
def _clean_tier(monkeypatch):
    monkeypatch.delenv("MXNET_KERNEL_TIER", raising=False)
    monkeypatch.delenv("MXNET_AUTOTUNE_CACHE_DIR", raising=False)
    kernel_tier.clear()
    yield
    kernel_tier.clear()


def _softmax_site():
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({})
    shapes = [(8, 10), (8,)]
    dtypes = ["float32", "float32"]
    return sm, attrs, shapes, dtypes


# ------------------------------------------------------------- selection
def test_mode_parsing(monkeypatch):
    assert kernel_tier.mode() == "auto"
    monkeypatch.setenv("MXNET_KERNEL_TIER", "xla")
    assert kernel_tier.mode() == "xla"
    monkeypatch.setenv("MXNET_KERNEL_TIER", "PALLAS")
    assert kernel_tier.mode() == "pallas"
    monkeypatch.setenv("MXNET_KERNEL_TIER", "nonsense")
    assert kernel_tier.mode() == "auto"


def test_forced_xla(monkeypatch):
    monkeypatch.setenv("MXNET_KERNEL_TIER", "xla")
    sm, attrs, shapes, dtypes = _softmax_site()
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"


def test_forced_pallas(monkeypatch):
    monkeypatch.setenv("MXNET_KERNEL_TIER", "pallas")
    sm, attrs, shapes, dtypes = _softmax_site()
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    # ineligible shape (3-d data) falls back to xla even when forced
    assert kernel_tier.resolve(sm, attrs, [(2, 3, 4), (2, 3)],
                               ["float32", "float32"], True) == "xla"


def test_auto_on_cpu_is_xla():
    """The acceptance contract: auto off-TPU always resolves XLA, no
    autotune ever runs."""
    sm, attrs, shapes, dtypes = _softmax_site()
    before = kernel_tier.cache_info()["decisions"]
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"
    assert kernel_tier.cache_info()["decisions"] == before


def test_op_without_variants_passthrough():
    fc = get_op("FullyConnected")
    assert kernel_tier.resolve(fc, {"num_hidden": 4}, [(2, 8)],
                               ["float32"], True) == "xla"


# ------------------------------------------------------------- autotune
def _fake_tpu(monkeypatch, pallas_ms, xla_ms):
    """Drive the auto path without hardware: backend reads 'tpu', the
    timer replays scripted medians (xla first, then pallas — autotune's
    call order)."""
    times = iter([xla_ms / 1e3, pallas_ms / 1e3])
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times))


def test_auto_autotune_picks_measured_winner(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    dec = kernel_tier.decisions()[-1]
    assert dec["variant"] == "pallas" and dec["source"] == "autotune"
    assert "faster" in dec["reason"]


def test_auto_never_picks_slower_pallas(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=3.0, xla_ms=1.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"
    dec = kernel_tier.decisions()[-1]
    assert dec["variant"] == "xla" and "slower" in dec["reason"]
    # the audit log invariant: nothing chosen that measured slower
    for d in kernel_tier.decisions():
        if d.get("variant") == "pallas" and "pallas_ms" in d:
            assert d["pallas_ms"] < d["xla_ms"]


def test_numerics_gate_failure_forces_xla(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=0.1, xla_ms=9.9)
    monkeypatch.setattr(kernel_tier, "numerics_gate",
                        lambda *a, **k: (False, 1.0))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"
    assert "numerics" in kernel_tier.decisions()[-1]["reason"]


def test_autotune_cache_hit_accounting(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    runs = metrics.counter("kernel_tier.autotune.runs")
    hits = metrics.counter("kernel_tier.cache.hit")
    r0, h0 = runs.value, hits.value
    kernel_tier.resolve(sm, attrs, shapes, dtypes, True)
    assert runs.value == r0 + 1
    # second resolve at the same key: cached winner, no re-timing
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    assert runs.value == r0 + 1
    assert hits.value == h0 + 1
    # a different shape is a different key -> fresh autotune
    times = iter([2.0e-3, 1.0e-3])
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times))
    kernel_tier.resolve(sm, attrs, [(16, 10), (16,)], dtypes, True)
    assert runs.value == r0 + 2


def test_autotune_persistence_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE_DIR", str(tmp_path))
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    path = tmp_path / "kernel_tier.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert any(v["variant"] == "pallas" for v in doc.values())
    # a fresh process (simulated by clear()) reuses the persisted winner
    # without re-running the autotune
    kernel_tier.clear()
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(
        kernel_tier, "_time_variant",
        lambda *a, **k: pytest.fail("persisted winner re-timed"))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    assert kernel_tier.decisions()[-1]["source"] == "persisted"


def test_uncacheable_attrs_fall_back(monkeypatch):
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    sm, attrs, shapes, dtypes = _softmax_site()
    attrs = dict(attrs, bogus=np.arange(3))     # array attr: RC401-unsafe
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"


# ------------------------------------------------- numerics parity gates
_DTYPE_CASES = [("float32", None), ("bfloat16", None)]


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_softmax_ce(dtype, tol):
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({})
    ok, err = kernel_tier.numerics_gate(
        sm, attrs, [(16, 12), (16,)], [dtype, "float32"], tol=tol)
    assert ok, f"softmax-CE parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_fused_conv_bn_relu(dtype, tol):
    cbr = get_op("FusedConvBNReLU")
    attrs = cbr.normalize_attrs(dict(kernel=(3, 3), num_filter=8,
                                     pad=(1, 1), fix_gamma=False))
    shapes = [(2, 4, 8, 8), (8, 4, 3, 3), (8,), (8,), (8,), (8,)]
    dtypes = [dtype, dtype, "float32", "float32", "float32", "float32"]
    ok, err = kernel_tier.numerics_gate(cbr, attrs, shapes, dtypes,
                                        is_train=True, tol=tol)
    assert ok, f"conv+BN+ReLU parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_sgd_mom_update(dtype, tol):
    op = get_op("sgd_mom_update")
    attrs = op.normalize_attrs(dict(lr=0.05, momentum=0.9, wd=1e-4,
                                    rescale_grad=0.5, clip_gradient=2.0))
    ok, err = kernel_tier.numerics_gate(
        op, attrs, [(50, 33)] * 3, [dtype] * 3, is_train=False, tol=tol)
    assert ok, f"sgd_mom parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_adam_update(dtype, tol):
    op = get_op("adam_update")
    attrs = op.normalize_attrs(dict(lr=0.01, wd=1e-4))
    ok, err = kernel_tier.numerics_gate(
        op, attrs, [(40, 16)] * 4, [dtype] * 4, is_train=False, tol=tol)
    assert ok, f"adam parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_layernorm(dtype, tol):
    ln = get_op("LayerNorm")
    attrs = ln.normalize_attrs({})
    ok, err = kernel_tier.numerics_gate(
        ln, attrs, [(16, 96), (96,), (96,)],
        [dtype, "float32", "float32"], tol=tol)
    assert ok, f"LayerNorm parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_bias_gelu(dtype, tol):
    bg = get_op("FusedBiasGeLU")
    ok, err = kernel_tier.numerics_gate(
        bg, {}, [(16, 64), (64,)], [dtype, dtype], tol=tol)
    assert ok, f"bias+GeLU parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_embedding(dtype, tol):
    emb = get_op("Embedding")
    attrs = emb.normalize_attrs({"input_dim": 50, "output_dim": 64,
                                 "scale": 1.5})
    rng = np.random.RandomState(0)
    ids = jnp.asarray((rng.rand(24) * 50).astype("f"))
    w = jnp.asarray(rng.randn(50, 64).astype("f")).astype(dtype)
    ok, err = kernel_tier.numerics_gate(
        emb, attrs, [(24,), (50, 64)], ["float32", dtype], tol=tol,
        inputs=[ids, w])
    assert ok, f"embedding parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_attention(dtype, tol):
    att = get_op("attention")
    attrs = att.normalize_attrs({"causal": True})
    ok, err = kernel_tier.numerics_gate(
        att, attrs, [(2, 2, 128, 32)] * 3, [dtype] * 3, tol=tol)
    assert ok, f"attention parity failed at {dtype}: {err}"


def test_layernorm_hand_backward_gradients():
    """The fused LayerNorm's HAND backward kernels (dx row pass +
    dgamma/dbeta accumulation) match the XLA composition's gradients
    for every differentiable input."""
    from mxnet_tpu.ops.pallas_kernels import fused_layernorm
    ln = get_op("LayerNorm")
    attrs = ln.normalize_attrs({})
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(24, 48).astype("f"))
    g = jnp.asarray(rng.rand(48).astype("f") + 0.5)
    b = jnp.asarray(rng.randn(48).astype("f"))

    def loss_pl(x, g, b):
        return (fused_layernorm(x, g, b)[0] ** 2).sum()

    def loss_xla(x, g, b):
        return (ln.forward(attrs, [x, g, b], [], True,
                           None)[0][0] ** 2).sum()

    gp = jax.grad(loss_pl, argnums=(0, 1, 2))(x, g, b)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(x, g, b)
    for a, r, nm in zip(gp, gx, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"LayerNorm {nm}")


def test_bias_gelu_hand_backward_gradients():
    from mxnet_tpu.ops.pallas_kernels import (fused_bias_gelu,
                                              _bias_gelu_xla)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 40).astype("f"))
    b = jnp.asarray(rng.randn(40).astype("f"))
    gp = jax.grad(lambda x, b: (fused_bias_gelu(x, b) ** 2).sum(),
                  argnums=(0, 1))(x, b)
    gx = jax.grad(lambda x, b: (_bias_gelu_xla({}, x, b) ** 2).sum(),
                  argnums=(0, 1))(x, b)
    for a, r, nm in zip(gp, gx, ("dx", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"bias+GeLU {nm}")


def test_embedding_scatter_add_backward():
    """The fused embedding's scatter-add backward matches jnp.take's
    gradient — including repeated ids (the accumulate case)."""
    from mxnet_tpu.ops.pallas_kernels import fused_embedding
    rng = np.random.RandomState(2)
    ids = jnp.asarray(np.array([3, 1, 3, 3, 0, 1], "f"))  # repeats
    w = jnp.asarray(rng.randn(8, 32).astype("f"))
    gp = jax.grad(lambda w: (fused_embedding(ids, w, 2.0) ** 2).sum())(w)
    gx = jax.grad(lambda w: ((jnp.take(w, ids.astype(jnp.int32),
                                       axis=0) * 2.0) ** 2).sum())(w)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               rtol=1e-5, atol=1e-6)


def test_attention_grad_parity():
    """The attention OpDef's pallas (flash) variant differentiates to
    the same gradients as the XLA composition (flash-recompute VJP)."""
    att = get_op("attention")
    attrs = att.normalize_attrs({"causal": True})
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 128, 16).astype("f"))
               for _ in range(3))

    def loss(fn):
        return lambda q: (fn(attrs, [q, k, v], [], True,
                             None)[0][0] ** 2).sum()

    gx = jax.grad(loss(att.forward))(q)
    gp = jax.grad(loss(att.variant_fn("pallas")))(q)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               rtol=1e-3, atol=1e-4)


_NEW_KERNEL_SITES = [
    ("LayerNorm", {}, [(16, 96), (96,), (96,)],
     ["float32", "float32", "float32"]),
    ("FusedBiasGeLU", {}, [(16, 64), (64,)], ["float32", "float32"]),
    ("Embedding", {"input_dim": 50, "output_dim": 128},
     [(24,), (50, 128)], ["float32", "float32"]),
    ("attention", {}, [(2, 2, 128, 32)] * 3, ["float32"] * 3),
]


@pytest.mark.parametrize("opname,raw_attrs,shapes,dtypes",
                         _NEW_KERNEL_SITES,
                         ids=[s[0] for s in _NEW_KERNEL_SITES])
def test_new_kernels_never_selected_when_slower(opname, raw_attrs,
                                                shapes, dtypes,
                                                monkeypatch):
    """Each memory-bound-sweep kernel rides the one-shot scripted-timer
    autotune: a slower measurement can never select it, a faster one
    does."""
    op = get_op(opname)
    attrs = op.normalize_attrs(raw_attrs)
    _fake_tpu(monkeypatch, pallas_ms=3.0, xla_ms=1.0)
    assert kernel_tier.resolve(op, attrs, shapes, dtypes,
                               True) == "xla"
    assert "slower" in kernel_tier.decisions()[-1]["reason"]
    kernel_tier.clear()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    assert kernel_tier.resolve(op, attrs, shapes, dtypes,
                               True) == "pallas"


# ----------------------------------------- remat-policy autotune keying
def test_remat_policy_keys_autotune(monkeypatch):
    """Flipping MXNET_REMAT_POLICY never reuses a stale selection: the
    policy token rides the autotune key (in-memory AND persisted), so
    each policy gets its own measurement."""
    from mxnet_tpu.telemetry import metrics as _metrics
    sm, attrs, shapes, dtypes = _softmax_site()
    monkeypatch.setenv("MXNET_REMAT_POLICY", "none")
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    runs = metrics.counter("kernel_tier.autotune.runs")
    r0 = runs.value
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "pallas"
    assert runs.value == r0 + 1
    # same site under a different policy: a FRESH autotune, and this
    # one measures pallas slower — the none-policy winner must not leak
    monkeypatch.setenv("MXNET_REMAT_POLICY", "all")
    times = iter([1.0e-3, 3.0e-3])             # xla 1ms, pallas 3ms
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "xla"
    assert runs.value == r0 + 2
    # and each policy's winner stays cached independently
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "xla"
    monkeypatch.setenv("MXNET_REMAT_POLICY", "none")
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "pallas"
    assert runs.value == r0 + 2


def test_remat_policy_keys_persisted_cache(tmp_path, monkeypatch):
    """The persisted kernel_tier.json distinguishes policies too: a
    fresh process under a different policy re-tunes instead of reusing
    the other policy's winner."""
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_REMAT_POLICY", "none")
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "pallas"
    kernel_tier.clear()                        # "fresh process"
    monkeypatch.setenv("MXNET_REMAT_POLICY", "all")
    times = iter([1.0e-3, 3.0e-3])
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "xla"
    assert kernel_tier.decisions()[-1]["source"] == "autotune"
    # while the none-policy entry is still served persisted
    kernel_tier.clear()
    monkeypatch.setenv("MXNET_REMAT_POLICY", "none")
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(
        kernel_tier, "_time_variant",
        lambda *a, **k: pytest.fail("persisted winner re-timed"))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes,
                               True) == "pallas"
    assert kernel_tier.decisions()[-1]["source"] == "persisted"


def test_parity_custom_vjp_gradients():
    """The Pallas variants' custom VJPs match the XLA compositions'
    gradients (softmax-CE uses its hand backward kernel; conv+BN+ReLU
    recomputes through XLA)."""
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({"grad_scale": 2.0,
                                "normalization": "batch"})
    rng = np.random.RandomState(0)
    d = jnp.asarray(rng.randn(8, 10).astype("f"))
    lab = jnp.asarray((rng.rand(8) * 10).astype("f"))

    def loss(fn):
        return lambda dd: fn(attrs, [dd, lab], [], True, None)[0][0].sum()

    gx = jax.grad(loss(sm.forward))(d)
    gp = jax.grad(loss(sm.variant_fn("pallas")))(d)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gp),
                               rtol=1e-5, atol=1e-6)


# -------------------------------------------- end-to-end tier regression
def _fit_params(tier, monkeypatch):
    if tier is None:
        monkeypatch.delenv("MXNET_KERNEL_TIER", raising=False)
    else:
        monkeypatch.setenv("MXNET_KERNEL_TIER", tier)
    kernel_tier.clear()
    mx.random.seed(7)
    rng = np.random.RandomState(1)
    X = rng.rand(32, 8).astype(np.float32)
    Y = (rng.rand(32) * 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Uniform(0.1),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_tier_xla_bit_exact_with_default(monkeypatch):
    """MXNET_KERNEL_TIER=xla reproduces the pre-tier (unset) results
    bit for bit, and auto on CPU is identical to both — autotune can
    never degrade correctness off-TPU."""
    base = _fit_params(None, monkeypatch)
    forced = _fit_params("xla", monkeypatch)
    auto = _fit_params("auto", monkeypatch)
    for k in base:
        assert np.array_equal(base[k], forced[k]), k
        assert np.array_equal(base[k], auto[k]), k


def test_forced_pallas_trains_close(monkeypatch):
    """Forced-pallas training (interpret mode on CPU) stays numerically
    close to the XLA run — the variants' custom VJPs are sound through
    a real fit loop."""
    ref = _fit_params("xla", monkeypatch)
    pal = _fit_params("pallas", monkeypatch)
    for k in ref:
        np.testing.assert_allclose(ref[k], pal[k], rtol=2e-3, atol=2e-4)
