"""Kernel tier: variant selection, one-shot autotune, numerics gates.

Everything runs on the CPU test mesh: Pallas executes in interpret mode
(rtc.py's gate), so parity is checkable everywhere, and the autotune
path is driven by monkeypatching the backend probe + timer — the
measured branch itself is exercised without TPU hardware.
"""
import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kernel_tier
from mxnet_tpu.ops.registry import get_op
from mxnet_tpu.telemetry import metrics


@pytest.fixture(autouse=True)
def _clean_tier(monkeypatch):
    monkeypatch.delenv("MXNET_KERNEL_TIER", raising=False)
    monkeypatch.delenv("MXNET_AUTOTUNE_CACHE_DIR", raising=False)
    kernel_tier.clear()
    yield
    kernel_tier.clear()


def _softmax_site():
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({})
    shapes = [(8, 10), (8,)]
    dtypes = ["float32", "float32"]
    return sm, attrs, shapes, dtypes


# ------------------------------------------------------------- selection
def test_mode_parsing(monkeypatch):
    assert kernel_tier.mode() == "auto"
    monkeypatch.setenv("MXNET_KERNEL_TIER", "xla")
    assert kernel_tier.mode() == "xla"
    monkeypatch.setenv("MXNET_KERNEL_TIER", "PALLAS")
    assert kernel_tier.mode() == "pallas"
    monkeypatch.setenv("MXNET_KERNEL_TIER", "nonsense")
    assert kernel_tier.mode() == "auto"


def test_forced_xla(monkeypatch):
    monkeypatch.setenv("MXNET_KERNEL_TIER", "xla")
    sm, attrs, shapes, dtypes = _softmax_site()
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"


def test_forced_pallas(monkeypatch):
    monkeypatch.setenv("MXNET_KERNEL_TIER", "pallas")
    sm, attrs, shapes, dtypes = _softmax_site()
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    # ineligible shape (3-d data) falls back to xla even when forced
    assert kernel_tier.resolve(sm, attrs, [(2, 3, 4), (2, 3)],
                               ["float32", "float32"], True) == "xla"


def test_auto_on_cpu_is_xla():
    """The acceptance contract: auto off-TPU always resolves XLA, no
    autotune ever runs."""
    sm, attrs, shapes, dtypes = _softmax_site()
    before = kernel_tier.cache_info()["decisions"]
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"
    assert kernel_tier.cache_info()["decisions"] == before


def test_op_without_variants_passthrough():
    fc = get_op("FullyConnected")
    assert kernel_tier.resolve(fc, {"num_hidden": 4}, [(2, 8)],
                               ["float32"], True) == "xla"


# ------------------------------------------------------------- autotune
def _fake_tpu(monkeypatch, pallas_ms, xla_ms):
    """Drive the auto path without hardware: backend reads 'tpu', the
    timer replays scripted medians (xla first, then pallas — autotune's
    call order)."""
    times = iter([xla_ms / 1e3, pallas_ms / 1e3])
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times))


def test_auto_autotune_picks_measured_winner(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    dec = kernel_tier.decisions()[-1]
    assert dec["variant"] == "pallas" and dec["source"] == "autotune"
    assert "faster" in dec["reason"]


def test_auto_never_picks_slower_pallas(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=3.0, xla_ms=1.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"
    dec = kernel_tier.decisions()[-1]
    assert dec["variant"] == "xla" and "slower" in dec["reason"]
    # the audit log invariant: nothing chosen that measured slower
    for d in kernel_tier.decisions():
        if d.get("variant") == "pallas" and "pallas_ms" in d:
            assert d["pallas_ms"] < d["xla_ms"]


def test_numerics_gate_failure_forces_xla(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=0.1, xla_ms=9.9)
    monkeypatch.setattr(kernel_tier, "numerics_gate",
                        lambda *a, **k: (False, 1.0))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"
    assert "numerics" in kernel_tier.decisions()[-1]["reason"]


def test_autotune_cache_hit_accounting(monkeypatch):
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    runs = metrics.counter("kernel_tier.autotune.runs")
    hits = metrics.counter("kernel_tier.cache.hit")
    r0, h0 = runs.value, hits.value
    kernel_tier.resolve(sm, attrs, shapes, dtypes, True)
    assert runs.value == r0 + 1
    # second resolve at the same key: cached winner, no re-timing
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    assert runs.value == r0 + 1
    assert hits.value == h0 + 1
    # a different shape is a different key -> fresh autotune
    times = iter([2.0e-3, 1.0e-3])
    monkeypatch.setattr(kernel_tier, "_time_variant",
                        lambda run, r, x, reps: next(times))
    kernel_tier.resolve(sm, attrs, [(16, 10), (16,)], dtypes, True)
    assert runs.value == r0 + 2


def test_autotune_persistence_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE_DIR", str(tmp_path))
    sm, attrs, shapes, dtypes = _softmax_site()
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    path = tmp_path / "kernel_tier.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert any(v["variant"] == "pallas" for v in doc.values())
    # a fresh process (simulated by clear()) reuses the persisted winner
    # without re-running the autotune
    kernel_tier.clear()
    monkeypatch.setattr(kernel_tier, "_backend", lambda: "tpu")
    monkeypatch.setattr(kernel_tier, "_device_kind", lambda: "TPU test")
    monkeypatch.setattr(
        kernel_tier, "_time_variant",
        lambda *a, **k: pytest.fail("persisted winner re-timed"))
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "pallas"
    assert kernel_tier.decisions()[-1]["source"] == "persisted"


def test_uncacheable_attrs_fall_back(monkeypatch):
    _fake_tpu(monkeypatch, pallas_ms=1.0, xla_ms=2.0)
    sm, attrs, shapes, dtypes = _softmax_site()
    attrs = dict(attrs, bogus=np.arange(3))     # array attr: RC401-unsafe
    assert kernel_tier.resolve(sm, attrs, shapes, dtypes, True) == "xla"


# ------------------------------------------------- numerics parity gates
_DTYPE_CASES = [("float32", None), ("bfloat16", None)]


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_softmax_ce(dtype, tol):
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({})
    ok, err = kernel_tier.numerics_gate(
        sm, attrs, [(16, 12), (16,)], [dtype, "float32"], tol=tol)
    assert ok, f"softmax-CE parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_fused_conv_bn_relu(dtype, tol):
    cbr = get_op("FusedConvBNReLU")
    attrs = cbr.normalize_attrs(dict(kernel=(3, 3), num_filter=8,
                                     pad=(1, 1), fix_gamma=False))
    shapes = [(2, 4, 8, 8), (8, 4, 3, 3), (8,), (8,), (8,), (8,)]
    dtypes = [dtype, dtype, "float32", "float32", "float32", "float32"]
    ok, err = kernel_tier.numerics_gate(cbr, attrs, shapes, dtypes,
                                        is_train=True, tol=tol)
    assert ok, f"conv+BN+ReLU parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_sgd_mom_update(dtype, tol):
    op = get_op("sgd_mom_update")
    attrs = op.normalize_attrs(dict(lr=0.05, momentum=0.9, wd=1e-4,
                                    rescale_grad=0.5, clip_gradient=2.0))
    ok, err = kernel_tier.numerics_gate(
        op, attrs, [(50, 33)] * 3, [dtype] * 3, is_train=False, tol=tol)
    assert ok, f"sgd_mom parity failed at {dtype}: {err}"


@pytest.mark.parametrize("dtype,tol", _DTYPE_CASES)
def test_parity_adam_update(dtype, tol):
    op = get_op("adam_update")
    attrs = op.normalize_attrs(dict(lr=0.01, wd=1e-4))
    ok, err = kernel_tier.numerics_gate(
        op, attrs, [(40, 16)] * 4, [dtype] * 4, is_train=False, tol=tol)
    assert ok, f"adam parity failed at {dtype}: {err}"


def test_parity_custom_vjp_gradients():
    """The Pallas variants' custom VJPs match the XLA compositions'
    gradients (softmax-CE uses its hand backward kernel; conv+BN+ReLU
    recomputes through XLA)."""
    sm = get_op("SoftmaxOutput")
    attrs = sm.normalize_attrs({"grad_scale": 2.0,
                                "normalization": "batch"})
    rng = np.random.RandomState(0)
    d = jnp.asarray(rng.randn(8, 10).astype("f"))
    lab = jnp.asarray((rng.rand(8) * 10).astype("f"))

    def loss(fn):
        return lambda dd: fn(attrs, [dd, lab], [], True, None)[0][0].sum()

    gx = jax.grad(loss(sm.forward))(d)
    gp = jax.grad(loss(sm.variant_fn("pallas")))(d)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gp),
                               rtol=1e-5, atol=1e-6)


# -------------------------------------------- end-to-end tier regression
def _fit_params(tier, monkeypatch):
    if tier is None:
        monkeypatch.delenv("MXNET_KERNEL_TIER", raising=False)
    else:
        monkeypatch.setenv("MXNET_KERNEL_TIER", tier)
    kernel_tier.clear()
    mx.random.seed(7)
    rng = np.random.RandomState(1)
    X = rng.rand(32, 8).astype(np.float32)
    Y = (rng.rand(32) * 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    d = mx.sym.var("data")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, initializer=mx.initializer.Uniform(0.1),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_tier_xla_bit_exact_with_default(monkeypatch):
    """MXNET_KERNEL_TIER=xla reproduces the pre-tier (unset) results
    bit for bit, and auto on CPU is identical to both — autotune can
    never degrade correctness off-TPU."""
    base = _fit_params(None, monkeypatch)
    forced = _fit_params("xla", monkeypatch)
    auto = _fit_params("auto", monkeypatch)
    for k in base:
        assert np.array_equal(base[k], forced[k]), k
        assert np.array_equal(base[k], auto[k]), k


def test_forced_pallas_trains_close(monkeypatch):
    """Forced-pallas training (interpret mode on CPU) stays numerically
    close to the XLA run — the variants' custom VJPs are sound through
    a real fit loop."""
    ref = _fit_params("xla", monkeypatch)
    pal = _fit_params("pallas", monkeypatch)
    for k in ref:
        np.testing.assert_allclose(ref[k], pal[k], rtol=2e-3, atol=2e-4)
